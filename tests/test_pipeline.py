"""Pipeline-parallel schedule tests (virtual 8-device mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ddl_tpu.parallel.mesh import make_mesh
from ddl_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_spec,
    stack_stage_params,
)
from ddl_tpu.parallel.train import make_train_step

D = 16


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stages(rng, n):
    return [
        {
            "w": jnp.asarray(rng.standard_normal((D, D)) / 4, jnp.float32),
            "b": jnp.asarray(rng.standard_normal((D,)) / 4, jnp.float32),
        }
        for _ in range(n)
    ]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


def test_pipeline_matches_sequential(rng):
    """pp=4 pipelined output == applying the 4 stages in sequence."""
    stages = _stages(rng, 4)
    stacked = stack_stage_params(stages)
    mesh = make_mesh({"pp": 4, "dp": 2})
    x = jnp.asarray(rng.standard_normal((8, D)), jnp.float32)
    out = pipeline_apply(stacked, x, _stage_fn, mesh, n_microbatches=4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(stages, x)), atol=1e-5
    )


def test_pipeline_fallback_no_pp_axis(rng):
    stages = _stages(rng, 3)
    stacked = stack_stage_params(stages)
    mesh = make_mesh({"dp": 8})
    x = jnp.asarray(rng.standard_normal((4, D)), jnp.float32)
    out = pipeline_apply(stacked, x, _stage_fn, mesh, n_microbatches=2)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(stages, x)), atol=1e-5
    )


def test_pipeline_spec_prepends_pp():
    spec = pipeline_spec({"w": P("fsdp", "tp"), "b": P(None)})
    assert spec["w"] == P("pp", "fsdp", "tp")
    assert spec["b"] == P("pp", None)


def test_bubble_fraction():
    from ddl_tpu.parallel import bubble_fraction

    assert bubble_fraction(1, 4) == 0.0  # no pipe, no bubble
    assert bubble_fraction(4, 4) == 3 / 7
    assert bubble_fraction(4, 28) == 3 / 31  # deep microbatching amortizes
    # 1f1b interleaving divides the per-chunk ramp cost: the ISSUE 5
    # acceptance point — strictly below gpipe's 0.429 at pp=4/M=4.
    assert bubble_fraction(4, 4, schedule="1f1b") == 3 / 11
    assert bubble_fraction(4, 4, schedule="1f1b", n_chunks=4) == 3 / 19
    assert bubble_fraction(4, 4, schedule="1f1b") < bubble_fraction(4, 4)
    import pytest

    with pytest.raises(ValueError):
        bubble_fraction(0, 4)
    with pytest.raises(ValueError):
        bubble_fraction(4, 4, schedule="pipedream")
    with pytest.raises(ValueError):
        bubble_fraction(4, 4, schedule="gpipe", n_chunks=2)


class Test1F1BSchedule:
    """The interleaved (1f1b) schedule: chunk layout, forward AND grad
    equivalence with gpipe/sequential at identical total stages, and
    the microbatch-divisibility contract."""

    def _layers(self, rng, n):
        return [
            {
                "w": jnp.asarray(
                    rng.standard_normal((D, D)) / 4, jnp.float32
                ),
                "b": jnp.asarray(
                    rng.standard_normal((D,)) / 4, jnp.float32
                ),
            }
            for _ in range(n)
        ]

    @staticmethod
    def _layer_fn(layer, x):
        return jnp.tanh(x @ layer["w"] + layer["b"])

    def _stage_fn(self, stage, x):
        out, _ = jax.lax.scan(
            lambda c, lyr: (self._layer_fn(lyr, c), None), x, stage
        )
        return out

    def _sequential(self, layers, x):
        for layer in layers:
            x = self._layer_fn(layer, x)
        return x

    def test_chunk_layout(self, rng):
        """Device d chunk c holds global stage c*S+d (the Megatron
        virtual-pipeline assignment)."""
        from ddl_tpu.parallel.pipeline import stack_layer_stages

        layers = self._layers(rng, 8)
        st = stack_layer_stages(layers, 4, n_chunks=2)
        assert st["w"].shape == (4, 2, 1, D, D)
        for d in range(4):
            for c in range(2):
                np.testing.assert_array_equal(
                    np.asarray(st["w"][d, c, 0]),
                    np.asarray(layers[c * 4 + d]["w"]),
                )
        import pytest

        with pytest.raises(ValueError):
            stack_layer_stages(layers, 4, n_chunks=3)  # 8 % 12 != 0

    def test_1f1b_matches_sequential_and_gpipe(self, rng):
        from ddl_tpu.parallel.pipeline import stack_layer_stages

        layers = self._layers(rng, 8)
        x = jnp.asarray(rng.standard_normal((8, D)), jnp.float32)
        mesh = make_mesh({"pp": 4, "dp": 2})
        ref = np.asarray(self._sequential(layers, x))
        gp = pipeline_apply(
            stack_layer_stages(layers, 4), x, self._stage_fn, mesh, 4
        )
        f1 = pipeline_apply(
            stack_layer_stages(layers, 4, n_chunks=2), x,
            self._stage_fn, mesh, 4, schedule="1f1b", n_chunks=2,
        )
        np.testing.assert_allclose(np.asarray(gp), ref, atol=1e-5)
        np.testing.assert_allclose(np.asarray(f1), ref, atol=1e-5)
        # M = 8 (multiple of S) exercises the two-group packing.
        f2 = pipeline_apply(
            stack_layer_stages(layers, 4, n_chunks=2), x,
            self._stage_fn, mesh, 8, schedule="1f1b", n_chunks=2,
        )
        np.testing.assert_allclose(np.asarray(f2), ref, atol=1e-5)

    def test_1f1b_fallback_no_pp_axis(self, rng):
        from ddl_tpu.parallel.pipeline import stack_layer_stages

        layers = self._layers(rng, 8)
        x = jnp.asarray(rng.standard_normal((8, D)), jnp.float32)
        out = pipeline_apply(
            stack_layer_stages(layers, 4, n_chunks=2), x,
            self._stage_fn, make_mesh({"dp": 8}), 4,
            schedule="1f1b", n_chunks=2,
        )
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(self._sequential(layers, x)), atol=1e-5,
        )

    def test_1f1b_grads_match_gpipe(self, rng):
        """Loss AND per-layer grads identical between the schedules at
        the same (total stages, M) — only device placement and tick
        order differ (ISSUE 5 acceptance)."""
        from ddl_tpu.parallel.pipeline import stack_layer_stages

        layers = self._layers(rng, 8)
        x = jnp.asarray(rng.standard_normal((8, D)), jnp.float32)
        mesh = make_mesh({"pp": 4, "dp": 2})

        def loss(stacked, schedule, n_chunks):
            out = pipeline_apply(
                stacked, x, self._stage_fn, mesh, 4,
                schedule=schedule, n_chunks=n_chunks,
            )
            return jnp.sum(out**2)

        lg, gg = jax.value_and_grad(
            lambda p: loss(p, "gpipe", None)
        )(stack_layer_stages(layers, 4))
        lf, gf = jax.value_and_grad(
            lambda p: loss(p, "1f1b", 2)
        )(stack_layer_stages(layers, 4, n_chunks=2))
        np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)
        # Map both grad layouts back to the original layer order:
        # gpipe [s, i] = layer 2s+i; 1f1b [d, c, 0] = layer c*4+d.
        for li in range(8):
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    np.asarray(gg[k][li // 2, li % 2]),
                    np.asarray(gf[k][li % 4, li // 4, 0]),
                    atol=2e-5, err_msg=f"layer {li} {k}",
                )

    def test_1f1b_requires_divisible_microbatches(self, rng):
        import pytest

        from ddl_tpu.parallel.pipeline import stack_layer_stages

        layers = self._layers(rng, 8)
        x = jnp.asarray(rng.standard_normal((6, D)), jnp.float32)
        mesh = make_mesh({"pp": 4, "dp": 2})
        st = stack_layer_stages(layers, 4, n_chunks=2)
        with pytest.raises(ValueError, match="divisible by n_stages"):
            pipeline_apply(
                st, x, self._stage_fn, mesh, 6,
                schedule="1f1b", n_chunks=2,
            )
        # Params stacked without the expected chunk axis are rejected
        # up front (here: a 4-layer gpipe stack, whose (4, 1, D, D)
        # leaves cannot carry n_chunks=2).  NB a gpipe stack with
        # L/S == n_chunks is shape-indistinguishable from a chunked
        # stack — the layout contract is the caller's.
        with pytest.raises(ValueError, match="stack_layer_stages"):
            pipeline_apply(
                stack_layer_stages(layers[:4], 4),
                jnp.asarray(rng.standard_normal((8, D)), jnp.float32),
                self._stage_fn, mesh, 4, schedule="1f1b", n_chunks=2,
            )


class TestLlamaPipeline:
    """The FLAGSHIP model through the pipe (VERDICT r4 item 4): llama
    blocks stacked into stages, equivalence vs the plain forward, and a
    full sharded train step on a pp×dp mesh."""

    def _cfg(self, n_layers=4):
        from ddl_tpu.models.llama import LlamaConfig

        # fp32 + dense attention so pp-vs-plain comparisons are tight.
        return LlamaConfig(
            vocab=64, d_model=32, n_layers=n_layers, n_heads=4,
            n_kv_heads=2, d_ff=64, dtype=jnp.float32, attn_impl="dense",
        )

    def test_stage_params_layout(self, rng):
        from ddl_tpu.models import llama

        cfg = self._cfg(4)
        params = llama.init_params(cfg, jax.random.key(0))
        pp = llama.stage_params(params, 2)
        # (S, L/S, ...) leaves; stage 1 layer 0 is original layer 2.
        assert pp["stages"]["wq"].shape == (2, 2, 32, 32)
        np.testing.assert_array_equal(
            np.asarray(pp["stages"]["wq"][1, 0]),
            np.asarray(params["layers"][2]["wq"]),
        )
        import pytest

        with pytest.raises(ValueError):
            llama.stage_params(params, 3)  # 4 layers don't split in 3

    def test_forward_pp_matches_forward(self, rng):
        """Pipelined llama logits == plain llama logits for every stage
        count that divides the layers (pp=4 and pp=2 over the 8-device
        mesh), microbatched or not."""
        from ddl_tpu.models import llama

        cfg = self._cfg(4)
        params = llama.init_params(cfg, jax.random.key(0))
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab, (8, 16)), jnp.int32
        )
        ref = np.asarray(llama.forward(params, tokens, cfg))
        for S, dp, M in ((4, 2, 4), (2, 4, 2), (4, 2, 8)):
            mesh = make_mesh({"pp": S, "dp": dp})
            got = llama.forward_pp(
                llama.stage_params(params, S), tokens, cfg, mesh,
                n_microbatches=M,
            )
            np.testing.assert_allclose(
                np.asarray(got), ref, atol=2e-5, rtol=2e-5,
                err_msg=f"pp={S} dp={dp} M={M}",
            )

    def test_train_step_pp_llama(self, rng):
        """Full sharded train step (loss+grad+adamw) of the pipelined
        llama on a pp=4 × dp=2 mesh: loss starts near ln(vocab) and
        decreases — the reverse schedule works through jax.grad."""
        from ddl_tpu.models import llama

        cfg = self._cfg(4)
        mesh = make_mesh({"pp": 4, "dp": 2})
        flat_params = llama.init_params(cfg, jax.random.key(0))
        params = llama.stage_params(flat_params, 4)
        tokens = np.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab, (8, 16)),
            np.int32,
        )
        init_fn, step_fn = make_train_step(
            lambda p, b: llama.next_token_loss_pp(
                p, b, cfg, mesh, n_microbatches=4
            ),
            optax.adamw(1e-2), mesh, llama.pp_param_specs(cfg),
            batch_spec=P(("dp",)),
        )
        state = init_fn(params)
        losses = []
        for _ in range(8):
            state, loss = step_fn(state, tokens)
            losses.append(float(loss))
        # Step-1 loss must match the UNPIPELINED loss on identical
        # params — an invariant of the schedule, unlike the absolute
        # ln(vocab) proximity of the old assert, which floats with the
        # jax version's init-draw stream.
        ref = float(
            llama.next_token_loss(flat_params, jnp.asarray(tokens), cfg)
        )
        assert abs(losses[0] - ref) < 0.05, (losses[0], ref)
        assert losses[-1] < losses[0] - 0.3, losses

    def test_forward_pp_tp_resident_matches(self, rng):
        """pp × tp: stages run on LOCAL Megatron weight shards with
        explicit psums — logits must equal the plain forward exactly
        (the tp-resident path changes memory and collectives, not
        math)."""
        from ddl_tpu.models import llama

        cfg = self._cfg(4)
        params = llama.init_params(cfg, jax.random.key(0))
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab, (8, 16)), jnp.int32
        )
        ref = np.asarray(llama.forward(params, tokens, cfg))
        mesh = make_mesh({"pp": 2, "tp": 2, "dp": 2})
        got = llama.forward_pp(
            llama.stage_params(params, 2), tokens, cfg, mesh,
            n_microbatches=4,
        )
        np.testing.assert_allclose(
            np.asarray(got), ref, atol=2e-5, rtol=2e-5
        )

    def test_forward_pp_degenerate_pp1_with_tp(self, rng):
        """pp=1 with a tp axis present takes the sequential fallback on
        FULL weights (tp-resident stages need a real pp axis for their
        psums) — must run, not raise, and match the plain forward."""
        from ddl_tpu.models import llama

        cfg = self._cfg(4)
        params = llama.init_params(cfg, jax.random.key(0))
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab, (4, 16)), jnp.int32
        )
        mesh = make_mesh({"pp": 1, "tp": 2, "dp": 4})
        got = llama.forward_pp(
            llama.stage_params(params, 1), tokens, cfg, mesh,
            n_microbatches=2,
        )
        np.testing.assert_allclose(
            np.asarray(got),
            np.asarray(llama.forward(params, tokens, cfg)),
            atol=2e-5, rtol=2e-5,
        )

    def test_train_step_pp_tp_llama(self, rng):
        """Full sharded train step of the tp-resident pipelined llama on
        pp=2 × tp=2 × dp=2 — grads flow through the psums and the
        ppermute schedule together."""
        from ddl_tpu.models import llama

        cfg = self._cfg(4)
        mesh = make_mesh({"pp": 2, "tp": 2, "dp": 2})
        init_fn, step_fn = make_train_step(
            lambda p, b: llama.next_token_loss_pp(
                p, b, cfg, mesh, n_microbatches=4
            ),
            optax.adamw(1e-2), mesh, llama.pp_param_specs(cfg),
            batch_spec=P(("dp",)),
        )
        flat_params = llama.init_params(cfg, jax.random.key(0))
        state = init_fn(llama.stage_params(flat_params, 2))
        tokens = np.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab, (8, 16)),
            np.int32,
        )
        losses = []
        for _ in range(6):
            state, loss = step_fn(state, tokens)
            losses.append(float(loss))
        # Same-params unpipelined reference (see test_train_step_pp_llama).
        ref = float(
            llama.next_token_loss(flat_params, jnp.asarray(tokens), cfg)
        )
        assert abs(losses[0] - ref) < 0.05, (losses[0], ref)
        assert losses[-1] < losses[0] - 0.3, losses

    def test_remat_pp_matches(self, rng):
        """Per-layer remat inside a pipeline stage changes memory, not
        math — for EVERY named policy (none/full/selective/dots)."""
        from ddl_tpu.models import llama

        cfg = self._cfg(4)
        params = llama.init_params(cfg, jax.random.key(0))
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab, (4, 16)), jnp.int32
        )
        mesh = make_mesh({"pp": 4, "dp": 2})
        pp = llama.stage_params(params, 4)
        a = llama.forward_pp(pp, tokens, cfg, mesh, n_microbatches=4)
        for policy in (True, "full", "selective", "dots"):
            cfg_r = type(cfg)(**{**cfg.__dict__, "remat": policy})
            b = llama.forward_pp(
                pp, tokens, cfg_r, mesh, n_microbatches=4
            )
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6,
                err_msg=f"remat={policy}",
            )

    def test_forward_pp_1f1b_matches_forward(self, rng):
        """The interleaved 1f1b schedule on the FLAGSHIP model: logits
        equal the plain forward (8 layers, pp=4 x 2 chunks)."""
        from ddl_tpu.models import llama

        cfg = self._cfg(8)
        params = llama.init_params(cfg, jax.random.key(0))
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab, (8, 16)), jnp.int32
        )
        ref = np.asarray(llama.forward(params, tokens, cfg))
        mesh = make_mesh({"pp": 4, "dp": 2})
        got = llama.forward_pp(
            llama.stage_params(params, 4, n_chunks=2), tokens, cfg,
            mesh, n_microbatches=4, schedule="1f1b", n_chunks=2,
        )
        np.testing.assert_allclose(
            np.asarray(got), ref, atol=2e-5, rtol=2e-5
        )

    def test_train_step_1f1b_matches_gpipe(self, rng):
        """Loss/grad equivalence of the 1f1b schedule with gpipe on the
        flagship model (ISSUE 5 acceptance): identical step-1 loss and
        per-layer gradients from identical params at the same (total
        stages, M)."""
        from ddl_tpu.models import llama

        cfg = self._cfg(8)
        flat = llama.init_params(cfg, jax.random.key(0))
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab, (8, 16)),
            jnp.int32,
        )
        mesh = make_mesh({"pp": 4, "dp": 2})
        lg, gg = jax.value_and_grad(
            lambda p: llama.next_token_loss_pp(
                p, tokens, cfg, mesh, n_microbatches=4
            )
        )(llama.stage_params(flat, 4))
        lf, gf = jax.value_and_grad(
            lambda p: llama.next_token_loss_pp(
                p, tokens, cfg, mesh, n_microbatches=4,
                schedule="1f1b", n_chunks=2,
            )
        )(llama.stage_params(flat, 4, n_chunks=2))
        ref = float(llama.next_token_loss(flat, tokens, cfg))
        assert abs(float(lg) - ref) < 0.05
        np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)
        # Grad layouts map back to original layer order: gpipe [s, i]
        # = layer 2s+i; 1f1b [d, c, 0] = layer c*4+d.  Compare a
        # representative leaf per layer plus the shared non-staged
        # leaves.
        for li in range(8):
            np.testing.assert_allclose(
                np.asarray(gg["stages"]["wq"][li // 2, li % 2]),
                np.asarray(gf["stages"]["wq"][li % 4, li // 4, 0]),
                atol=2e-5, err_msg=f"layer {li}",
            )
        for leaf in ("embed", "lm_head", "final_norm"):
            np.testing.assert_allclose(
                np.asarray(gg[leaf]), np.asarray(gf[leaf]), atol=2e-5
            )


class TestMoePipeline:
    """MoE through the pipe: the activation pytree carries the router
    aux accumulator alongside the residual stream."""

    def _cfg(self, **kw):
        from ddl_tpu.models.moe import MoeConfig

        base = dict(
            vocab=64, d_model=32, n_layers=4, n_heads=4, n_kv_heads=2,
            d_ff=64, n_experts=4, dtype=jnp.float32, attn_impl="dense",
            capacity_factor=8.0,  # unbound capacity -> exact logits
        )
        base.update(kw)
        return MoeConfig(**base)

    def test_forward_pp_matches_forward(self, rng):
        """With capacity unbound, routing is per-token, so pipelined
        logits equal the plain forward exactly; the aux differs only by
        its granularity (mean of per-microbatch aux) and stays the same
        order of magnitude."""
        from ddl_tpu.models import moe

        cfg = self._cfg()
        params = moe.init_params(cfg, jax.random.key(0))
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab, (8, 16)), jnp.int32
        )
        ref_logits, ref_aux = moe.forward(params, tokens, cfg)
        mesh = make_mesh({"pp": 4, "dp": 2})
        got_logits, got_aux = moe.forward_pp(
            moe.stage_params(params, 4), tokens, cfg, mesh,
            n_microbatches=4,
        )
        np.testing.assert_allclose(
            np.asarray(got_logits), np.asarray(ref_logits),
            atol=2e-5, rtol=2e-5,
        )
        assert np.isfinite(float(got_aux)) and float(got_aux) > 0
        # Same load-balance pressure at different granularity.
        assert abs(float(got_aux) - float(ref_aux)) < 0.5 * float(ref_aux)

    def test_train_step_pp_moe(self, rng):
        """Full sharded train step of the pipelined MoE on pp=4 × dp=2 —
        grads flow through the routed experts, the aux accumulator, and
        the ppermute schedule."""
        from ddl_tpu.models import moe

        cfg = self._cfg(capacity_factor=2.0)
        mesh = make_mesh({"pp": 4, "dp": 2})
        init_fn, step_fn = make_train_step(
            lambda p, b: moe.next_token_loss_pp(
                p, b, cfg, mesh, n_microbatches=4
            ),
            optax.adamw(1e-2), mesh, moe.pp_param_specs(cfg),
            batch_spec=P(("dp",)),
        )
        state = init_fn(
            moe.stage_params(moe.init_params(cfg, jax.random.key(0)), 4)
        )
        tokens = np.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab, (8, 16)),
            np.int32,
        )
        losses = []
        for _ in range(8):
            state, loss = step_fn(state, tokens)
            losses.append(float(loss))
        assert abs(losses[0] - np.log(cfg.vocab)) < 1.0, losses[0]
        assert losses[-1] < losses[0] - 0.3, losses


class TestViTPipeline:
    """The image family through the pipe: same stage layout and schedule
    as llama (shared stack_layer_stages), non-causal attention."""

    def _cfg(self):
        from ddl_tpu.models.vit import ViTConfig

        return ViTConfig(
            image_size=16, patch_size=4, d_model=32, n_layers=4,
            n_heads=4, d_ff=64, n_classes=8, dtype=jnp.float32,
            attn_impl="dense",
        )

    def test_forward_pp_matches_forward(self, rng):
        from ddl_tpu.models import vit

        cfg = self._cfg()
        params = vit.init_params(cfg, jax.random.key(0))
        images = jnp.asarray(
            rng.random((8, 16 * 16 * 3)), jnp.float32
        )
        ref = np.asarray(vit.forward(params, images, cfg))
        mesh = make_mesh({"pp": 4, "dp": 2})
        got = vit.forward_pp(
            vit.stage_params(params, 4), images, cfg, mesh,
            n_microbatches=4,
        )
        np.testing.assert_allclose(
            np.asarray(got), ref, atol=2e-5, rtol=2e-5
        )

    def test_train_step_pp_vit(self, rng):
        from ddl_tpu.models import vit

        cfg = self._cfg()
        mesh = make_mesh({"pp": 4, "dp": 2})
        init_fn, step_fn = make_train_step(
            lambda p, b: vit.classification_loss_pp(
                p, b, cfg, mesh, n_microbatches=4
            ),
            optax.adam(1e-2), mesh, vit.pp_param_specs(cfg),
            batch_spec=P(("dp",)),
        )
        flat_params = vit.init_params(cfg, jax.random.key(0))
        state = init_fn(vit.stage_params(flat_params, 4))
        g = np.random.default_rng(0)
        pixels = g.random((8, 16 * 16 * 3)).astype(np.float32)
        labels = g.integers(0, 8, (8, 1)).astype(np.float32)
        losses = []
        for _ in range(8):
            state, loss = step_fn(state, (pixels, labels))
            losses.append(float(loss))
        # Same-params unpipelined reference (see test_train_step_pp_llama).
        ref = float(
            vit.classification_loss(flat_params, (pixels, labels), cfg)
        )
        assert abs(losses[0] - ref) < 0.05, (losses[0], ref)
        assert losses[-1] < losses[0] - 0.3, losses


def test_pipeline_gradients_train(rng):
    """A pipelined regression model trains end-to-end on a pp×dp mesh —
    grads flow backwards through the ppermute schedule."""
    mesh = make_mesh({"pp": 4, "dp": 2})
    stacked = stack_stage_params(_stages(rng, 4))
    x = jnp.asarray(rng.standard_normal((16, D)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((16, D)) * 0.1, jnp.float32)

    def loss_fn(params, batch):
        xb, yb = batch
        pred = pipeline_apply(params, xb, _stage_fn, mesh, n_microbatches=4)
        return jnp.mean((pred - yb) ** 2)

    init_fn, step_fn = make_train_step(
        loss_fn, optax.adam(1e-2), mesh,
        pipeline_spec({"w": P(None, None), "b": P(None)}),
        batch_spec=P(),
    )
    state = init_fn(stacked)
    losses = []
    for _ in range(30):
        state, loss = step_fn(state, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
