"""Global shuffle tests: permutation properties (hypothesis), host
rendezvous exchange, device collectives on the 8-device CPU mesh."""

import threading
import time

import numpy as np
import pytest

# The property tests below need hypothesis (a test extra, pyproject
# [test]); without it, skip this module cleanly instead of erroring the
# whole collection.
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from ddl_tpu.exceptions import DDLError
from ddl_tpu.shuffle import (
    ThreadExchangeShuffler,
    Rendezvous,
    exchange_permutation,
    exchange_slices,
    inverse_permutation,
)
from ddl_tpu.types import Topology, RunMode


class TestPermutationProperties:
    @given(
        n=st.integers(min_value=3, max_value=64),
        seed=st.integers(min_value=0, max_value=2**31 - 1),
        round_=st.integers(min_value=0, max_value=1000),
    )
    @settings(max_examples=200, deadline=None)
    def test_no_self_sends_no_two_cycles(self, n, seed, round_):
        p = exchange_permutation(n, seed, round_)
        assert sorted(p) == list(range(n))  # a permutation
        assert np.all(p != np.arange(n))  # no self-sends
        assert np.all(p[p] != np.arange(n))  # no 2-cycles

    @given(
        n=st.integers(min_value=2, max_value=32),
        seed=st.integers(min_value=0, max_value=2**31 - 1),
    )
    @settings(max_examples=100, deadline=None)
    def test_deterministic_shared_agreement(self, n, seed):
        """All peers independently compute the identical permutation
        (reference shuffle.py:28-30 semantics)."""
        a = exchange_permutation(n, seed, 7)
        b = exchange_permutation(n, seed, 7)
        assert np.array_equal(a, b)

    def test_special_cases(self):
        assert list(exchange_permutation(1, 0, 0)) == [0]
        assert list(exchange_permutation(2, 123, 9)) == [1, 0]

    def test_inverse(self):
        p = exchange_permutation(16, 3, 4)
        inv = inverse_permutation(p)
        assert np.array_equal(p[inv], np.arange(16))

    def test_exchange_slices(self):
        a, b = exchange_slices(10)
        assert (a, b) == (slice(0, 5), slice(5, 10))


class TestThreadExchange:
    def _run_instances(self, n_instances, n_rows=8, num_exchange=4, rounds=1):
        """Simulate the same producer-idx across n instances, each with a
        tagged window; run `rounds` exchange rounds concurrently."""
        rdv = Rendezvous()
        arys = [
            np.full((n_rows, 2), float(i), dtype=np.float32)
            for i in range(n_instances)
        ]
        for i, a in enumerate(arys):
            a[:, 1] = np.arange(n_rows)  # row ids survive exchange

        def worker(i):
            topo = Topology(
                n_instances=n_instances, instance_idx=i, n_producers=1,
                mode=RunMode.THREAD,
            )
            sh = ThreadExchangeShuffler(
                topo, producer_idx=1, num_exchange=num_exchange, rendezvous=rdv
            )
            for _ in range(rounds):
                sh.global_shuffle(arys[i])

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(n_instances)]
        [t.start() for t in ts]
        [t.join(30) for t in ts]
        assert not any(t.is_alive() for t in ts)
        return arys

    @pytest.mark.parametrize("n_instances", [2, 3, 5])
    def test_exchange_conserves_samples(self, n_instances):
        arys = self._run_instances(n_instances)
        # Global multiset of origin tags is conserved.
        tags = np.concatenate([a[:, 0] for a in arys])
        counts = {float(i): int((tags == i).sum()) for i in range(n_instances)}
        assert all(c == 8 for c in counts.values())

    def test_rows_actually_moved(self):
        arys = self._run_instances(3)
        # Exchanged lanes (rows 0:4) no longer carry the local tag.
        for i, a in enumerate(arys):
            assert np.all(a[:4, 0] != float(i))
            assert np.all(a[4:, 0] == float(i))  # non-lane rows untouched

    def test_multi_round_drift_tolerant(self):
        arys = self._run_instances(4, rounds=5)
        tags = np.concatenate([a[:, 0] for a in arys])
        assert len(tags) == 32
        for i in range(4):
            assert (tags == float(i)).sum() == 8

    def test_bad_method_rejected(self):
        topo = Topology(n_instances=2, instance_idx=0, n_producers=1)
        with pytest.raises(NotImplementedError):
            ThreadExchangeShuffler(topo, 1, 4, exchange_method="bsend")


def _shm_exchange_worker(i, n_instances, session, root, rounds, pipe):
    """Spawn target: one instance's producer-side exchange over
    ShmRendezvous (module-level for pickling)."""
    import numpy as np

    from ddl_tpu.shuffle import ShmRendezvous, ThreadExchangeShuffler
    from ddl_tpu.types import RunMode, Topology

    ary = np.full((8, 2), float(i), dtype=np.float32)
    ary[:, 1] = np.arange(8)
    topo = Topology(
        n_instances=n_instances, instance_idx=i, n_producers=1,
        mode=RunMode.PROCESS,
    )
    sh = ThreadExchangeShuffler(
        topo, producer_idx=1, num_exchange=4,
        rendezvous=ShmRendezvous(session, root=root),
    )
    for _ in range(rounds):
        sh.global_shuffle(ary)
    pipe.send(ary)
    pipe.close()


class TestShmRendezvous:
    def test_put_take_roundtrip(self, tmp_path):
        from ddl_tpu.shuffle import ShmRendezvous

        rdv = ShmRendezvous("t-roundtrip", root=str(tmp_path))
        rows = np.arange(12, dtype=np.float32).reshape(4, 3)
        rdv.put((1, 0, 2), rows)
        out = rdv.take((1, 0, 2), timeout_s=5)
        np.testing.assert_array_equal(out, rows)
        rdv.cleanup()

    def test_take_aborts_on_flag(self, tmp_path):
        from ddl_tpu.exceptions import ShutdownRequested
        from ddl_tpu.shuffle import ShmRendezvous

        rdv = ShmRendezvous("t-abort", root=str(tmp_path))
        flag = {"down": False}

        def aborter():
            time.sleep(0.15)
            flag["down"] = True

        threading.Thread(target=aborter, daemon=True).start()
        t0 = time.monotonic()
        with pytest.raises(ShutdownRequested):
            rdv.take((1, 0, 0), timeout_s=30,
                     should_abort=lambda: flag["down"])
        assert time.monotonic() - t0 < 5.0
        rdv.cleanup()

    def test_take_retains_for_replay_until_retired(self, tmp_path):
        """Elastic × shuffle contract: a consumed mailbox stays readable
        (``.done``) so a respawned producer replaying its predecessor's
        round takes the SAME rows; retire() closes the replay window."""
        from ddl_tpu.exceptions import DDLError
        from ddl_tpu.shuffle import Rendezvous, ShmRendezvous

        for rdv in (Rendezvous(), ShmRendezvous("t-replay", root=str(tmp_path))):
            rows = np.arange(6, dtype=np.float32).reshape(2, 3)
            rdv.put((1, 4, 0), rows)
            first = rdv.take((1, 4, 0), timeout_s=5)
            np.testing.assert_array_equal(first, rows)
            # Replayed take (the respawn path): same rows, no blocking.
            np.testing.assert_array_equal(
                rdv.take((1, 4, 0), timeout_s=5), rows
            )
            rdv.retire((1, 4, 0))
            with pytest.raises(DDLError):
                rdv.take((1, 4, 0), timeout_s=0.2)

    def test_stale_session_sweep(self, tmp_path):
        """A crashed run's RAM-backed mailbox dir is reclaimed once its
        minting pid is dead AND it is old; a live run's dir survives any
        age (mtime alone would misfire on slow exchange cadences), as do
        hand-named sessions and foreign files (ADVICE r4: nothing else
        ever removed an uncleaned session)."""
        import os
        import uuid

        import ddl_tpu.shuffle as shuffle_mod
        from ddl_tpu.shuffle import ShmRendezvous

        # A pid that cannot be alive: spawn a trivial child and reap it
        # (no os.fork — forking the multi-threaded pytest/JAX process
        # can deadlock the child).
        import subprocess
        import sys

        child = subprocess.Popen([sys.executable, "-c", "pass"])
        child.wait(timeout=30)
        dead_pid = child.pid

        def session(pid):
            return f"t-{pid}-{uuid.uuid4().hex[:12]}"

        crashed = ShmRendezvous(session(dead_pid), root=str(tmp_path))
        crashed.put((0, 0, 0), np.zeros(2, np.float32))
        live_old = ShmRendezvous(session(os.getpid()), root=str(tmp_path))
        live_old.put((0, 0, 0), np.zeros(2, np.float32))
        young = ShmRendezvous(session(dead_pid), root=str(tmp_path))
        young.put((0, 0, 0), np.zeros(2, np.float32))
        named = ShmRendezvous("hand-named-old", root=str(tmp_path))
        named.put((0, 0, 0), np.zeros(2, np.float32))
        other = tmp_path / "ddl-rdv-not-a-dir"
        other.write_text("plain file, never touched")
        old = time.time() - 2 * shuffle_mod.STALE_SESSION_S
        for rdv in (crashed, live_old, named):
            os.utime(rdv._dir, (old, old))

        shuffle_mod._sweep_stale_sessions(str(tmp_path))
        assert not os.path.isdir(crashed._dir)  # dead minter + old: swept
        assert os.path.isdir(live_old._dir)  # alive minter: kept at any age
        assert os.path.isdir(young._dir)  # dead minter but young: grace
        assert os.path.isdir(named._dir)  # hand-named: caller's to clean
        assert other.read_text() == "plain file, never touched"

    def test_factory_is_picklable(self, tmp_path):
        """PROCESS mode ships the factory by pickle to spawned workers —
        a closure factory (the pre-fix shape) would fail right here."""
        import pickle

        from ddl_tpu.shuffle import ShmRendezvous, make_session

        f = ThreadExchangeShuffler.factory(
            rendezvous=ShmRendezvous(
                make_session("t-pick"), root=str(tmp_path)
            )
        )
        g = pickle.loads(pickle.dumps(f))
        topo = Topology(n_instances=2, instance_idx=0, n_producers=1,
                        mode=RunMode.PROCESS)
        sh = g(topology=topo, producer_idx=1, num_exchange=4,
               exchange_method="sendrecv_replace")
        assert sh.span == "process"
        g.rendezvous.cleanup()

    # n=2 runs ONE round: the fixed swap permutation would ping-pong the
    # same lanes straight back on round 2 (see examples/global_shuffle.py
    # docstring) and the rows-moved assertion would vacuously fail.
    @pytest.mark.parametrize("n_instances,rounds", [(2, 1), (3, 2)])
    def test_cross_process_exchange_conserves_samples(
        self, n_instances, rounds, tmp_path
    ):
        """PROCESS-mode twin of the THREAD multiset-preservation test
        (VERDICT r3 item 4): real OS processes exchanging over the
        /dev/shm mailbox fabric."""
        import multiprocessing as mp

        from ddl_tpu.shuffle import ShmRendezvous, make_session

        session = make_session("t-xproc")
        root = str(tmp_path)
        ctx = mp.get_context("spawn")
        procs, parents = [], []
        for i in range(n_instances):
            parent, child = ctx.Pipe(duplex=False)
            p = ctx.Process(
                target=_shm_exchange_worker,
                args=(i, n_instances, session, root, rounds, child),
            )
            p.start()
            child.close()
            procs.append(p)
            parents.append(parent)
        arys = []
        for parent, p in zip(parents, procs):
            assert parent.poll(120), "worker produced nothing in 120s"
            arys.append(parent.recv())
            p.join(30)
            assert p.exitcode == 0
        tags = np.concatenate([a[:, 0] for a in arys])
        for i in range(n_instances):
            assert (tags == float(i)).sum() == 8  # multiset conserved
        # Rows actually crossed the process boundary.
        for i, a in enumerate(arys):
            assert np.any(a[:, 0] != float(i))
        ShmRendezvous(session, root=root).cleanup()


class TestSpanRejection:
    """A fabric narrower than the topology fails loudly at handshake
    (VERDICT r3 Missing #2: previously a silent per-process stall)."""

    def _handshake(self, topo, factory):
        from ddl_tpu import DataProducerOnInitReturn, ProducerFunctionSkeleton
        from ddl_tpu.datapusher import DataPusher
        from ddl_tpu.transport.connection import (
            ProducerConnection, ThreadChannel,
        )
        from ddl_tpu.types import MetaData_Consumer_To_Producer

        class P(ProducerFunctionSkeleton):
            def on_init(self, **kw):
                return DataProducerOnInitReturn(
                    nData=16, nValues=2, shape=(16, 2), splits=(1, 1)
                )

            def post_init(self, my_ary, **kw):
                my_ary[:] = 0.0

        cons_end, prod_end = ThreadChannel.pair()
        cons_end.send(MetaData_Consumer_To_Producer(
            data_producer_function=P(), batch_size=8, n_epochs=1,
            global_shuffle_fraction_exchange=0.5,
            exchange_method="sendrecv_replace",
        ))
        cross = topo.mode is not RunMode.THREAD
        return DataPusher(
            ProducerConnection(prod_end, 1, cross_process=cross),
            topo, 1, shuffler_factory=factory,
        )

    def test_process_mode_rejects_thread_rendezvous(self):
        from ddl_tpu.exceptions import DoesNotMatchError

        topo = Topology(n_instances=2, instance_idx=0, n_producers=1,
                        mode=RunMode.PROCESS)
        with pytest.raises(DoesNotMatchError, match="in-process Rendezvous"):
            self._handshake(topo, ThreadExchangeShuffler.factory())

    def test_multihost_rejects_host_side_fabric(self):
        from ddl_tpu.exceptions import DoesNotMatchError
        from ddl_tpu.shuffle import ShmRendezvous, make_session

        topo = Topology(n_instances=2, instance_idx=0, n_producers=1,
                        mode=RunMode.MULTIHOST)
        rdv = ShmRendezvous(make_session("t-mh"), root="/tmp")
        with pytest.raises(DoesNotMatchError, match="cannot span hosts"):
            self._handshake(
                topo, ThreadExchangeShuffler.factory(rendezvous=rdv)
            )
        rdv.cleanup()

    def test_rejoin_requires_replay_capable_shuffler(self, tmp_path):
        """Elastic rejoin + shuffle is gated on POSITIVE capability: a
        fabric without consumed-box retention (no retire) fails at
        handshake with the clear old-style error, not a runtime
        timeout; a retention fabric with nslots=1 is rejected too (the
        one-slot restore could read the predecessor's torn in-flight
        fill)."""
        from ddl_tpu import DataProducerOnInitReturn, ProducerFunctionSkeleton
        from ddl_tpu.datapusher import DataPusher
        from ddl_tpu.exceptions import DoesNotMatchError
        from ddl_tpu.shuffle import ShmRendezvous, make_session
        from ddl_tpu.transport.connection import (
            ProducerConnection, ThreadChannel,
        )
        from ddl_tpu.transport.ring import ThreadRing
        from ddl_tpu.types import MetaData_Consumer_To_Producer

        class P(ProducerFunctionSkeleton):
            def on_init(self, **kw):
                return DataProducerOnInitReturn(
                    nData=16, nValues=2, shape=(16, 2), splits=(1, 1)
                )

            def post_init(self, my_ary, **kw):
                my_ary[:] = 0.0

        class NoRetentionFabric:
            """put/take/discard only — the pre-replay fabric interface."""

            span = "thread"

            def put(self, key, rows):
                pass

            def take(self, key, timeout_s=60.0, should_abort=None):
                raise AssertionError("never reached")

            def discard(self, key):
                pass

        def handshake(factory, nslots=2):
            cons_end, prod_end = ThreadChannel.pair()
            cons_end.send(MetaData_Consumer_To_Producer(
                data_producer_function=P(), batch_size=8, n_epochs=1,
                global_shuffle_fraction_exchange=0.5,
                exchange_method="sendrecv_replace",
            ))
            topo = Topology(n_instances=2, instance_idx=0, n_producers=1,
                            mode=RunMode.THREAD)
            return DataPusher(
                ProducerConnection(prod_end, 1, cross_process=False),
                topo, 1, nslots=nslots, shuffler_factory=factory,
                rejoin_ring=ThreadRing(nslots, 16 * 2 * 4),
            )

        with pytest.raises(DoesNotMatchError, match="supports_elastic_replay"):
            handshake(
                ThreadExchangeShuffler.factory(rendezvous=NoRetentionFabric())
            )
        with pytest.raises(DoesNotMatchError, match="nslots >= 2"):
            handshake(
                ThreadExchangeShuffler.factory(
                    rendezvous=ShmRendezvous(
                        make_session("t-one-slot"), root=str(tmp_path)
                    )
                ),
                nslots=1,
            )

    def test_process_mode_accepts_shm_rendezvous(self):
        from ddl_tpu.shuffle import ShmRendezvous, make_session

        topo = Topology(n_instances=2, instance_idx=0, n_producers=1,
                        mode=RunMode.PROCESS)
        rdv = ShmRendezvous(make_session("t-ok"), root="/tmp")
        pusher = self._handshake(
            topo, ThreadExchangeShuffler.factory(rendezvous=rdv)
        )
        assert pusher.shuffler is not None
        assert pusher.shuffler.span == "process"
        pusher.connection.finalize()
        rdv.cleanup()


class TestDeviceShuffle:
    @pytest.fixture(scope="class")
    def mesh(self):
        from ddl_tpu.parallel import data_parallel_mesh

        return data_parallel_mesh()

    def _sharded_window(self, mesh, n_instances, rows_per_instance=8, width=3):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        host = np.zeros((n_instances * rows_per_instance, width), np.float32)
        for i in range(n_instances):
            blk = host[i * rows_per_instance : (i + 1) * rows_per_instance]
            blk[:, 0] = i  # origin tag
            blk[:, 1] = np.arange(rows_per_instance)  # row id
        return jax.device_put(host, NamedSharding(mesh, P("dp"))), host

    def test_ppermute_exchange(self, mesh):
        from ddl_tpu.parallel import DeviceGlobalShuffler

        n = mesh.shape["dp"]
        sh = DeviceGlobalShuffler(mesh, num_exchange=4, seed=42)
        window, host = self._sharded_window(mesh, n)
        out = np.asarray(sh.shuffle(window))
        # Conservation of the global sample multiset.
        assert sorted(out[:, 0].tolist()) == sorted(host[:, 0].tolist())
        p = exchange_permutation(n, 42, 0)
        for i in range(n):
            blk = out[i * 8 : (i + 1) * 8]
            inv = inverse_permutation(p)
            # Lane A of instance i now carries rows from inv[i] (who sent
            # forward to i); lane B carries rows from p[i].
            assert np.all(blk[0:2, 0] == inv[i])
            assert np.all(blk[2:4, 0] == p[i])
            assert np.all(blk[4:, 0] == i)  # untouched rows

    def test_all_to_all_exchange(self, mesh):
        from ddl_tpu.parallel import DeviceGlobalShuffler

        n = mesh.shape["dp"]
        sh = DeviceGlobalShuffler(mesh, num_exchange=n, method="all_to_all")
        window, host = self._sharded_window(mesh, n, rows_per_instance=2 * n)
        out = np.asarray(sh.shuffle(window))
        assert sorted(out[:, 0].tolist()) == sorted(host[:, 0].tolist())
        # Each instance's exchange block now holds one row from EVERY peer.
        for i in range(n):
            blk = out[i * 2 * n : i * 2 * n + n]
            assert sorted(blk[:, 0].tolist()) == list(range(n))

    def test_rounds_vary_permutation(self, mesh):
        from ddl_tpu.parallel import DeviceGlobalShuffler

        n = mesh.shape["dp"]
        if n <= 2:
            pytest.skip("needs >2 instances")
        sh = DeviceGlobalShuffler(mesh, num_exchange=2, seed=7)
        w, _ = self._sharded_window(mesh, n)
        o1 = np.asarray(sh.shuffle(w))
        o2 = np.asarray(sh.shuffle(w))
        assert not np.array_equal(o1, o2)  # fresh permutation per round


class TestEndToEndGlobalShuffle:
    def test_cross_instance_rows_reach_consumers(self):
        """Two simulated instances, full pipeline: producer-side global
        shuffle runs inside the DataPusher loop (the path that was dead
        code in the reference, SURVEY Q1) and foreign-instance samples
        show up in drained windows."""
        import queue
        from ddl_tpu.datapusher import DataPusher
        from ddl_tpu.dataloader import DistributedDataLoader
        from ddl_tpu.transport.connection import (
            ConsumerConnection, ProducerConnection, ThreadChannel,
        )
        from ddl_tpu.types import Marker
        from ddl_tpu import DataProducerOnInitReturn, ProducerFunctionSkeleton

        class Tagged(ProducerFunctionSkeleton):
            def on_init(self, instance_idx=0, **kw):
                self.tag = float(instance_idx)
                return DataProducerOnInitReturn(
                    nData=16, nValues=2, shape=(16, 2), splits=(1, 1)
                )

            def post_init(self, my_ary, **kw):
                my_ary[:] = self.tag

        rdv = Rendezvous()
        results = {}

        def run_instance(i):
            topo = Topology(
                n_instances=2, instance_idx=i, n_producers=1,
                mode=RunMode.THREAD,
            )
            cons_end, prod_end = ThreadChannel.pair()
            pconn = ProducerConnection(prod_end, 1, cross_process=False)

            def producer():
                pusher = DataPusher(
                    pconn, topo, 1,
                    shuffler_factory=ThreadExchangeShuffler.factory(rdv),
                )
                pusher.push_data()

            pt = threading.Thread(target=producer, daemon=True)
            pt.start()
            loader = DistributedDataLoader(
                Tagged(), batch_size=16,
                connection=ConsumerConnection([cons_end]),
                n_epochs=2, output="numpy",
                global_shuffle_fraction_exchange=0.5,  # 8 rows per round
            )
            tags = []
            for _ in range(2):
                for (a, b) in loader:
                    tags.append(a[:, 0].copy())
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
            results[i] = np.concatenate(tags)
            pt.join(10)

        ts = [threading.Thread(target=run_instance, args=(i,)) for i in (0, 1)]
        [t.start() for t in ts]
        [t.join(60) for t in ts]
        assert not any(t.is_alive() for t in ts)
        # Each instance saw samples tagged by the OTHER instance.
        assert np.any(results[0] == 1.0), "instance 0 never saw foreign rows"
        assert np.any(results[1] == 0.0), "instance 1 never saw foreign rows"
        # And conservation: across both, half the rows moved each way.
        assert np.sum(results[0] == 1.0) == np.sum(results[1] == 0.0)


class TestRendezvousShutdown:
    def test_take_aborts_on_shutdown_flag(self):
        """A producer stranded in the exchange (partner already tearing
        down) must wake promptly via should_abort, not wait out the full
        rendezvous timeout — the flake this fixes stranded a producer 60s
        at phase teardown."""
        from ddl_tpu.exceptions import ShutdownRequested
        from ddl_tpu.shuffle import Rendezvous

        rdv = Rendezvous()
        flag = {"down": False}
        t0 = time.monotonic()

        def aborter():
            time.sleep(0.15)
            flag["down"] = True

        threading.Thread(target=aborter, daemon=True).start()
        with pytest.raises(ShutdownRequested):
            rdv.take((1, 0, 0), timeout_s=30.0,
                     should_abort=lambda: flag["down"])
        assert time.monotonic() - t0 < 5.0  # woke promptly, not at 30s

    def test_pusher_exchange_wait_observes_ring_shutdown(self):
        """End-to-end: instance 0's producer blocks in the exchange with
        no partner; flagging its ring shuts the pipeline down cleanly."""
        from ddl_tpu import DataProducerOnInitReturn, ProducerFunctionSkeleton
        from ddl_tpu.datapusher import DataPusher
        from ddl_tpu.shuffle import Rendezvous
        from ddl_tpu.transport.connection import (
            ConsumerConnection,
            ProducerConnection,
            ThreadChannel,
        )
        from ddl_tpu.types import (
            MetaData_Consumer_To_Producer,
            RunMode,
            Topology,
        )

        class P(ProducerFunctionSkeleton):
            def on_init(self, **kw):
                return DataProducerOnInitReturn(
                    nData=8, nValues=2, shape=(8, 2), splits=(1, 1)
                )

            def post_init(self, my_ary, **kw):
                my_ary[:] = 0.0

        topo = Topology(n_instances=2, instance_idx=0, n_producers=1,
                        mode=RunMode.THREAD)
        cons_end, prod_end = ThreadChannel.pair()
        pconn = ProducerConnection(prod_end, 1, cross_process=False)
        rdv = Rendezvous()  # private: partner instance never shows up

        def producer():
            DataPusher(
                pconn, topo, 1,
                shuffler_factory=ThreadExchangeShuffler.factory(rdv),
            ).push_data()

        pt = threading.Thread(target=producer, daemon=True)
        pt.start()
        conn = ConsumerConnection([cons_end])
        conn.send_metadata(MetaData_Consumer_To_Producer(
            data_producer_function=P(), batch_size=8, n_epochs=1,
            global_shuffle_fraction_exchange=0.5,
            exchange_method="sendrecv_replace",
        ))
        conn.recv_metadata_as_consumer()
        conn.attach_rings()
        time.sleep(0.3)  # let the producer reach the partnerless exchange
        t0 = time.monotonic()
        conn.shutdown_operation()
        pt.join(10)
        assert not pt.is_alive()
        assert time.monotonic() - t0 < 5.0  # clean, prompt exit
        conn.finalize()

    def test_aborted_exchange_retracts_posted_rows(self):
        """A shuffler whose take aborts must discard its own put so a
        later run on the same rendezvous can't pop stale rows."""
        from ddl_tpu.exceptions import ShutdownRequested
        from ddl_tpu.shuffle import Rendezvous

        rdv = Rendezvous()
        topo = Topology(n_instances=2, instance_idx=0, n_producers=1,
                        mode=RunMode.THREAD)
        sh = ThreadExchangeShuffler(topo, 1, num_exchange=4, rendezvous=rdv)
        ary = np.zeros((8, 2), np.float32)
        with pytest.raises(ShutdownRequested):
            sh.global_shuffle(ary, should_abort=lambda: True)
        assert not rdv._boxes, rdv._boxes  # nothing stale left behind
