"""Auxiliary subsystem tests: checkpoint/resume, watchdog, config, readers."""

import os
import threading
import time

import jax
import numpy as np
import optax
import pytest

from ddl_tpu.checkpoint import (
    LoaderCheckpoint,
    latest_step,
    restore_train_state,
    save_train_state,
)
from ddl_tpu.config import LoaderConfig
from ddl_tpu.readers import ArrayProducer, FileShardProducer, TokenStreamProducer
from datagen import encode_example_int64, write_image_shard, write_tfrecord
from ddl_tpu.watchdog import Watchdog


class TestTrainCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        from ddl_tpu.models import pointnet
        from ddl_tpu.parallel.mesh import make_mesh
        from ddl_tpu.parallel.train import make_train_step

        cfg = pointnet.PointNetConfig(hidden=(8,))
        mesh = make_mesh({"dp": 8})
        init_fn, step_fn = make_train_step(
            lambda p, b: pointnet.weighted_mse_loss(p, b, cfg),
            optax.adam(1e-2), mesh, pointnet.param_specs(cfg),
        )
        state = init_fn(pointnet.init_params(cfg, jax.random.key(0)))
        batch = (
            np.ones((8, 3), np.float32),
            np.zeros((8, 6), np.float32),
            np.ones((8, 1), np.float32),
        )
        for _ in range(3):
            state, _ = step_fn(state, batch)
        save_train_state(state, str(tmp_path / "ckpt"))
        assert latest_step(str(tmp_path / "ckpt")) == 3

        fresh = init_fn(pointnet.init_params(cfg, jax.random.key(1)))
        restored = restore_train_state(str(tmp_path / "ckpt"), fresh)
        assert restored.step == 3
        np.testing.assert_allclose(
            np.asarray(restored.params["layers"][0]["w"]),
            np.asarray(state.params["layers"][0]["w"]),
        )
        # Restored state keeps training.
        restored2, loss = step_fn(restored, batch)
        assert np.isfinite(float(loss))

    def test_loader_checkpoint_roundtrip(self, tmp_path):
        ck = LoaderCheckpoint(epoch=3, target=1, batches_in_window=2,
                              shuffle_round=7)
        p = str(tmp_path / "loader.json")
        ck.save(p)
        assert LoaderCheckpoint.load(p) == ck


class _FakeRing:
    def __init__(self):
        self.committed = 0.0
        self.released = 0.0
        self.down = False

    def is_shutdown(self):
        return self.down

    def stats(self):
        return {"committed": self.committed, "released": self.released,
                "producer_stall_s": 0.0, "consumer_stall_s": 0.0}


class _FakeWorkers:
    def __init__(self, rings):
        self.threads = []
        self.processes = []

        class C:
            pass

        self.connection = C()
        self.connection.rings = rings
        self.aborted = False

    def abort(self):
        self.aborted = True


class TestWatchdog:
    def test_dead_thread_detected(self):
        w = _FakeWorkers([_FakeRing()])
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join(5.0)
        w.threads = [t]
        wd = Watchdog(w, poll_interval_s=0.01)
        assert "died" in wd.check_once()

    def test_stall_detected_and_abort_fired(self):
        ring = _FakeRing()
        w = _FakeWorkers([ring])
        wd = Watchdog(w, poll_interval_s=0.02, stall_budget_s=0.1)
        wd.start()
        time.sleep(0.4)  # no progress, committed == released
        wd.stop()
        assert wd.failures and "no progress" in wd.failures[0]
        assert w.aborted

    def test_shutdown_in_progress_suppresses_failures(self):
        # Mid-teardown: one of two rings flagged, producer thread already
        # exited. Must NOT be reported as a failure.
        r1, r2 = _FakeRing(), _FakeRing()
        r1.down = True
        w = _FakeWorkers([r1, r2])
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join(5.0)
        w.threads = [t]
        wd = Watchdog(w, poll_interval_s=0.01)
        assert wd.check_once() is None

    def test_ring_double_without_is_shutdown_tolerated(self):
        class _Bare:
            def stats(self):
                return {"committed": 1.0, "released": 0.0}

        w = _FakeWorkers([_Bare()])
        wd = Watchdog(w, poll_interval_s=0.01)
        assert wd.check_once() is None  # progress pending, nothing dead

    def test_crashing_sweep_does_not_kill_watchdog(self):
        w = _FakeWorkers([_FakeRing()])
        wd = Watchdog(w, poll_interval_s=0.01, stall_budget_s=10.0)
        boom = {"n": 0}
        real = wd.check_once

        def flaky():
            boom["n"] += 1
            if boom["n"] == 1:
                raise RuntimeError("transient")
            return real()

        wd.check_once = flaky
        wd.start()
        time.sleep(0.1)
        wd.stop()
        assert boom["n"] > 1  # survived the first crashing sweep
        assert not wd.failures

    def test_progress_keeps_quiet(self):
        ring = _FakeRing()
        w = _FakeWorkers([ring])
        wd = Watchdog(w, poll_interval_s=0.02, stall_budget_s=0.2)
        wd.start()
        for _ in range(10):
            ring.committed += 1
            ring.released += 1
            time.sleep(0.03)
        wd.stop()
        assert not wd.failures


class TestConfig:
    def test_layering(self, tmp_path, monkeypatch):
        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text('{"batch_size": 64, "n_epochs": 5}')
        monkeypatch.setenv("DDL_TPU_BATCH_SIZE", "128")
        cfg = LoaderConfig.load(str(cfg_path), n_producers=7)
        assert cfg.batch_size == 128  # env beats file
        assert cfg.n_epochs == 5  # file beats default
        assert cfg.n_producers == 7  # kwargs beat all

    def test_unknown_keys_rejected(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text('{"batch_sizes": 64}')
        with pytest.raises(ValueError, match="unknown config keys"):
            LoaderConfig.load(str(p))

    def test_save_load_roundtrip(self, tmp_path):
        cfg = LoaderConfig(batch_size=99)
        p = str(tmp_path / "out.json")
        cfg.save(p)
        assert LoaderConfig.load(p).batch_size == 99

    def test_config_drives_decorator(self):
        """LoaderConfig is consumed by the pipeline, not just its own
        tests (VERDICT r2 item 6): the decorator takes its topology from
        the config."""
        from ddl_tpu import distributed_dataloader

        cfg = LoaderConfig(n_producers=3, mode="thread", nslots=1)

        @distributed_dataloader(config=cfg)
        def main(env):
            return (
                env.topology.n_producers,
                env.topology.mode.value,
                len(env.connection.channels),
            )

        n, mode, chans = main()
        assert (n, mode, chans) == (3, "thread", 3)

    def test_config_drives_trainer_fit(self, rng):
        """One LoaderConfig configures an entire Trainer.fit run."""
        import jax
        import optax
        from jax.sharding import PartitionSpec as P

        from ddl_tpu.models import pointnet
        from ddl_tpu.parallel.mesh import make_mesh
        from ddl_tpu.readers import ArrayProducer
        from ddl_tpu.trainer import Trainer

        cfg = LoaderConfig(
            batch_size=16, n_epochs=2, n_producers=2, mode="thread",
            nslots=2, output="numpy",
        )
        net = pointnet.PointNetConfig(n_inputs=3, n_outputs=2)
        trainer = Trainer(
            loss_fn=lambda p, b: pointnet.weighted_mse_loss(p, b, net),
            optimizer=optax.adam(1e-2),
            mesh=make_mesh({"dp": 8}),
            param_specs=pointnet.param_specs(net),
            init_params=pointnet.init_params(net, jax.random.key(0)),
            batch_spec=P(("dp",)),
            watchdog=False,
        )
        data = rng.random((128, 6)).astype(np.float32)
        res = trainer.fit(
            ArrayProducer(data, window_size=32, splits=(3, 2, 1)),
            config=cfg,
        )
        assert len(res.losses) == 2
        assert all(np.isfinite(l) for l in res.losses)

    def test_fit_without_batch_size_or_config_rejected(self):
        import jax
        import optax
        from jax.sharding import PartitionSpec as P

        from ddl_tpu.models import pointnet
        from ddl_tpu.parallel.mesh import make_mesh
        from ddl_tpu.readers import ArrayProducer
        from ddl_tpu.trainer import Trainer

        net = pointnet.PointNetConfig(n_inputs=3, n_outputs=2)
        trainer = Trainer(
            loss_fn=lambda p, b: pointnet.weighted_mse_loss(p, b, net),
            optimizer=optax.adam(1e-2),
            mesh=make_mesh({"dp": 8}),
            param_specs=pointnet.param_specs(net),
            init_params=pointnet.init_params(net, jax.random.key(0)),
            batch_spec=P(("dp",)),
            watchdog=False,
        )
        with pytest.raises(ValueError, match="batch_size and n_epochs"):
            trainer.fit(ArrayProducer(np.ones((8, 6), np.float32),
                                      window_size=8, splits=(3, 2, 1)))


class TestReaders:
    def _drain_one(self, producer, batch_size=8, n_epochs=2):
        from ddl_tpu import DistributedDataLoader, Marker, distributed_dataloader

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                producer, batch_size=batch_size, connection=env.connection,
                n_epochs=n_epochs, output="numpy",
            )
            out = []
            for _ in range(n_epochs):
                for batch in loader:
                    out.append([c.copy() for c in batch])
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
            return out

        return main()

    def test_array_producer(self):
        data = np.arange(256 * 5, dtype=np.float32).reshape(256, 5)
        out = self._drain_one(ArrayProducer(data, window_size=32, splits=(4, 1)))
        assert out and out[0][0].shape == (8, 4) and out[0][1].shape == (8, 1)
        # Every served row is a real dataset row.
        row = np.concatenate([out[0][0][0], out[0][1][0]])
        assert float(row[0]) % 5 == 0 and row[1] == row[0] + 1

    def test_file_shard_producer(self, tmp_path):
        for i in range(4):
            np.save(tmp_path / f"shard_{i}.npy",
                    np.full((16, 3), float(i), np.float32))
        out = self._drain_one(
            FileShardProducer(str(tmp_path / "shard_*.npy")), batch_size=16
        )
        tags = {float(b[0][0, 0]) for b in out}
        assert len(tags) >= 2  # multiple shards flowed through

    def test_file_shard_too_few_shards(self, tmp_path):
        np.save(tmp_path / "only.npy", np.zeros((4, 2), np.float32))

        with pytest.raises(Exception):  # surfaced via handshake failure
            self._drain_one(FileShardProducer(str(tmp_path / "only_*.npy")))

    def test_token_stream_producer(self, tmp_path):
        tokens = (np.arange(4096) % 97).astype(np.int32)
        f = tmp_path / "tokens.bin"
        tokens.tofile(f)
        out = self._drain_one(
            TokenStreamProducer(str(f), seq_len=32, window_rows=16),
            batch_size=8,
        )
        (seqs,) = out[0]
        assert seqs.shape == (8, 32) and seqs.dtype == np.int32
        # Sequences are contiguous slices of the stream.
        d = np.diff(seqs[0].astype(np.int64)) % 97
        assert np.all(d == 1)

    def test_packed_token_producer(self, tmp_path):
        from ddl_tpu.readers import PackedTokenProducer

        # Documents of varied length separated by EOS token 0.
        rng = np.random.default_rng(3)
        docs = [
            rng.integers(1, 90, size=int(n)).tolist() + [0]
            for n in rng.integers(3, 40, size=200)
        ]
        tokens = np.asarray(
            [t for d in docs for t in d], np.int32
        )
        f = tmp_path / "packed.bin"
        tokens.tofile(f)
        out = self._drain_one(
            PackedTokenProducer(str(f), seq_len=32, window_rows=16,
                                delimiter=0),
            batch_size=8,
        )
        toks, seg = out[0]
        assert toks.shape == seg.shape == (8, 32)
        for r in range(8):
            # Segment ids start at 0, are nondecreasing, and increment
            # exactly after each delimiter (EOS belongs to its document).
            assert seg[r, 0] == 0
            expect = np.zeros(32, np.int64)
            expect[1:] = np.cumsum(toks[r, :-1] == 0)
            np.testing.assert_array_equal(seg[r].astype(np.int64), expect)

    def test_packed_training_end_to_end(self, tmp_path):
        """Loader-fed packed pretraining: PackedTokenProducer ->
        window-streamed Trainer -> segment-masked flash loss."""
        import jax
        import optax
        from jax.sharding import PartitionSpec as P

        from ddl_tpu.models import llama
        from ddl_tpu.parallel.mesh import make_mesh
        from ddl_tpu.readers import PackedTokenProducer
        from ddl_tpu.trainer import Trainer

        rng = np.random.default_rng(4)
        docs = [
            rng.integers(1, 60, size=int(n)).tolist() + [0]
            for n in rng.integers(4, 30, size=400)
        ]
        tokens = np.asarray([t for d in docs for t in d], np.int32)
        f = tmp_path / "pack.bin"
        tokens.tofile(f)
        cfg = llama.LlamaConfig(
            vocab=64, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq=32, dtype=jax.numpy.float32,
        )
        trainer = Trainer(
            loss_fn=lambda p, b: llama.next_token_loss(
                p, b[0], cfg, segment_ids=b[1]
            ),
            optimizer=optax.adamw(3e-3),
            mesh=make_mesh({"dp": 8}),
            param_specs=llama.param_specs(cfg),
            init_params=llama.init_params(cfg, jax.random.key(0)),
            batch_spec=P(("dp",)),
            watchdog=False,
        )
        res = trainer.fit(
            PackedTokenProducer(str(f), seq_len=32, window_rows=32,
                                delimiter=0),
            batch_size=8, n_epochs=4, n_producers=2, mode="thread",
            output="jax", window_stream=True,
        )
        assert all(np.isfinite(v) for v in res.losses), res.losses
        assert res.losses[-1] < res.losses[0]


class TestShuffleRoundResume:
    def test_shuffler_round_roundtrips(self, tmp_path):
        from ddl_tpu.parallel import DeviceGlobalShuffler, data_parallel_mesh

        mesh = data_parallel_mesh()
        sh = DeviceGlobalShuffler(mesh, num_exchange=4, seed=9)
        sh._round = 5

        class _L:  # minimal loader stand-in
            _epoch, _target, _batches_in_window = 2, 1, 0

        ck = LoaderCheckpoint.capture(_L(), shuffler=sh)
        assert ck.shuffle_round == 5
        p = str(tmp_path / "l.json")
        ck.save(p)
        sh2 = DeviceGlobalShuffler(mesh, num_exchange=4, seed=9)
        l2 = _L()
        LoaderCheckpoint.load(p).apply(l2, shuffler=sh2)
        assert sh2._round == 5  # permutation schedule continues


class TestWebDatasetProducer:
    def test_image_shards_drain(self, tmp_path):
        from ddl_tpu.readers import WebDatasetProducer

        for s in range(2):
            write_image_shard(
                str(tmp_path / f"shard-{s}.tar"),
                [(f"s{s}k{i}", s * 10 + i) for i in range(6)],
            )
        from ddl_tpu import DistributedDataLoader, Marker, distributed_dataloader

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                WebDatasetProducer(
                    str(tmp_path / "shard-*.tar"), image_size=8,
                    window_rows=4,
                ),
                batch_size=4, connection=env.connection, n_epochs=2,
                output="numpy",
            )
            labels = []
            for _ in range(2):
                for px, y in loader:
                    assert px.shape == (4, 8 * 8 * 3)
                    assert px.min() >= 0.0 and px.max() <= 1.0
                    labels.extend(int(v) for v in y.ravel())
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
            return labels

        labels = main()
        # Both shards' label ranges appear (one shard per producer).
        assert any(v < 10 for v in labels) and any(v >= 10 for v in labels)


class TestTFRecordProducer:
    def test_example_roundtrip(self):
        from ddl_tpu.readers import example_int64_feature

        payload = encode_example_int64("input_ids", [7, 300, 2, 99999])
        got = example_int64_feature(payload, "input_ids")
        assert got.tolist() == [7, 300, 2, 99999]
        assert example_int64_feature(payload, "other") is None

    def test_tfrecord_stream_drains(self, tmp_path):
        from ddl_tpu import DistributedDataLoader, Marker, distributed_dataloader
        from ddl_tpu.readers import TFRecordTokenProducer

        rng = np.random.default_rng(0)
        for s in range(2):
            payloads = [
                encode_example_int64(
                    "input_ids", rng.integers(0, 1000, 50).tolist()
                )
                for _ in range(8)
            ]
            write_tfrecord(str(tmp_path / f"c4-{s}.tfrecord"), payloads)

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                TFRecordTokenProducer(
                    str(tmp_path / "c4-*.tfrecord"), seq_len=16,
                    window_rows=8,
                ),
                batch_size=8, connection=env.connection, n_epochs=2,
                output="numpy",
            )
            n = 0
            for _ in range(2):
                for (tok,) in loader:
                    assert tok.shape == (8, 16) and tok.dtype == np.int32
                    assert (tok >= 0).all() and (tok < 1000).all()
                    n += 1
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
            return n

        assert main() == 2

    def test_raw_payload_mode(self, tmp_path):
        from ddl_tpu.readers import TFRecordTokenProducer

        toks = np.arange(64, dtype="<i4")
        write_tfrecord(str(tmp_path / "raw-0.tfrecord"), [toks.tobytes()])
        p = TFRecordTokenProducer(
            str(tmp_path / "raw-*.tfrecord"), seq_len=8, window_rows=4,
            feature_key=None,
        )
        ret = p.on_init(producer_idx=1)
        ary = np.zeros(ret.shape, np.int32)
        p.post_init(my_ary=ary)
        assert ary.ravel().tolist() == list(range(32))


class TestProfilingAndBandwidth:
    def test_trace_writes_profile(self, tmp_path):
        """profiling.trace captures a jax.profiler trace to the log dir."""
        import jax.numpy as jnp

        from ddl_tpu.profiling import annotate, maybe_trace, trace

        with trace(str(tmp_path)):
            with annotate("ddl.test_span"):
                _ = float(jnp.sum(jnp.ones((8, 8))))
        produced = list((tmp_path).rglob("*"))
        assert any(p.is_file() for p in produced), produced
        # maybe_trace with no dir is a no-op (no error, nothing written).
        with maybe_trace(None):
            pass

    def test_h2d_bandwidth_and_utilization(self):
        from ddl_tpu.ingest import measure_h2d_bandwidth, north_star_report
        from ddl_tpu.observability import Metrics

        bw = measure_h2d_bandwidth(nbytes=1 << 16, trials=1)
        assert bw > 0
        m = Metrics()
        m.incr("ingest.bytes", 1000.0)
        rep = north_star_report(m, link_bytes_per_sec=bw)
        assert rep["link_bytes_per_sec"] == bw
        # The incr'd bytes must actually flow into the utilization.
        assert rep["bandwidth_utilization"] > 0.0
        # Without a denominator the utilization key is absent, not zero.
        assert "bandwidth_utilization" not in north_star_report(m)
