"""PyShmRing under ``DDL_TPU_FORCE_PY_RING=1``: the pure-Python fallback.

The fallback path (no C++ toolchain) previously had no dedicated tests —
it was only exercised incidentally when the native build happened to be
missing.  These tests force it explicitly and cover the protocol under
contention, shutdown-during-wait (the §3.5 any-time-cancellability
property), the open/attach path, and the end-to-end loader ride.

In-process use is GIL-serialized (safe on any ISA); the one spawned-
process test carries the TSO guard from ``ringsupport``.
"""

import os
import threading
import time

import numpy as np
import pytest

from ddl_tpu.exceptions import (
    ShutdownRequested,
    StallTimeoutError,
    TransportError,
)
from ddl_tpu.transport import shm_ring as shm_ring_mod
from ddl_tpu.transport.shm_ring import (
    PyShmRing,
    create_shm_ring,
    make_ring_name,
    open_shm_ring,
)
from ringsupport import TSO


@pytest.fixture
def force_py(monkeypatch):
    """Force the fallback and allow it on this (in-process, serialized)
    interpreter regardless of ISA."""
    monkeypatch.setenv("DDL_TPU_FORCE_PY_RING", "1")
    monkeypatch.setenv("DDL_TPU_UNSAFE_PY_RING", "1")


@pytest.fixture
def ring(force_py):
    r = create_shm_ring(make_ring_name("pyforce"), 2, 256)
    yield r
    r.shutdown()
    r.close()
    try:
        r.unlink()
    except OSError:
        pass


class TestForcedSelection:
    def test_factories_return_py_ring(self, ring):
        """With the env knob set, both factories must yield the fallback
        even though this image has a working g++."""
        assert isinstance(ring, PyShmRing)
        peer = open_shm_ring(ring.name)
        assert isinstance(peer, PyShmRing)
        assert (peer.nslots, peer.slot_bytes) == (2, 256)
        peer.close()

    def test_native_available_reports_false(self, force_py):
        assert shm_ring_mod.native_available() is False


class TestProtocol:
    def test_fifo_handoff_and_payload(self, ring):
        for i in range(2):
            slot = ring.acquire_fill(timeout_s=5)
            view = ring.slot_view(slot)
            view[:4] = i + 1
            ring.commit(slot, 4)
        for i in range(2):
            slot = ring.acquire_drain(timeout_s=5)
            assert ring.slot_payload(slot) == 4
            assert list(ring.slot_view(slot)[:4]) == [i + 1] * 4
            ring.release(slot)

    def test_fill_blocks_when_full_then_timeout(self, ring):
        ring.commit(ring.acquire_fill(timeout_s=5), 1)
        ring.commit(ring.acquire_fill(timeout_s=5), 1)
        with pytest.raises(StallTimeoutError):
            ring.acquire_fill(timeout_s=0.2)

    def test_drain_timeout_when_empty(self, ring):
        with pytest.raises(StallTimeoutError):
            ring.acquire_drain(timeout_s=0.2)

    def test_drain_ahead_validation_and_lookahead(self, ring):
        with pytest.raises(ValueError):
            ring.acquire_drain_ahead(2, timeout_s=0.2)
        ring.commit(ring.acquire_fill(timeout_s=5), 1)
        ring.commit(ring.acquire_fill(timeout_s=5), 1)
        s0 = ring.acquire_drain_ahead(0, timeout_s=5)
        s1 = ring.acquire_drain_ahead(1, timeout_s=5)
        assert {s0, s1} == {0, 1}
        assert ring.poll_drain_ready(0)
        ring.release(s0)
        ring.release(s1)

    def test_threaded_producer_consumer(self, ring):
        """A producer thread and the main-thread consumer exchange 50
        windows through the 2-slot ring with correct content in order."""
        n = 50

        def produce():
            for i in range(n):
                slot = ring.acquire_fill(timeout_s=30)
                ring.slot_view(slot)[:8] = np.frombuffer(
                    np.int64(i).tobytes(), dtype=np.uint8
                )
                ring.commit(slot, 8)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        got = []
        for _ in range(n):
            slot = ring.acquire_drain(timeout_s=30)
            got.append(
                int(ring.slot_view(slot)[:8].view(np.int64)[0])
            )
            ring.release(slot)
        t.join(30)
        assert not t.is_alive()
        assert got == list(range(n))


class TestShutdown:
    def test_shutdown_during_blocked_drain(self, ring):
        """The §3.5 property on the fallback: a consumer blocked in
        acquire_drain must wake with ShutdownRequested when any thread
        flips the shutdown flag — well before the wait timeout."""
        waiter_err = []

        def drain():
            t0 = time.monotonic()
            try:
                ring.acquire_drain(timeout_s=60)
            except ShutdownRequested:
                waiter_err.append(("shutdown", time.monotonic() - t0))
            except StallTimeoutError:  # pragma: no cover - the bug case
                waiter_err.append(("timeout", time.monotonic() - t0))

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        time.sleep(0.1)  # let it block
        ring.shutdown()
        t.join(10)
        assert not t.is_alive()
        assert waiter_err and waiter_err[0][0] == "shutdown"
        assert waiter_err[0][1] < 30, "woke by timeout, not by shutdown"

    def test_shutdown_during_blocked_fill(self, ring):
        ring.commit(ring.acquire_fill(timeout_s=5), 1)
        ring.commit(ring.acquire_fill(timeout_s=5), 1)  # ring now full

        def fill():
            with pytest.raises(ShutdownRequested):
                ring.acquire_fill(timeout_s=60)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        time.sleep(0.1)
        ring.shutdown()
        t.join(10)
        assert not t.is_alive()

    def test_shutdown_flag_is_persistent_across_open(self, ring):
        ring.shutdown()
        peer = open_shm_ring(ring.name)
        assert peer.is_shutdown()
        with pytest.raises(ShutdownRequested):
            peer.acquire_drain(timeout_s=1)
        peer.close()


class TestFormatAndGates:
    def test_open_rejects_native_format_segment(self, force_py, tmp_path):
        """A py-format open of a non-py segment must fail loudly (magic
        mismatch), not hand back garbage counters."""
        name = make_ring_name("badfmt")
        path = f"/dev/shm/{name.lstrip('/')}"
        with open(path, "wb") as f:
            f.write(b"\x00" * 8192)  # header-sized zeros, no magic
        try:
            with pytest.raises(TransportError, match="not py-format"):
                PyShmRing.open(name)
        finally:
            os.unlink(path)

    def test_tso_gate_blocks_without_override(self, monkeypatch):
        """On non-TSO ISAs construction must refuse unless overridden; on
        TSO machines the gate is a no-op (simulated via the machine
        probe)."""
        monkeypatch.setenv("DDL_TPU_FORCE_PY_RING", "1")
        monkeypatch.delenv("DDL_TPU_UNSAFE_PY_RING", raising=False)
        import platform

        monkeypatch.setattr(platform, "machine", lambda: "aarch64")
        with pytest.raises(TransportError, match="total-store-order"):
            PyShmRing.create(make_ring_name("tso"), 2, 64)

    def test_stats_track_counters(self, ring):
        ring.commit(ring.acquire_fill(timeout_s=5), 1)
        st = ring.stats()
        assert st["committed"] == 1.0 and st["released"] == 0.0
        ring.release(ring.acquire_drain(timeout_s=5))
        assert ring.stats()["released"] == 1.0


@pytest.mark.skipif(not TSO, reason="cross-process py ring needs TSO")
class TestInplaceOverPyRing:
    def test_process_inplace_stream_byte_identical_on_py_ring(
        self, force_py, tmp_path, monkeypatch
    ):
        """The write-once PROCESS path over the PYTHON shm ring: a real
        spawned producer fills FileShardProducer windows straight into
        PyShmRing slots (DDL_TPU_INPLACE=1) and the served stream is
        byte-identical to the copying fill (DDL_TPU_INPLACE=0) on the
        same transport."""
        from ddl_tpu import (
            DistributedDataLoader,
            Marker,
            distributed_dataloader,
        )
        from ddl_tpu.readers import FileShardProducer

        rng = np.random.default_rng(3)
        for i in range(2):
            np.save(
                tmp_path / f"shard_{i}.npy",
                rng.standard_normal((8, 4)).astype(np.float32),
            )
        pattern = str(tmp_path / "shard_*.npy")

        def drain(inplace):
            monkeypatch.setenv("DDL_TPU_INPLACE", inplace)

            @distributed_dataloader(n_producers=1, mode="process")
            def main(env):
                loader = DistributedDataLoader(
                    FileShardProducer(pattern, seed=0, warm=False),
                    batch_size=4, connection=env.connection,
                    n_epochs=2, output="numpy",
                )
                out = []
                for _ in range(2):
                    for cols in loader:
                        out.append(np.hstack(
                            [np.asarray(c) for c in cols]
                        ).copy())
                        loader.mark(Marker.END_OF_BATCH)
                    loader.mark(Marker.END_OF_EPOCH)
                return np.stack(out)

            return main()

        np.testing.assert_array_equal(drain("1"), drain("0"))


@pytest.mark.skipif(not TSO, reason="cross-process py ring needs TSO")
class TestLoaderRide:
    def test_thread_mode_loader_served_by_forced_py_ring(
        self, force_py, monkeypatch
    ):
        """End-to-end: PROCESS-mode-style shm rings forced to the Python
        implementation still serve a full (single-producer, in-process)
        drain loop through the public ring API."""
        # Producer/consumer pair over one forced py ring, window-sized
        # batches, exactly as DataPusher/DistributedDataLoader drive it.
        ring = create_shm_ring(make_ring_name("ride"), 2, 4 * 8)
        assert isinstance(ring, PyShmRing)
        windows = [np.arange(4, dtype=np.int64) + 10 * k for k in range(5)]

        def produce():
            try:
                for w in windows:
                    slot = ring.acquire_fill(timeout_s=30)
                    ring.slot_view(slot)[:].view(np.int64)[:] = w
                    ring.commit(slot, w.nbytes)
                    # after the last commit the consumer shuts us down
                ring.acquire_fill(timeout_s=30)
            except ShutdownRequested:
                pass

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        seen = []
        for _ in windows:
            slot = ring.acquire_drain(timeout_s=30)
            seen.append(ring.slot_view(slot)[:].view(np.int64).copy())
            ring.release(slot)
        ring.shutdown()
        t.join(30)
        assert not t.is_alive()
        np.testing.assert_array_equal(np.stack(seen), np.stack(windows))
        ring.close()
        ring.unlink()
