"""Ingest path tests: DeviceIngestor, PrefetchIterator, epoch resync."""

import numpy as np

from ddl_tpu import (
    DataProducerOnInitReturn,
    DistributedDataLoader,
    Marker,
    ProducerFunctionSkeleton,
    distributed_dataloader,
)
from ddl_tpu.ingest import DeviceIngestor, PrefetchIterator


class SeqProducer(ProducerFunctionSkeleton):
    def on_init(self, producer_idx=0, **kw):
        return DataProducerOnInitReturn(
            nData=32, nValues=4, shape=(32, 4), splits=(3, 1)
        )

    def post_init(self, my_ary, **kw):
        my_ary[:, -1] = np.arange(32)


class TestDeviceIngestor:
    def test_put_returns_device_arrays(self):
        import jax

        ing = DeviceIngestor()
        cols = (np.ones((4, 3), np.float32), np.zeros((4, 1), np.float32))
        a, b = ing.put(cols)
        assert isinstance(a, jax.Array)
        np.testing.assert_array_equal(np.asarray(a), cols[0])

    def test_sharded_put(self):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        sharding = NamedSharding(mesh, P("dp"))
        ing = DeviceIngestor(sharding=sharding)
        (a,) = ing.put((np.ones((16, 3), np.float32),))
        assert a.sharding == sharding
        assert len(a.addressable_shards) == len(jax.devices())


class TestPrefetchIterator:
    def test_order_and_exhaustion(self):
        batches = [(np.full((2, 2), i, np.float32),) for i in range(7)]
        out = list(PrefetchIterator(iter(batches), DeviceIngestor(), depth=3))
        assert len(out) == 7
        for i, (a,) in enumerate(out):
            assert float(np.asarray(a)[0, 0]) == i

    def test_empty_iterator(self):
        assert list(PrefetchIterator(iter([]), DeviceIngestor())) == []


class TestEarlyEpochEnd:
    def test_mid_window_epoch_end_resyncs(self):
        """Breaking an epoch early must not re-serve the stale window."""

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=8, connection=env.connection,
                n_epochs=2, output="numpy",
            )
            # Epoch 0: consume only 2 of 4 batches, then end the epoch.
            for i in range(2):
                loader[i]
                loader.mark(Marker.END_OF_BATCH)
            loader.mark(Marker.END_OF_EPOCH)
            # Epoch 1: a full drain must start at a fresh window boundary.
            count = 0
            for _ in loader:
                loader.mark(Marker.END_OF_BATCH)
                count += 1
            loader.mark(Marker.END_OF_EPOCH)
            assert loader._batches_in_window == 0
            return count

        assert main() == 4


class TestGlobalArray:
    def test_make_global_array_sharded(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ddl_tpu.ingest import make_global_array
        from ddl_tpu.parallel import data_parallel_mesh

        mesh = data_parallel_mesh()
        sharding = NamedSharding(mesh, P("dp"))
        batch = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
        g = make_global_array(batch, sharding)
        assert g.shape == (16, 3)
        assert len(g.addressable_shards) == len(jax.devices())
        np.testing.assert_array_equal(np.asarray(g), batch)


class TestLoaderShardedIngest:
    def test_loader_jax_output_with_sharding(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ddl_tpu import (
            DistributedDataLoader,
            Marker,
            distributed_dataloader,
        )
        from ddl_tpu.parallel import data_parallel_mesh

        mesh = data_parallel_mesh()
        sharding = NamedSharding(mesh, P("dp"))

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=32, connection=env.connection,
                n_epochs=1, output="jax", sharding=sharding,
            )
            feats, tag = loader[0]
            assert feats.sharding == sharding
            assert len(feats.addressable_shards) == len(jax.devices())
            loader.mark(Marker.END_OF_BATCH)
            loader.mark(Marker.END_OF_EPOCH)

        main()


class TestNorthStarReport:
    def test_report_keys(self):
        from ddl_tpu.ingest import north_star_report
        from ddl_tpu.observability import Metrics

        m = Metrics()
        m.incr("consumer.samples", 100)
        m.add_time("consumer.wait", 0.1)
        r = north_star_report(m)
        assert set(r) == {
            "samples_per_sec", "stall_fraction", "ingest_bytes_per_sec",
            "windows", "elapsed_s",
        }
        assert r["samples_per_sec"] > 0


class TestLoaderPrefetch:
    """loader.prefetch(): lookahead device iteration (VERDICT r2 item 5)."""

    def test_prefetch_matches_plain_iteration(self):
        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=8, connection=env.connection,
                n_epochs=4, output="jax",
            )
            plain_epochs, pf_epochs = [], []
            for epoch in range(4):
                use_pf = epoch % 2 == 1
                it = loader.prefetch(2) if use_pf else loader
                got = [np.asarray(y).ravel().tolist() for _, y in it]
                (pf_epochs if use_pf else plain_epochs).append(got)
                for _ in got:
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
            return plain_epochs, pf_epochs

        plain, pf = main()
        # Same producers, deterministic windows: prefetch epochs must see
        # exactly the same batches plain epochs saw (4 batches of 8 rows).
        assert plain == pf, (plain, pf)
        assert all(len(ep) == 4 for ep in plain + pf)

    def test_prefetch_requires_jax_output(self):
        import pytest

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=8, connection=env.connection,
                n_epochs=1, output="numpy",
            )
            with pytest.raises(RuntimeError, match="prefetch"):
                loader.prefetch()
            for _ in loader:
                loader.mark(Marker.END_OF_BATCH)
            loader.mark(Marker.END_OF_EPOCH)

        main()
