"""Ingest path tests: DeviceIngestor, PrefetchIterator, epoch resync."""

import os

import numpy as np
import pytest

from ddl_tpu import (
    DataProducerOnInitReturn,
    DistributedDataLoader,
    Marker,
    ProducerFunctionSkeleton,
    distributed_dataloader,
)
from ddl_tpu.ingest import DeviceIngestor, PrefetchIterator


class SeqProducer(ProducerFunctionSkeleton):
    def on_init(self, producer_idx=0, **kw):
        return DataProducerOnInitReturn(
            nData=32, nValues=4, shape=(32, 4), splits=(3, 1)
        )

    def post_init(self, my_ary, **kw):
        my_ary[:, -1] = np.arange(32)


class InplaceSeqProducer(ProducerFunctionSkeleton):
    """Module-level (picklable for PROCESS mode), zero-copy slot fill."""

    inplace_fill = True

    def on_init(self, producer_idx=0, **kw):
        self.iteration = 0
        return DataProducerOnInitReturn(
            nData=32, nValues=4, shape=(32, 4), splits=(3, 1)
        )

    def post_init(self, my_ary, **kw):
        my_ary[:] = 0.0

    def execute_function(self, my_ary, **kw):
        self.iteration += 1
        my_ary[:] = self.iteration * 100.0


class TaggedWindowProducer(ProducerFunctionSkeleton):
    """Each window uniformly tagged producer_idx*1000 + iteration."""

    inplace_fill = True

    def on_init(self, producer_idx=0, **kw):
        self.idx = producer_idx
        self.iteration = 0
        return DataProducerOnInitReturn(
            nData=32, nValues=4, shape=(32, 4), splits=(3, 1)
        )

    def post_init(self, my_ary, **kw):
        my_ary[:] = self.idx * 1000

    def execute_function(self, my_ary, **kw):
        self.iteration += 1
        my_ary[:] = self.idx * 1000 + self.iteration


class TestDeviceIngestor:
    def test_put_returns_device_arrays(self):
        import jax

        ing = DeviceIngestor()
        cols = (np.ones((4, 3), np.float32), np.zeros((4, 1), np.float32))
        a, b = ing.put(cols)
        assert isinstance(a, jax.Array)
        np.testing.assert_array_equal(np.asarray(a), cols[0])

    def test_sharded_put(self):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        sharding = NamedSharding(mesh, P("dp"))
        ing = DeviceIngestor(sharding=sharding)
        (a,) = ing.put((np.ones((16, 3), np.float32),))
        assert a.sharding == sharding
        assert len(a.addressable_shards) == len(jax.devices())


class TestPrefetchIterator:
    def test_order_and_exhaustion(self):
        batches = [(np.full((2, 2), i, np.float32),) for i in range(7)]
        out = list(PrefetchIterator(iter(batches), DeviceIngestor(), depth=3))
        assert len(out) == 7
        for i, (a,) in enumerate(out):
            assert float(np.asarray(a)[0, 0]) == i

    def test_empty_iterator(self):
        assert list(PrefetchIterator(iter([]), DeviceIngestor())) == []


class TestEarlyEpochEnd:
    def test_mid_window_epoch_end_resyncs(self):
        """Breaking an epoch early must not re-serve the stale window."""

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=8, connection=env.connection,
                n_epochs=2, output="numpy",
            )
            # Epoch 0: consume only 2 of 4 batches, then end the epoch.
            for i in range(2):
                loader[i]
                loader.mark(Marker.END_OF_BATCH)
            loader.mark(Marker.END_OF_EPOCH)
            # Epoch 1: a full drain must start at a fresh window boundary.
            count = 0
            for _ in loader:
                loader.mark(Marker.END_OF_BATCH)
                count += 1
            loader.mark(Marker.END_OF_EPOCH)
            assert loader._batches_in_window == 0
            return count

        assert main() == 4


class TestGlobalArray:
    def test_make_global_array_sharded(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ddl_tpu.ingest import make_global_array
        from ddl_tpu.parallel import data_parallel_mesh

        mesh = data_parallel_mesh()
        sharding = NamedSharding(mesh, P("dp"))
        batch = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
        g = make_global_array(batch, sharding)
        assert g.shape == (16, 3)
        assert len(g.addressable_shards) == len(jax.devices())
        np.testing.assert_array_equal(np.asarray(g), batch)


class TestLoaderShardedIngest:
    def test_loader_jax_output_with_sharding(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ddl_tpu import (
            DistributedDataLoader,
            Marker,
            distributed_dataloader,
        )
        from ddl_tpu.parallel import data_parallel_mesh

        mesh = data_parallel_mesh()
        sharding = NamedSharding(mesh, P("dp"))

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=32, connection=env.connection,
                n_epochs=1, output="jax", sharding=sharding,
            )
            feats, tag = loader[0]
            assert feats.sharding == sharding
            assert len(feats.addressable_shards) == len(jax.devices())
            loader.mark(Marker.END_OF_BATCH)
            loader.mark(Marker.END_OF_EPOCH)

        main()


class TestNorthStarReport:
    def test_report_keys(self):
        from ddl_tpu.ingest import north_star_report
        from ddl_tpu.observability import Metrics

        m = Metrics()
        m.incr("consumer.samples", 100)
        m.add_time("consumer.wait", 0.1)
        r = north_star_report(m)
        assert set(r) == {
            "samples_per_sec", "stall_fraction", "ingest_bytes_per_sec",
            "windows", "elapsed_s",
            # staged-ingest extras (ddl_tpu.staging)
            "stage_copy_s", "transfer_s", "stall_s",
            "pool_hits", "pool_misses", "queue_depth_max",
            "alias_windows", "alias_fallbacks",
            # robustness extras (ISSUE 3: watchdog + integrity + ladder)
            "respawns", "watchdog_failures", "corrupt_windows",
            "replays", "shuffle_degraded", "staging_retries",
            "inline_fallbacks",
            # shard-cache extras (ISSUE 4: ddl_tpu.cache tiers)
            "cache_hits", "cache_misses", "cache_evictions",
            "cache_spills", "cache_spill_hits", "cache_quarantined",
            "cache_resident_bytes", "cache_resident_bytes_max",
            # training hot-path extras (ISSUE 5: overlap health +
            # pipeline-schedule gauges)
            "window_wait_s", "release_wait_s", "pp_bubble", "pp_chunks",
            # ICI ingest tier extras (ISSUE 7: ddl_tpu/parallel/ici)
            "ici_bytes", "ici_windows", "ici_fallbacks",
            "ici_fanout_s", "ici_redistribute_s", "ici_peak_bytes",
            # fused compute/ingest step extras (ISSUE 12: overlap
            # proof + two-slot landing occupancy)
            "ingest_overlap_s", "fused_windows", "slots_in_flight",
            # distributed-optimizer extras (ISSUE 8:
            # ddl_tpu/parallel/optimizer)
            "opt_state_bytes_per_replica", "opt_state_bytes_total",
            "opt_grad_comm_bytes_raw", "opt_grad_comm_bytes_quantized",
            "opt_gather_s", "opt_scatter_s",
            # multi-host control plane extras (ISSUE 10:
            # ddl_tpu/cluster — membership churn + ladder actions)
            "view_changes", "host_losses", "host_rejoins",
            "heartbeats_dropped", "shard_adoptions",
            "cluster_cache_adoptions", "pool_updates",
            # multi-tenant ingest service extras (ISSUE 11:
            # ddl_tpu.serve — admission + autoscaler)
            "serve_tenants", "serve_scale_ups", "serve_scale_downs",
            "serve_admission_waits_s", "serve_tenant_stall",
            # data-plane wire format extras (ISSUE 13: ddl_tpu.wire —
            # honest encoded/raw byte pair + ladder counters)
            "wire_encoded_bytes", "wire_payload_bytes",
            "wire_decoded_windows", "wire_decode_fails",
            "wire_fallbacks",
            # preemption tolerance extras (ISSUE 14: ddl_tpu.resilience
            # — notice/drain events, async-checkpoint stall split,
            # restore-ladder health, serve-plane revocations)
            "resilience_notices", "resilience_drains",
            "resilience_drain_s", "resilience_ckpts",
            "resilience_final_ckpts", "resilience_ckpt_submit_s",
            "resilience_ckpt_write_s", "resilience_ckpt_quarantined",
            "resilience_ckpt_cold_starts", "serve_revocations",
            # end-to-end tracing extras (ISSUE 15: ddl_tpu.obs —
            # histogram percentiles, per-stage breakdown,
            # cross-process aggregation + flight-recorder health)
            "window_latency_p50", "window_latency_p99",
            "admission_wait_p99", "serve_tenant_admission_p99",
            "stage_breakdown", "obs_reports_applied",
            "obs_reports_stale", "obs_flight_dumps",
            # self-tuning extras (ISSUE 20: ddl_tpu/tune —
            # calibration/controller decision counts + provenance)
            "tune_decisions", "tune_reverts", "tune_cost_source",
        }
        assert r["samples_per_sec"] > 0
        # The per-tenant stall block is a DICT keyed by tenant name
        # (empty when no tenancy ran), not a flat float.
        assert isinstance(r["serve_tenant_stall"], dict)
        # So are the per-tenant admission p99s and the stage breakdown.
        assert isinstance(r["serve_tenant_admission_p99"], dict)
        assert isinstance(r["stage_breakdown"], dict)
        assert "acquire_wait" in r["stage_breakdown"]

    def test_report_serve_block_reflects_tenancy(self):
        """The serve_* keys chart real scheduler/autoscaler activity."""
        from ddl_tpu.ingest import north_star_report
        from ddl_tpu.observability import Metrics
        from ddl_tpu.serve import AdmissionController, TenantSpec

        m = Metrics()
        m.incr("consumer.samples", 1)
        ctl = AdmissionController(metrics=m)
        a = ctl.register(TenantSpec("alpha"))
        a.admit(1.0)
        a.note_served(4096)
        m.incr("serve.scale_ups")
        ctl.report()  # refreshes the serve.stall.<tenant> gauges
        r = north_star_report(m)
        assert r["serve_tenants"] == 1
        assert r["serve_scale_ups"] == 1
        assert r["serve_admission_waits_s"] >= 0
        # Keyed by tenant NAME only: set_gauge's ".max" companions are
        # filtered, or consumers would see a phantom tenant "alpha.max".
        assert set(r["serve_tenant_stall"]) == {"alpha"}


class TestFusedGatedRelease:
    """``gate_release_on``: the fused-step protocol's loader half —
    ring-slot release gated on the CONSUMING step's done-future, not
    the bare transfer (ISSUE 12).  Exercised with a controllable fake
    future and the accelerator-style inline path forced (the CPU
    client's detached source releases at yield, where gating is a
    documented no-op)."""

    class _Future:
        """Duck-typed device future: non-blocking ``is_ready`` probe +
        a ``block_until_ready`` the forced flush path may call."""

        def __init__(self):
            self.ready = False
            self.forced = False

        def is_ready(self):
            return self.ready

        def block_until_ready(self):
            self.forced = True
            self.ready = True
            return self

    def _run(self, body):
        from ddl_tpu.observability import Metrics

        m = Metrics()

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=32, connection=env.connection,
                n_epochs=3, output="jax", metrics=m,
            )
            # Force the accelerator-style inline discipline: treat the
            # transfer as sourcing the ring slot, so releases ride the
            # probe-gated backlog instead of happening at yield.
            loader._ingestor.window_source_detached = lambda: False
            try:
                return body(loader, m)
            finally:
                loader.shutdown()

        return main()

    def test_release_waits_for_consuming_step(self):
        def body(loader, m):
            ring = loader.connection.rings[0]
            stream = loader.windows(lookahead=0)
            fut = self._Future()
            next(stream)
            assert len(loader._release_backlog) == 1
            loader.gate_release_on(fut)
            assert m.counter("ingest.fused_gated") == 1
            # The transfer itself is long done (CPU), but the consuming
            # step is not: the sweep at the next acquire must NOT free
            # the slot.
            next(stream)
            assert ring.stats()["released"] == 0
            assert len(loader._release_backlog) >= 1
            # Step completes -> the very next sweep frees the slot.
            fut.ready = True
            next(stream)
            assert ring.stats()["released"] >= 1
            assert not fut.forced  # released by the probe, not a flush

        self._run(body)

    def test_pending_step_future_cannot_deadlock(self):
        """A gated slot with its step future still pending when the
        ring runs dry: the forced flush block_until_ready's the
        COMBINED (transfer, step) future — the stream keeps moving and
        shutdown drains everything; the protocol can never strand a
        slot."""

        def body(loader, m):
            ring = loader.connection.rings[0]
            stream = loader.windows(lookahead=0)
            fut = self._Future()
            next(stream)
            loader.gate_release_on(fut)
            # Drain the remaining windows WITHOUT ever resolving the
            # future ourselves: the ring (nslots=2) exhausts and the
            # stream's forced flush must wait out the step future.
            for _ in stream:
                pass
            assert fut.forced  # the flush waited on the step, not a spin
            assert ring.stats()["released"] >= 1
            # Teardown drains the remaining backlog: every acquired
            # slot comes back, nothing stranded (idempotent with the
            # harness's own shutdown).
            loader.shutdown()
            assert ring.stats()["released"] == 3
            assert not loader._release_backlog

        self._run(body)

    def test_gate_is_noop_when_slot_released_at_yield(self):
        """On the CPU client (detached source) the slot is back with
        the producer at yield — gating must be a harmless no-op, so a
        fused trainer runs unchanged on any client."""
        from ddl_tpu.observability import Metrics

        m = Metrics()

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=32, connection=env.connection,
                n_epochs=2, output="jax", metrics=m,
            )
            stream = loader.windows()
            next(stream)
            loader.gate_release_on(self._Future())
            assert m.counter("ingest.fused_gated") == 0
            assert loader._last_stream_entry is None
            loader.shutdown()

        main()


class TestLoaderPrefetch:
    """loader.prefetch(): lookahead device iteration (VERDICT r2 item 5)."""

    def test_prefetch_matches_plain_iteration(self):
        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=8, connection=env.connection,
                n_epochs=4, output="jax",
            )
            plain_epochs, pf_epochs = [], []
            for epoch in range(4):
                use_pf = epoch % 2 == 1
                it = loader.prefetch(2) if use_pf else loader
                got = [np.asarray(y).ravel().tolist() for _, y in it]
                (pf_epochs if use_pf else plain_epochs).append(got)
                for _ in got:
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
            return plain_epochs, pf_epochs

        plain, pf = main()
        # Same producers, deterministic windows: prefetch epochs must see
        # exactly the same batches plain epochs saw (4 batches of 8 rows).
        assert plain == pf, (plain, pf)
        assert all(len(ep) == 4 for ep in plain + pf)

    def test_windows_streaming(self):
        """windows(): whole-window zero-copy streaming, content + rotation
        + epoch accounting match per-batch iteration semantics."""

        class CountingProducer(ProducerFunctionSkeleton):
            inplace_fill = True

            def on_init(self, producer_idx=0, **kw):
                self.idx = producer_idx
                self.iteration = 0
                return DataProducerOnInitReturn(
                    nData=32, nValues=4, shape=(32, 4), splits=(3, 1)
                )

            def post_init(self, my_ary, **kw):
                my_ary[:] = self.idx * 1000

            def execute_function(self, my_ary, **kw):
                # inplace_fill contract: fully rewrite the window.
                self.iteration += 1
                my_ary[:] = self.idx * 1000 + self.iteration

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                CountingProducer(), batch_size=8, connection=env.connection,
                n_epochs=6, output="jax",
            )
            tags = []
            for win in loader.windows():
                assert win.shape == (4, 8, 4)  # (bpw, batch, values)
                vals = np.unique(np.asarray(win))
                assert len(vals) == 1  # each window uniform by design
                tags.append(float(vals[0]))
                loader.mark(Marker.END_OF_EPOCH)
            assert loader.epoch == 6
            return tags

        tags = main()
        # Round-robin producers (1-based idx, like the reference's shm
        # ranks), each window freshly rewritten in place: producer 1
        # serves 1001,1002,..., producer 2 serves 2001,2002,...
        assert tags == [
            1001.0, 2001.0, 1002.0, 2002.0, 1003.0, 2003.0,
        ], tags

    def test_windows_bytes_counted_at_completion(self):
        """Stream byte accounting lands at transfer COMPLETION (finish),
        not dispatch: across a mid-stream registry reset — exactly what
        the bench's steady-state window does — ingest.bytes and
        consumer.samples must cover identical windows, so their ratio is
        exactly bytes-per-sample.  Dispatch-time accounting would lose
        the lookahead window in flight at the reset (VERDICT r4 Weak #3)."""
        from ddl_tpu.observability import Metrics

        metrics = Metrics()

        @distributed_dataloader(n_producers=2, mode="thread", nslots=2)
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=8, connection=env.connection,
                n_epochs=8, output="jax", metrics=metrics,
            )
            for seen, win in enumerate(loader.windows()):
                if seen == 2:
                    metrics.reset()  # steady-state span, lookahead in flight
                loader.mark(Marker.END_OF_EPOCH)
            return metrics.counter("ingest.bytes"), metrics.counter(
                "consumer.samples"
            ), metrics.counter("ingest.windows")

        nbytes, samples, windows = main()
        bytes_per_sample = 4 * 4  # SeqProducer: 4 f32 values per row
        assert samples > 0 and windows > 0
        assert nbytes == samples * bytes_per_sample, (nbytes, samples)

    def test_windows_double_buffer_holds_two_slots(self):
        """Double-buffered streaming (VERDICT r3 item 3): before window k
        is yielded, window k+1 must already be acquired — a recording
        proxy over the single producer's ring observes TWO concurrently
        held slots, and the lookahead acquisition precedes the previous
        slot's release.  Runs INLINE (staged=False): early slot release
        is the staged engine's whole point and deliberately breaks the
        held-until-transfer-complete property asserted here; the staged
        counterpart lives in tests/test_staging.py."""
        import time

        class RecordingRing:
            def __init__(self, inner):
                self._inner = inner
                self.events = []
                self.held = 0
                self.max_held = 0

            def acquire_drain_ahead(self, ahead, timeout_s=300.0):
                slot = self._inner.acquire_drain_ahead(ahead, timeout_s)
                self.held += 1
                self.max_held = max(self.max_held, self.held)
                self.events.append(("acquire", slot, ahead))
                return slot

            def acquire_drain(self, timeout_s=300.0):
                return self.acquire_drain_ahead(0, timeout_s)

            def release(self, slot):
                self.held -= 1
                self.events.append(("release", slot))
                self._inner.release(slot)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        @distributed_dataloader(n_producers=1, mode="thread", nslots=2)
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=8, connection=env.connection,
                n_epochs=4, output="jax", staged=False,
            )
            rec = RecordingRing(env.connection.rings[0])
            env.connection.rings[0] = rec
            # Let the producer run ahead so the non-blocking lookahead
            # try-acquire deterministically finds window k+1 committed.
            deadline = time.time() + 10
            while rec.stats()["committed"] < 2 and time.time() < deadline:
                time.sleep(0.01)
            n = 0
            for win in loader.windows():
                assert win.shape == (4, 8, 4)
                n += 1
                loader.mark(Marker.END_OF_EPOCH)
            assert n == 4
            return rec

        rec = main()
        assert rec.max_held == 2, rec.events
        first_release = rec.events.index(("release", 0))
        lookaheads = [
            i for i, e in enumerate(rec.events)
            if e[0] == "acquire" and e[2] == 1
        ]
        assert lookaheads and lookaheads[0] < first_release, rec.events

    def test_windows_break_resumes_at_next_unserved(self):
        """Abandoning the stream with a lookahead window in flight must
        not lose data: acquisition has no ring side effect, so a resumed
        stream serves exactly the next unserved window (code-review
        finding on the double-buffer change)."""

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                TaggedWindowProducer(), batch_size=8,
                connection=env.connection, n_epochs=6, output="jax",
            )
            tags = []
            for win in loader.windows():
                tags.append(float(np.unique(np.asarray(win))[0]))
                loader.mark(Marker.END_OF_EPOCH)
                if len(tags) == 2:
                    break  # abandon mid-stream, lookahead likely held
            for win in loader.windows():
                tags.append(float(np.unique(np.asarray(win))[0]))
                loader.mark(Marker.END_OF_EPOCH)
            return tags

        tags = main()
        assert tags == [
            1001.0, 2001.0, 1002.0, 2002.0, 1003.0, 2003.0,
        ], tags

    def test_windows_stale_generator_finalize_harmless(self):
        """A dead generator finalized LATE — after a new stream started —
        must not corrupt the live rotation (review finding: an earlier
        version rewound shared loader state in the generator's finally,
        which fires at GC time, not at abandonment time)."""

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                TaggedWindowProducer(), batch_size=8,
                connection=env.connection, n_epochs=6, output="jax",
            )
            it1 = loader.windows()
            tags = [float(np.unique(np.asarray(next(it1)))[0])]
            loader.mark(Marker.END_OF_EPOCH)
            it2 = loader.windows()  # it1 abandoned but still referenced
            tags.append(float(np.unique(np.asarray(next(it2)))[0]))
            loader.mark(Marker.END_OF_EPOCH)
            it1.close()  # stale generator finalizes only NOW
            for win in it2:
                tags.append(float(np.unique(np.asarray(win))[0]))
                loader.mark(Marker.END_OF_EPOCH)
            return tags

        tags = main()
        assert tags == [
            1001.0, 2001.0, 1002.0, 2002.0, 1003.0, 2003.0,
        ], tags

    def test_windows_concurrent_streams_rejected(self):
        """Interleaving two live windows() streams would double-release
        ring slots (review finding): the superseded stream must raise,
        not corrupt the counters."""
        import pytest

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                TaggedWindowProducer(), batch_size=8,
                connection=env.connection, n_epochs=6, output="jax",
            )
            it1 = loader.windows()
            next(it1)
            loader.mark(Marker.END_OF_EPOCH)
            it2 = loader.windows()
            next(it2)  # supersedes it1
            loader.mark(Marker.END_OF_EPOCH)
            with pytest.raises(RuntimeError, match="superseded"):
                next(it1)
            # The live stream keeps working.
            next(it2)
            loader.mark(Marker.END_OF_EPOCH)
            loader.shutdown()

        main()

    def test_windows_deep_lookahead(self):
        """lookahead > 1 genuinely deepens the pipeline (not capped at
        one): with nslots=4 and lookahead=3 the consumer holds more than
        two slots at once mid-stream.  Inline mode (staged=False): the
        staged engine releases slots at copy-completion, so held-count
        depth is asserted on the path that holds slots for the whole
        transfer."""
        import time

        class HeldCounter:
            def __init__(self, inner):
                self._inner = inner
                self.held = 0
                self.max_held = 0

            def acquire_drain_ahead(self, ahead, timeout_s=300.0):
                slot = self._inner.acquire_drain_ahead(ahead, timeout_s)
                self.held += 1
                self.max_held = max(self.max_held, self.held)
                return slot

            def acquire_drain(self, timeout_s=300.0):
                return self.acquire_drain_ahead(0, timeout_s)

            def release(self, slot):
                self.held -= 1
                self._inner.release(slot)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        @distributed_dataloader(n_producers=1, mode="thread", nslots=4)
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=8, connection=env.connection,
                n_epochs=8, output="jax", staged=False,
            )
            rec = HeldCounter(env.connection.rings[0])
            env.connection.rings[0] = rec
            deadline = time.time() + 10
            while rec.stats()["committed"] < 4 and time.time() < deadline:
                time.sleep(0.01)
            n = 0
            for win in loader.windows(lookahead=3):
                n += 1
                loader.mark(Marker.END_OF_EPOCH)
            assert n == 8
            return rec

        rec = main()
        assert rec.max_held >= 3, rec.max_held

    def test_windows_ragged_tail_unserved(self):
        """nData not a batch multiple: windows() serves the same batches
        the per-batch path serves, dropping the ragged tail rows."""

        class RaggedProducer(ProducerFunctionSkeleton):
            def on_init(self, producer_idx=0, **kw):
                return DataProducerOnInitReturn(
                    nData=33, nValues=4, shape=(33, 4), splits=(3, 1)
                )

            def post_init(self, my_ary, **kw):
                my_ary[:, -1] = np.arange(33)

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                RaggedProducer(), batch_size=8, connection=env.connection,
                n_epochs=1, output="jax",
            )
            (win,) = list(loader.windows())
            loader.mark(Marker.END_OF_EPOCH)
            return np.asarray(win)

        win = main()
        assert win.shape == (4, 8, 4)
        np.testing.assert_array_equal(win[..., -1].ravel(), np.arange(32))

    def test_inplace_fill_rejects_global_shuffle(self):
        """Exchange on nslots-stale slots would be silently wrong data —
        the producer constructor must reject the combination."""
        import pytest

        from ddl_tpu.datapusher import DataPusher
        from ddl_tpu.exceptions import DoesNotMatchError
        from ddl_tpu.shuffle import ThreadExchangeShuffler
        from ddl_tpu.transport.connection import (
            ProducerConnection,
            ThreadChannel,
        )
        from ddl_tpu.types import (
            MetaData_Consumer_To_Producer,
            RunMode,
            Topology,
        )

        topo = Topology(
            n_instances=2, instance_idx=0, n_producers=1,
            mode=RunMode.THREAD,
        )
        cons_end, prod_end = ThreadChannel.pair()
        cons_end.send(
            MetaData_Consumer_To_Producer(
                data_producer_function=InplaceSeqProducer(), batch_size=8,
                n_epochs=1, global_shuffle_fraction_exchange=0.5,
                exchange_method="sendrecv_replace",
            )
        )
        with pytest.raises(DoesNotMatchError, match="inplace_fill"):
            DataPusher(
                ProducerConnection(prod_end, 1, cross_process=False),
                topo, 1,
                shuffler_factory=ThreadExchangeShuffler.factory(),
            )

    def test_windows_requires_jax_output(self):
        import pytest

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=8, connection=env.connection,
                n_epochs=1, output="numpy",
            )
            with pytest.raises(RuntimeError, match="windows"):
                next(loader.windows())
            for _ in loader:
                loader.mark(Marker.END_OF_BATCH)
            loader.mark(Marker.END_OF_EPOCH)

        main()

    def test_inplace_fill_process_mode(self):
        """inplace_fill writes land in shm ring slots across processes."""

        @distributed_dataloader(n_producers=1, mode="process")
        def main(env):
            loader = DistributedDataLoader(
                InplaceSeqProducer(), batch_size=8,
                connection=env.connection, n_epochs=2, output="numpy",
            )
            seen = []
            for _ in range(2):
                for x, y in loader:
                    seen.append(float(y[0, 0]))
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
            return seen

        seen = main()
        # Window 0: iteration 1 tags batches 1.x; window 1: iteration 2.
        assert seen == [100.0, 100.0, 100.0, 100.0,
                        200.0, 200.0, 200.0, 200.0], seen

    def test_prefetch_requires_jax_output(self):
        import pytest

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=8, connection=env.connection,
                n_epochs=1, output="numpy",
            )
            with pytest.raises(RuntimeError, match="prefetch"):
                loader.prefetch()
            for _ in loader:
                loader.mark(Marker.END_OF_BATCH)
            loader.mark(Marker.END_OF_EPOCH)

        main()


class TestDeferredSlotRelease:
    """Accelerator-shaped inline streams (the transfer sources the ring
    slot): slot release is gated on a transfer-completion probe instead
    of a per-window host ``block_until_ready`` (ISSUE 5 — the old sync
    serialized window k+1's H2D against window k's scanned steps).  The
    CPU client detaches sources in ``put_window``, so the attached path
    is exercised by pinning ``window_source_detached`` False — data
    stays correct either way (the alias-guard copy still runs)."""

    def _pin_attached(self, monkeypatch):
        from ddl_tpu.ingest import DeviceIngestor

        monkeypatch.setattr(
            DeviceIngestor, "window_source_detached", lambda self: False
        )

    def test_stream_correct_and_backlog_drained(self, monkeypatch):
        self._pin_attached(monkeypatch)

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                TaggedWindowProducer(), batch_size=8,
                connection=env.connection, n_epochs=6, output="jax",
                staged=False,
            )
            tags = []
            backlog_seen = 0
            for win in loader.windows():
                tags.append(float(np.unique(np.asarray(win))[0]))
                backlog_seen = max(
                    backlog_seen, len(loader._release_backlog)
                )
                loader.mark(Marker.END_OF_EPOCH)
            # The final mark shut the loader down: every deferred slot
            # must have been flushed back to its ring.
            return tags, backlog_seen, len(loader._release_backlog)

        tags, backlog_seen, backlog_left = main()
        assert tags == [
            1001.0, 2001.0, 1002.0, 2002.0, 1003.0, 2003.0,
        ], tags
        # The deferral actually engaged (at least one window released
        # via the probe path), and nothing leaked past shutdown.
        assert backlog_seen >= 1
        assert backlog_left == 0

    def test_break_then_new_stream_inherits_backlog(self, monkeypatch):
        """A new stream must account for the old stream's yielded-but-
        unreleased slots (they are still held on the ring) — the
        drain-lookahead bookkeeping starts from the backlog instead of
        re-acquiring served windows."""
        self._pin_attached(monkeypatch)

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                TaggedWindowProducer(), batch_size=8,
                connection=env.connection, n_epochs=6, output="jax",
                staged=False,
            )
            tags = []
            for win in loader.windows():
                tags.append(float(np.unique(np.asarray(win))[0]))
                loader.mark(Marker.END_OF_EPOCH)
                if len(tags) == 2:
                    break  # abandon with deferred releases pending
            for win in loader.windows():
                tags.append(float(np.unique(np.asarray(win))[0]))
                loader.mark(Marker.END_OF_EPOCH)
            return tags

        tags = main()
        assert tags == [
            1001.0, 2001.0, 1002.0, 2002.0, 1003.0, 2003.0,
        ], tags

    def test_batch_path_flushes_backlog(self, monkeypatch):
        """Switching from a stream to batch iteration flushes deferred
        releases first — the batch-path drain must not re-serve a slot
        the stream already yielded."""
        self._pin_attached(monkeypatch)

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                TaggedWindowProducer(), batch_size=8,
                connection=env.connection, n_epochs=3, output="jax",
                staged=False,
            )
            it = loader.windows()
            first = float(np.unique(np.asarray(next(it))))
            loader.mark(Marker.END_OF_EPOCH)
            # Batch-iterate the next epoch: backlog must flush, and the
            # window served is the next UNSERVED one.
            seen = []
            for cols in loader:
                seen.append(float(np.asarray(cols[0])[0, 0]))
                loader.mark(Marker.END_OF_BATCH)
            loader.mark(Marker.END_OF_EPOCH)
            assert len(loader._release_backlog) == 0
            loader.shutdown()
            return first, seen

        first, seen = main()
        assert first == 1001.0
        assert seen and all(v == 2001.0 for v in seen), seen


class AutoInplaceProducer(ProducerFunctionSkeleton):
    """Capability-advertising producer: every fill fully rewrites, so
    the pusher MAY hand it a live slot view (but must not when a global
    shuffle needs a persistent my_ary)."""

    supports_inplace_fill = True

    def on_init(self, producer_idx=0, **kw):
        self.iteration = 0
        return DataProducerOnInitReturn(
            nData=32, nValues=4, shape=(32, 4), splits=(3, 1)
        )

    def post_init(self, my_ary, **kw):
        my_ary[:] = 0.0

    def execute_function(self, my_ary, **kw):
        self.iteration += 1
        my_ary[:] = self.iteration


class TestAutoInplaceFill:
    """The extended inplace contract (write-once producers): a
    ``supports_inplace_fill`` producer fills live ring slots by default,
    degrades to the copying fill when a shuffler owns my_ary, and obeys
    the ``DDL_TPU_INPLACE`` escape hatch — which never overrides a
    producer that FORCES ``inplace_fill``."""

    def _pusher(self, producer, shuffle=0.0, n_instances=1, factory=None):
        from ddl_tpu.datapusher import DataPusher
        from ddl_tpu.transport.connection import (
            ProducerConnection,
            ThreadChannel,
        )
        from ddl_tpu.types import (
            MetaData_Consumer_To_Producer,
            RunMode,
            Topology,
        )

        topo = Topology(
            n_instances=n_instances, instance_idx=0, n_producers=1,
            mode=RunMode.THREAD,
        )
        cons_end, prod_end = ThreadChannel.pair()
        cons_end.send(
            MetaData_Consumer_To_Producer(
                data_producer_function=producer, batch_size=8,
                n_epochs=1, global_shuffle_fraction_exchange=shuffle,
                exchange_method="sendrecv_replace",
            )
        )
        return DataPusher(
            ProducerConnection(prod_end, 1, cross_process=False),
            topo, 1, shuffler_factory=factory,
        )

    def test_builtin_readers_advertise_capability(self):
        from ddl_tpu.readers import (
            ArrayProducer,
            FileShardProducer,
            TFRecordTokenProducer,
            TokenStreamProducer,
            WebDatasetProducer,
        )

        for cls in (
            ArrayProducer, FileShardProducer, WebDatasetProducer,
            TokenStreamProducer, TFRecordTokenProducer,
        ):
            assert cls.supports_inplace_fill is True
            assert cls.inplace_fill is False  # opt-in stays the pusher's

    def test_auto_inplace_gets_live_slot_view(self):
        p = self._pusher(AutoInplaceProducer())
        assert p.inplace_fill is True
        assert p._fill_slot is not None
        assert np.shares_memory(
            p.my_ary, p.ring.slot_view(p._fill_slot)
        )

    def test_env_escape_hatch_restores_copy_fill(self, monkeypatch):
        monkeypatch.setenv("DDL_TPU_INPLACE", "0")
        p = self._pusher(AutoInplaceProducer())
        assert p.inplace_fill is False
        assert not any(
            np.shares_memory(p.my_ary, p.ring.slot_view(s))
            for s in range(p.ring.nslots)
        )

    def test_env_escape_hatch_never_overrides_forced(self, monkeypatch):
        monkeypatch.setenv("DDL_TPU_INPLACE", "0")
        p = self._pusher(InplaceSeqProducer())
        assert p.inplace_fill is True  # forced = contract, not preference

    def test_auto_degrades_under_global_shuffle(self):
        """Unlike FORCED inplace (rejected — see
        test_inplace_fill_rejects_global_shuffle), a capability
        advertisement quietly keeps the private my_ary the exchange
        needs."""
        from ddl_tpu.shuffle import ThreadExchangeShuffler

        p = self._pusher(
            AutoInplaceProducer(), shuffle=0.5, n_instances=2,
            factory=ThreadExchangeShuffler.factory(),
        )
        assert p.shuffler is not None
        assert p.inplace_fill is False


class TestWriteOnceByteIdentity:
    """PROCESS inplace stream ≡ THREAD stream ≡ the old copying PROCESS
    path (``DDL_TPU_INPLACE=0``), cache-on and cache-off, for every
    built-in shard reader: the write-once refactor must change copy
    counts, never bytes."""

    def _drain(self, make_producer, mode, batch_size, n_epochs=3):
        @distributed_dataloader(n_producers=1, mode=mode)
        def main(env):
            loader = DistributedDataLoader(
                make_producer(), batch_size=batch_size,
                connection=env.connection, n_epochs=n_epochs,
                output="numpy",
            )
            out = []
            for _ in range(n_epochs):
                for cols in loader:
                    out.append(
                        np.hstack([np.asarray(c) for c in cols]).copy()
                    )
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
            return np.stack(out)

        return main()

    #: label -> (run mode, DDL_TPU_INPLACE, cache on).  The THREAD
    #: cache-off run is the reference stream.
    MATRIX = {
        "thread": ("thread", "1", False),
        "thread_cache": ("thread", "1", True),
        "process_inplace": ("process", "1", False),
        "process_inplace_cache": ("process", "1", True),
        "process_copy": ("process", "0", False),
        "process_copy_cache": ("process", "0", True),
    }

    def _assert_matrix_identical(
        self, make_producer, batch_size, monkeypatch, tmp_path
    ):
        runs = {}
        for label, (mode, inplace, cache_on) in self.MATRIX.items():
            monkeypatch.setenv("DDL_TPU_INPLACE", inplace)
            if cache_on:
                monkeypatch.setenv("DDL_TPU_CACHE", "1")
                monkeypatch.setenv(
                    "DDL_TPU_CACHE_SPILL_DIR",
                    str(tmp_path / f"spill_{label}"),
                )
            else:
                monkeypatch.delenv("DDL_TPU_CACHE", raising=False)
                monkeypatch.delenv(
                    "DDL_TPU_CACHE_SPILL_DIR", raising=False
                )
            runs[label] = self._drain(make_producer, mode, batch_size)
        ref = runs["thread"]
        for label, got in runs.items():
            np.testing.assert_array_equal(
                got, ref,
                err_msg=f"{label} stream diverged from the THREAD "
                "cache-off reference",
            )

    def test_fileshard_matrix(self, tmp_path, monkeypatch):
        rng = np.random.default_rng(0)
        for i in range(2):
            np.save(
                tmp_path / f"shard_{i}.npy",
                rng.standard_normal((8, 6)).astype(np.float32),
            )
        pattern = str(tmp_path / "shard_*.npy")

        from ddl_tpu.readers import FileShardProducer

        self._assert_matrix_identical(
            lambda: FileShardProducer(pattern, seed=0, warm=False),
            batch_size=4, monkeypatch=monkeypatch, tmp_path=tmp_path,
        )

    def test_tfrecord_matrix(self, tmp_path, monkeypatch):
        import sys

        sys.path.insert(0, os.path.dirname(__file__))
        from datagen import encode_example_int64, write_tfrecord

        payloads = [
            encode_example_int64(
                "input_ids", list(range(20 * i, 20 * i + 20))
            )
            for i in range(4)
        ]
        path = str(tmp_path / "toks.tfrecord")
        write_tfrecord(path, payloads)

        from ddl_tpu.readers import TFRecordTokenProducer

        self._assert_matrix_identical(
            lambda: TFRecordTokenProducer(
                str(tmp_path / "toks.tfrecord"), seq_len=8,
                window_rows=4, warm=False,
            ),
            batch_size=4, monkeypatch=monkeypatch, tmp_path=tmp_path,
        )

    def test_webdataset_matrix(self, tmp_path, monkeypatch):
        pytest.importorskip("PIL")
        import sys

        sys.path.insert(0, os.path.dirname(__file__))
        from datagen import write_image_shard

        write_image_shard(
            str(tmp_path / "imgs.tar"),
            [(f"s{i:03d}", i % 3) for i in range(4)],
            size=8,
        )

        from ddl_tpu.readers import WebDatasetProducer

        self._assert_matrix_identical(
            lambda: WebDatasetProducer(
                str(tmp_path / "imgs.tar"), image_size=8,
                window_rows=4, warm=False,
            ),
            batch_size=4, monkeypatch=monkeypatch, tmp_path=tmp_path,
        )
