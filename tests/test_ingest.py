"""Ingest path tests: DeviceIngestor, PrefetchIterator, epoch resync."""

import numpy as np

from ddl_tpu import (
    DataProducerOnInitReturn,
    DistributedDataLoader,
    Marker,
    ProducerFunctionSkeleton,
    distributed_dataloader,
)
from ddl_tpu.ingest import DeviceIngestor, PrefetchIterator


class SeqProducer(ProducerFunctionSkeleton):
    def on_init(self, producer_idx=0, **kw):
        return DataProducerOnInitReturn(
            nData=32, nValues=4, shape=(32, 4), splits=(3, 1)
        )

    def post_init(self, my_ary, **kw):
        my_ary[:, -1] = np.arange(32)


class TestDeviceIngestor:
    def test_put_returns_device_arrays(self):
        import jax

        ing = DeviceIngestor()
        cols = (np.ones((4, 3), np.float32), np.zeros((4, 1), np.float32))
        a, b = ing.put(cols)
        assert isinstance(a, jax.Array)
        np.testing.assert_array_equal(np.asarray(a), cols[0])

    def test_sharded_put(self):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        sharding = NamedSharding(mesh, P("dp"))
        ing = DeviceIngestor(sharding=sharding)
        (a,) = ing.put((np.ones((16, 3), np.float32),))
        assert a.sharding == sharding
        assert len(a.addressable_shards) == len(jax.devices())


class TestPrefetchIterator:
    def test_order_and_exhaustion(self):
        batches = [(np.full((2, 2), i, np.float32),) for i in range(7)]
        out = list(PrefetchIterator(iter(batches), DeviceIngestor(), depth=3))
        assert len(out) == 7
        for i, (a,) in enumerate(out):
            assert float(np.asarray(a)[0, 0]) == i

    def test_empty_iterator(self):
        assert list(PrefetchIterator(iter([]), DeviceIngestor())) == []


class TestEarlyEpochEnd:
    def test_mid_window_epoch_end_resyncs(self):
        """Breaking an epoch early must not re-serve the stale window."""

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                SeqProducer(), batch_size=8, connection=env.connection,
                n_epochs=2, output="numpy",
            )
            # Epoch 0: consume only 2 of 4 batches, then end the epoch.
            for i in range(2):
                loader[i]
                loader.mark(Marker.END_OF_BATCH)
            loader.mark(Marker.END_OF_EPOCH)
            # Epoch 1: a full drain must start at a fresh window boundary.
            count = 0
            for _ in loader:
                loader.mark(Marker.END_OF_BATCH)
                count += 1
            loader.mark(Marker.END_OF_EPOCH)
            assert loader._batches_in_window == 0
            return count

        assert main() == 4
