"""Chaos suite: seeded fault injection against full loader pipelines.

Tier-1 runs a DETERMINISTIC single-fault matrix — for every fault kind
the pipeline must either recover with byte-identical, exactly-once
delivery of the window stream, or degrade along the documented ladder
(docs/ROBUSTNESS.md).  It must never deadlock, and never silently drop
or duplicate a window.  ``@pytest.mark.slow`` adds a randomized
multi-fault soak (``make chaos``).

The producer serves a fully deterministic pattern (window ``it`` has
every element derived from ``(producer, it, position)``), so "recovered"
is asserted at byte granularity, not just by count.
"""

import os
import time

import numpy as np
import pytest

from ddl_tpu import (
    DataProducerOnInitReturn,
    DistributedDataLoader,
    Marker,
    ProducerFunctionSkeleton,
    distributed_dataloader,
)
from ddl_tpu import faults, integrity
from ddl_tpu.exceptions import (
    IntegrityError,
    InjectedFault,
    ShutdownRequested,
    TransportError,
)
from ddl_tpu.faults import FaultKind, FaultPlan, FaultSpec, fault_point
from ddl_tpu.observability import Metrics, metrics as default_metrics
from ddl_tpu.watchdog import Watchdog

N_DATA, N_VALUES = 16, 4
SHAPE = (N_DATA, N_VALUES)


def pattern(it: int, producer_idx: int = 1) -> np.ndarray:
    """Byte-deterministic content of window ``it`` (1-based)."""
    base = producer_idx * 100_000 + it * 1_000
    return (
        base + (np.arange(N_DATA * N_VALUES, dtype=np.float32) % 997)
    ).reshape(SHAPE).astype(np.float32)


class PatternProducer(ProducerFunctionSkeleton):
    """Windows 1, 2, 3, ... of :func:`pattern` — replayable by the default
    ``fast_forward`` (state advances only through ``execute_function``)."""

    def on_init(self, producer_idx=1, **kw):
        self.idx = producer_idx
        self.it = 0
        return DataProducerOnInitReturn(
            nData=N_DATA, nValues=N_VALUES, shape=SHAPE,
            splits=(N_VALUES - 1, 1),
        )

    def post_init(self, my_ary, **kw):
        my_ary[:] = 0.0

    def execute_function(self, my_ary, **kw):
        self.it += 1
        my_ary[:] = pattern(self.it, self.idx)


class InplacePatternProducer(PatternProducer):
    """The same deterministic pattern stream, FORCED write-once: every
    fill lands straight in the live ring slot (module-level so PROCESS
    chaos tests can pickle it across the spawn boundary)."""

    inplace_fill = True


def drain_numpy(plan, n_epochs=6, metrics=None, stall_budget_s=60.0,
                producer_cls=PatternProducer):
    """Run a 1-producer THREAD pipeline under ``plan``; return the window
    arrays served, the watchdog, and the metrics registry."""
    m = metrics or Metrics()

    @distributed_dataloader(n_producers=1, mode="thread")
    def main(env):
        wd = Watchdog(
            env.workers, poll_interval_s=0.1, stall_budget_s=stall_budget_s,
            respawn=True, metrics=m,
        ).start()
        try:
            loader = DistributedDataLoader(
                producer_cls(), batch_size=N_DATA,
                connection=env.connection, n_epochs=n_epochs,
                output="numpy", timeout_s=60.0, metrics=m,
            )
            windows = []
            for _ in range(n_epochs):
                for cols in loader:
                    windows.append(np.hstack([np.asarray(c) for c in cols]))
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
        finally:
            wd.stop()
        return windows, wd

    with faults.armed(plan):
        windows, wd = main()
    return windows, wd, m


def drain_windows_jax(plan, n_epochs=5, metrics=None):
    """Run the staged ``windows()`` stream (engine forced on) under
    ``plan``; return served window arrays, the metrics, and the loader's
    engine-faulted flag."""
    m = metrics or Metrics()

    @distributed_dataloader(n_producers=1, mode="thread")
    def main(env):
        loader = DistributedDataLoader(
            PatternProducer(), batch_size=N_DATA,
            connection=env.connection, n_epochs=n_epochs, output="jax",
            timeout_s=60.0, metrics=m, staged=True,
        )
        windows = []
        for win in loader.windows():
            windows.append(np.asarray(win).reshape(SHAPE).copy())
            loader.mark(Marker.END_OF_EPOCH)
        engine = loader._ingestor._engine
        return windows, bool(engine is not None and engine.faulted)

    with faults.armed(plan):
        windows, faulted = main()
    return windows, faulted, m


def expected(n_epochs):
    return [pattern(it) for it in range(1, n_epochs + 1)]


def assert_byte_identical(got, n_epochs):
    want = expected(n_epochs)
    assert len(got) == len(want), (len(got), len(want))
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_array_equal(g, w, err_msg=f"window {i + 1}")


# ---------------------------------------------------------------------------
# The deterministic single-fault matrix (tier-1).  One test per fault
# kind; each asserts exactly-once byte-identical delivery or the
# documented degradation — never a deadlock, drop, or duplicate.
# ---------------------------------------------------------------------------


class TestFaultMatrix:
    def test_producer_crash_respawned_byte_identical(self):
        plan = FaultPlan(
            [FaultSpec("producer.fill", FaultKind.PRODUCER_CRASH, at=3)]
        )
        windows, wd, m = drain_numpy(plan)
        assert_byte_identical(windows, 6)
        assert list(wd.respawns) == [1]
        assert list(wd.failures) == []
        assert m.counter("watchdog.respawns") == 1
        assert plan.fired and plan.fired[0][1] == "producer_crash"

    def test_producer_slowdown_recovers_unassisted(self):
        plan = FaultPlan(
            [FaultSpec("producer.fill", FaultKind.PRODUCER_SLOWDOWN,
                       at=2, count=2, param=0.3)]
        )
        windows, wd, m = drain_numpy(plan)
        assert_byte_identical(windows, 6)
        assert list(wd.respawns) == []
        assert list(wd.failures) == []
        assert len(plan.fired) == 2

    def test_spurious_shutdown_respawned_byte_identical(self):
        """A spurious ShutdownRequested kills one producer incarnation
        cleanly; the watchdog tells a spurious signal (rings still live)
        from real teardown and respawns into the exact position."""
        plan = FaultPlan(
            [FaultSpec("producer.fill", FaultKind.SPURIOUS_SHUTDOWN, at=2)]
        )
        windows, wd, m = drain_numpy(plan)
        assert_byte_identical(windows, 6)
        assert list(wd.respawns) == [1]
        assert list(wd.failures) == []

    def test_ring_corruption_quarantined_and_replayed(self):
        """Flipped slot bytes after commit: drain-time CRC verification
        quarantines the window and the producer replays it — the served
        stream is byte-identical, with the corruption visible in
        metrics, not in data."""
        plan = FaultPlan(
            [FaultSpec("producer.commit", FaultKind.RING_CORRUPTION,
                       at=2, param=4)]
        )
        windows, wd, m = drain_numpy(plan)
        assert_byte_identical(windows, 6)
        assert m.counter("integrity.corrupt_windows") == 1
        assert m.counter("integrity.replays") == 1
        assert list(wd.failures) == []

    def test_persistent_corruption_escalates_to_integrity_error(self):
        """Corruption that survives every replay exhausts the budget and
        raises IntegrityError — loudly, instead of serving bad bytes or
        spinning forever (the documented terminal rung)."""
        plan = FaultPlan(
            [FaultSpec("producer.commit", FaultKind.RING_CORRUPTION,
                       at=2, count=50, param=4)]
        )
        m = Metrics()

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                PatternProducer(), batch_size=N_DATA,
                connection=env.connection, n_epochs=6,
                output="numpy", timeout_s=15.0, metrics=m,
            )
            with pytest.raises(IntegrityError, match="still corrupt"):
                for _ in range(6):
                    for cols in loader:
                        loader.mark(Marker.END_OF_BATCH)
                    loader.mark(Marker.END_OF_EPOCH)
            loader.shutdown()

        with faults.armed(plan):
            main()
        assert m.counter("integrity.replays") == 2  # DDL_TPU_MAX_REPLAYS
        assert m.counter("integrity.corrupt_windows") >= 3

    def test_inplace_crash_mid_fill_respawned_byte_identical(self):
        """PRODUCER_CRASH at the ``pusher.inplace_fill`` site: the ring
        slot is fully WRITTEN but not yet stamped/committed — the torn
        slot (new payload under the previous occupant's stale trailer)
        must never reach the consumer.  Write-once ordering (stamp AFTER
        fill, commit after stamp) guarantees it is never committed; the
        respawned incarnation rejoins the surviving ring, reads the last
        COMMITTED slot's header for its exact position, and re-fills the
        torn slot from scratch.  Byte-identical, exactly once, zero
        corrupt windows observed."""
        plan = FaultPlan(
            [FaultSpec("pusher.inplace_fill", FaultKind.PRODUCER_CRASH,
                       at=3)]
        )
        windows, wd, m = drain_numpy(
            plan, producer_cls=InplacePatternProducer
        )
        assert_byte_identical(windows, 6)
        assert list(wd.respawns) == [1]
        assert list(wd.failures) == []
        assert m.counter("integrity.corrupt_windows") == 0
        assert plan.fired and plan.fired[0][0] == "pusher.inplace_fill"

    def test_inplace_torn_commit_quarantined_and_replayed(self):
        """A torn COMMITTED slot on the write-once path (bytes flipped
        after the trailer stamp — what a real shared-memory scribble
        looks like): the drain-time CRC quarantines it, and the replay
        rewinds the inplace producer THROUGH ITS LIVE SLOT VIEW
        (on_init → post_init → fast_forward all write into the acquired
        slot).  Served stream byte-identical, exactly once."""
        plan = FaultPlan(
            [FaultSpec("producer.commit", FaultKind.RING_CORRUPTION,
                       at=2, param=4)]
        )
        windows, wd, m = drain_numpy(
            plan, producer_cls=InplacePatternProducer
        )
        assert_byte_identical(windows, 6)
        assert m.counter("integrity.corrupt_windows") == 1
        assert m.counter("integrity.replays") == 1
        assert list(wd.failures) == []

    def test_staging_copy_fault_retried(self):
        """A transient staging-copy failure is retried with backoff; the
        stream stays byte-identical and the retry is metered."""
        plan = FaultPlan(
            [FaultSpec("staging.copy", FaultKind.STAGING_COPY_FAIL, at=2)]
        )
        windows, faulted, m = drain_windows_jax(plan)
        assert_byte_identical(windows, 5)
        assert m.counter("staging.retries") >= 1
        assert not faulted

    def test_staged_transfer_fault_falls_back_inline(self):
        """Persistent staged-transfer failure: bounded retries, then the
        salvaged staging buffer rides the sanctioned inline path and the
        engine is latched faulted — every window still arrives
        byte-identical, exactly once."""
        plan = FaultPlan(
            [FaultSpec("staging.transfer", FaultKind.STAGED_TRANSFER_FAIL,
                       at=1, count=999)]
        )
        windows, faulted, m = drain_windows_jax(plan)
        assert_byte_identical(windows, 5)
        assert m.counter("staging.retries") >= 1
        assert m.counter("staging.inline_fallbacks") >= 1
        assert faulted

    def test_staged_transfer_timeout_recovers(self):
        """An injected transfer delay stalls, never corrupts: the
        bounded waits absorb it and the stream is byte-identical."""
        plan = FaultPlan(
            [FaultSpec("staging.transfer",
                       FaultKind.STAGED_TRANSFER_TIMEOUT, at=2, param=0.4)]
        )
        windows, faulted, m = drain_windows_jax(plan)
        assert_byte_identical(windows, 5)
        assert m.counter("integrity.corrupt_windows") == 0
        assert not faulted

    def test_ici_dma_fail_mid_fused_stream_latches_sync_fallback(self):
        """ICI_DMA_FAIL fired mid-fused-stream (the two-slot ingest
        tier active on the virtual mesh): the distributor latches the
        synchronous xla fallback for the rest of the run WITHOUT
        stranding the in-flight landing slot (already-dispatched
        windows resolve on their own semaphores) or the consumer's
        release backlog, and the served stream stays byte-identical —
        the fused protocol's degradation rung."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        m = Metrics()
        n_epochs = 6
        mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
        sharding = NamedSharding(mesh, P(None, "dp"))
        plan = FaultPlan(
            [FaultSpec("ici.fanout", FaultKind.ICI_DMA_FAIL, at=3)]
        )

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                PatternProducer(), batch_size=N_DATA,
                connection=env.connection, n_epochs=n_epochs,
                output="jax", timeout_s=60.0, metrics=m,
                sharding=sharding, distribute="ici",
            )
            windows = []
            for win in loader.windows():
                windows.append(np.asarray(win).reshape(SHAPE).copy())
                loader.mark(Marker.END_OF_EPOCH)
            assert not loader._release_backlog  # nothing stranded
            return windows, loader._ingestor._ici

        with faults.armed(plan):
            windows, dist = main()
        assert_byte_identical(windows, n_epochs)
        assert plan.fired and plan.fired[0][1] == "ici_dma_fail"
        assert dist.faulted  # latched: the rest of the run rode xla
        assert m.counter("ici.fallbacks") == 1
        # Exactly the pre-fault windows rode the fused ICI tier; the
        # fault window and every later one took the synchronous path.
        assert m.counter("ici.windows") == 2
        assert m.counter("ici.fused_windows") == 2
        # The latch cleared the landing-slot tracking (no phantom
        # occupancy), while the high-water proves slots were used.
        assert m.gauge("ici.slots_in_flight") == 0.0

    def test_shuffle_peer_loss_degrades_to_local(self):
        """Exchange partner lost: the round degrades to a node-local
        shuffle (loud warning + metric) instead of stalling; after
        max_peer_losses consecutive losses the exchange is disabled and
        the run COMPLETES.  Row multiset per window is preserved."""
        from ddl_tpu.env import WorkerSet
        from ddl_tpu.shuffle import Rendezvous, ThreadExchangeShuffler
        from ddl_tpu.types import RunMode, Topology

        class TaggedShuffleProducer(ProducerFunctionSkeleton):
            """Tagged rows, locally shuffled in place per refill — so
            served content is a row PERMUTATION, and loss/duplication is
            visible as a multiset change."""

            def on_init(self, producer_idx=1, **kw):
                self._rng = np.random.default_rng(0)
                return DataProducerOnInitReturn(
                    nData=N_DATA, nValues=N_VALUES, shape=SHAPE,
                    splits=(N_VALUES - 1, 1),
                )

            def post_init(self, my_ary, **kw):
                my_ary[:] = (
                    np.arange(N_DATA, dtype=np.float32)[:, None]
                    + np.arange(N_VALUES, dtype=np.float32)[None, :] * 100
                )

            def execute_function(self, my_ary, **kw):
                self._rng.shuffle(my_ary)

        plan = FaultPlan(
            [FaultSpec("shuffle.exchange", FaultKind.SHUFFLE_PEER_LOSS,
                       at=1, count=999)]
        )
        n_epochs = 5
        before = default_metrics().counter("shuffle.degraded")
        # Instance 0 of a declared 2-instance topology, with NO instance
        # 1 running: every exchange round has a lost peer by construction.
        topo = Topology(
            n_instances=2, instance_idx=0, n_producers=1,
            mode=RunMode.THREAD,
        )
        ws = WorkerSet(
            topo, nslots=2,
            shuffler_factory=ThreadExchangeShuffler.factory(
                rendezvous=Rendezvous(),  # private: no cross-test leaks
                exchange_timeout_s=5.0, max_peer_losses=2,
            ),
        )
        t0 = time.monotonic()
        with faults.armed(plan):
            loader = DistributedDataLoader(
                TaggedShuffleProducer(), batch_size=N_DATA,
                connection=ws.connection, n_epochs=n_epochs,
                output="numpy", global_shuffle_fraction_exchange=0.5,
                timeout_s=60.0,
            )
            windows = []
            try:
                for _ in range(n_epochs):
                    for cols in loader:
                        windows.append(
                            np.hstack([np.asarray(c) for c in cols])
                        )
                        loader.mark(Marker.END_OF_BATCH)
                    loader.mark(Marker.END_OF_EPOCH)
            finally:
                loader.shutdown()
                ws.abort()
                ws.join(30.0)
        assert len(windows) == n_epochs
        # Degraded rounds permute rows locally: the original row tags are
        # conserved as a multiset in EVERY served window — peer loss cost
        # global mixing, never data.
        tags = (
            np.arange(N_DATA, dtype=np.float32)[:, None]
            + np.arange(N_VALUES, dtype=np.float32)[None, :] * 100
        )
        for i, win in enumerate(windows):
            np.testing.assert_array_equal(
                np.sort(win, axis=0), np.sort(tags, axis=0),
                err_msg=f"window {i + 1} lost/duplicated rows",
            )
        assert default_metrics().counter("shuffle.degraded") - before >= 2
        # Never stalled out a full exchange timeout, let alone one per
        # round: the injection fails fast and the latch disables the rest.
        assert time.monotonic() - t0 < 30.0

    def test_handshake_crash_fails_fast_with_typed_error(self):
        """A producer crashing during its handshake ships the failure to
        the consumer — construction raises TransportError promptly
        instead of stalling until the handshake timeout."""
        plan = FaultPlan(
            [FaultSpec("producer.handshake", FaultKind.PRODUCER_CRASH)]
        )

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            with pytest.raises(TransportError, match="handshake"):
                DistributedDataLoader(
                    PatternProducer(), batch_size=N_DATA,
                    connection=env.connection, n_epochs=1, output="numpy",
                )

        t0 = time.monotonic()
        with faults.armed(plan):
            main()
        assert time.monotonic() - t0 < 30.0

    def test_host_loss_repartitions_byte_identical(self):
        """HOST_LOSS at cluster.heartbeat (ISSUE 10): the injected loss
        of a whole mock host drives the epoch-fenced view change — pool
        shrink, shard adoption — and the stream recovers byte-identical
        full-shard coverage (the runner lives in tests/test_cluster.py;
        the matrix row wires it into the tier-1 chaos sweep)."""
        from test_cluster import (
            assert_full_coverage_byte_identical,
            drain_cluster,
        )

        plan = FaultPlan(
            # at=8: past bootstrap sweeps, mid-stream (50 ms cadence).
            [FaultSpec("cluster.heartbeat", FaultKind.HOST_LOSS,
                       at=8, producer_idx=1)]
        )
        seen, m, sup = drain_cluster(plan=plan, n_epochs=24, pace_s=0.05)
        assert plan.fired, "HOST_LOSS spec never fired"
        assert m.counter("cluster.host_losses") == 1.0
        assert m.counter("cluster.view_changes") == 1.0
        assert m.counter("watchdog.failures") == 0.0
        assert_full_coverage_byte_identical(seen)

    def test_tenant_burst_mid_stream_byte_identical(self):
        """TENANT_BURST at serve.admit (ISSUE 11): an injected demand
        spike lands on a tenant's 4th admission mid-stream — the
        fair-share scheduler absorbs it as phantom bytes charged to the
        burster's own share (replenish rounds pay it down), and the
        stream completes byte-identical with every window served (the
        scheduler/runner live in ddl_tpu/serve + tests/test_serve.py;
        the matrix row wires the kind into the tier-1 chaos sweep)."""
        from test_serve import (
            ROWS,
            PatternProducer,
            assert_pattern_windows,
        )

        from ddl_tpu import DistributedDataLoader, distributed_dataloader
        from ddl_tpu.observability import Metrics
        from ddl_tpu.serve import AdmissionController, TenantSpec

        m = Metrics()
        ctl = AdmissionController(metrics=m)
        tenant = ctl.register(TenantSpec("burst-me"))
        plan = FaultPlan(
            [FaultSpec("serve.admit", FaultKind.TENANT_BURST,
                       at=4, producer_idx=0, param=float(16 << 20))]
        )
        n_epochs = 8

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                PatternProducer(), batch_size=ROWS,
                connection=env.connection, n_epochs=n_epochs,
                output="numpy", timeout_s=30.0, metrics=m,
            )
            tenant.bind(loader)
            wins = []
            for _ in range(n_epochs):
                for (win,) in loader:
                    wins.append(win.copy())
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
            return wins

        with faults.armed(plan):
            wins = main()
        assert plan.fired and plan.fired[0][1] == "tenant_burst"
        assert len(wins) == n_epochs
        assert_pattern_windows(wins)
        assert m.counter("serve.tenant_bursts") == 1.0
        assert m.counter("ingest.burst-me.windows") == n_epochs
        # The spike was paid down by replenish rounds, not a timeout.
        assert m.counter("serve.rounds") >= 1.0

    def test_scale_decision_delay_defers_but_preserves_the_decision(self):
        """SCALE_DECISION_DELAY at serve.scale (ISSUE 11): the policy
        loop's decision lands ``param`` seconds late and is the SAME
        decision — reaction time degrades, membership correctness never
        (the policy machine lives in ddl_tpu/serve/autoscaler.py; the
        runner idiom mirrors tests/test_serve.py's)."""
        from test_serve import FakeCluster, make_scaler

        from ddl_tpu.cluster import HostInfo

        clock = [0.1]
        sig = {"stall_fraction": 0.9}
        fc = FakeCluster([0])
        sc = make_scaler(fc, sig, clock, sustain_s=0.0, cooldown_s=0.0,
                         standby=[HostInfo(1, loader_ranks=(2,))])
        plan = FaultPlan(
            [FaultSpec("serve.scale", FaultKind.SCALE_DECISION_DELAY,
                       at=1, param=0.1)]
        )
        t0 = time.perf_counter()
        with faults.armed(plan):
            out = sc.step()
        assert time.perf_counter() - t0 >= 0.1
        assert out == "up" and fc.rejoins == [1]
        assert plan.fired[0][1] == "scale_decision_delay"
        # The next (undelayed) step sees the grown pool and is a no-op
        # within cooldown semantics — the delayed action was complete.
        assert len(fc.supervisor.view.hosts) == 2

    def test_heartbeat_drop_expires_lease_then_recovers(self):
        """Persistent HEARTBEAT_DROP at cluster.heartbeat: single drops
        are absorbed (only the lease ages), but a host whose every beat
        is lost expires and leaves the view — the stream re-partitions
        and completes with full coverage."""
        from test_cluster import (
            assert_full_coverage_byte_identical,
            drain_cluster,
        )

        plan = FaultPlan(
            [FaultSpec("cluster.heartbeat", FaultKind.HEARTBEAT_DROP,
                       producer_idx=1, count=100_000)]
        )
        seen, m, sup = drain_cluster(
            plan=plan, n_epochs=24, lease_s=0.4, pace_s=0.05
        )
        assert plan.fired
        assert m.counter("cluster.heartbeats_dropped") > 1.0
        assert m.counter("cluster.host_losses") == 1.0
        assert m.counter("watchdog.failures") == 0.0
        assert_full_coverage_byte_identical(seen)


# ---------------------------------------------------------------------------
# Engine mechanics: determinism, matching, serialization, zero-cost.
# ---------------------------------------------------------------------------


class TestFaultEngine:
    def test_disarmed_fault_point_is_a_noop(self):
        assert faults.armed_plan() is None
        fault_point("producer.fill", producer_idx=1)
        fault_point("nonexistent.site", view=np.zeros(4, np.uint8))

    def test_plan_json_roundtrip(self):
        plan = FaultPlan(
            [FaultSpec("producer.fill", FaultKind.PRODUCER_CRASH, at=3,
                       count=2, producer_idx=1, param=0.5)],
            seed=7,
        )
        back = FaultPlan.from_json(plan.to_json())
        assert back.seed == 7
        assert back.specs == plan.specs

    def test_at_and_count_hit_windows(self):
        plan = FaultPlan(
            [FaultSpec("s", FaultKind.PRODUCER_CRASH, at=2, count=2)]
        )
        with faults.armed(plan):
            fault_point("s")  # hit 1: below `at`
            with pytest.raises(InjectedFault):
                fault_point("s")  # hit 2
            with pytest.raises(InjectedFault):
                fault_point("s")  # hit 3
            fault_point("s")  # hit 4: past the window
        assert [f[3] for f in plan.fired] == [2, 3]

    def test_producer_idx_narrowing(self):
        plan = FaultPlan(
            [FaultSpec("s", FaultKind.PRODUCER_CRASH, producer_idx=2)]
        )
        with faults.armed(plan):
            fault_point("s", producer_idx=1)  # other producer: no match
            fault_point("s")  # no producer context: no match
            with pytest.raises(InjectedFault):
                fault_point("s", producer_idx=2)

    def test_armed_context_restores_previous_plan_and_env(self):
        outer = FaultPlan([FaultSpec("a", FaultKind.PRODUCER_CRASH)])
        inner = FaultPlan([FaultSpec("b", FaultKind.PRODUCER_CRASH)])
        with faults.armed(outer):
            with faults.armed(inner, export=True):
                assert faults.armed_plan() is inner
                assert faults.PLAN_ENV in os.environ
            assert faults.armed_plan() is outer
            assert faults.PLAN_ENV not in os.environ
        assert faults.armed_plan() is None

    def test_corruption_is_seed_deterministic(self):
        def corrupted(seed):
            buf = np.zeros(64, np.uint8)
            plan = FaultPlan(
                [FaultSpec("s", FaultKind.RING_CORRUPTION, param=4)],
                seed=seed,
            )
            with faults.armed(plan):
                fault_point("s", view=buf)
            return buf

        np.testing.assert_array_equal(corrupted(3), corrupted(3))
        assert not np.array_equal(corrupted(3), corrupted(4))

    def test_hang_observes_abort(self):
        plan = FaultPlan(
            [FaultSpec("s", FaultKind.PRODUCER_HANG, param=30.0)]
        )
        t0 = time.monotonic()
        flag = {"down": False}

        import threading

        def aborter():
            time.sleep(0.2)
            flag["down"] = True

        threading.Thread(target=aborter, daemon=True).start()
        with faults.armed(plan):
            with pytest.raises(ShutdownRequested):
                fault_point("s", should_abort=lambda: flag["down"])
        assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# Integrity layer units: header codec, drain verification, TFRecord CRCs.
# ---------------------------------------------------------------------------


class TestIntegrityLayer:
    def _slot(self, payload_val=7, payload_bytes=128):
        slot = np.zeros(payload_bytes + integrity.HEADER_BYTES, np.uint8)
        slot[:payload_bytes] = payload_val
        integrity.write_header(
            slot, payload_bytes, seq=5, producer_idx=2,
            crc=integrity.window_crc(slot[:payload_bytes]),
        )
        return slot, payload_bytes

    def test_header_roundtrip_and_verify_ok(self):
        slot, n = self._slot()
        hdr = integrity.read_header(slot, n)
        assert hdr.valid_magic and hdr.seq == 5 and hdr.producer_idx == 2
        assert integrity.verify_window(slot, n, 5, 2) is None

    def test_verify_catches_flipped_byte(self):
        slot, n = self._slot()
        slot[17] ^= 0xFF
        err = integrity.verify_window(slot, n, 5, 2)
        assert err is not None and "crc32" in err

    def test_verify_catches_seq_and_producer_mismatch(self):
        slot, n = self._slot()
        assert "seq" in integrity.verify_window(slot, n, 6, 2)
        assert "producer" in integrity.verify_window(slot, n, 5, 3)

    def test_verify_catches_unstamped_header(self):
        slot = np.zeros(128 + integrity.HEADER_BYTES, np.uint8)
        assert "magic" in integrity.verify_window(slot, 128, 0, 1)

    def test_enable_gate(self, monkeypatch):
        monkeypatch.delenv("DDL_TPU_INTEGRITY", raising=False)
        assert integrity.integrity_enabled()
        monkeypatch.setenv("DDL_TPU_INTEGRITY", "0")
        assert not integrity.integrity_enabled()
        assert integrity.integrity_enabled(override=True)

    def test_pipeline_with_integrity_disabled(self, monkeypatch):
        """The DDL_TPU_INTEGRITY=0 escape hatch serves the PR 2 byte
        path: no headers, no verification, identical data."""
        monkeypatch.setenv("DDL_TPU_INTEGRITY", "0")
        windows, wd, m = drain_numpy(None, n_epochs=3)
        assert_byte_identical(windows, 3)
        assert m.counter("integrity.corrupt_windows") == 0


class TestTFRecordCRC:
    def _write(self, tmp_path, valid=True):
        import sys

        sys.path.insert(0, os.path.dirname(__file__))
        from datagen import encode_example_int64, write_tfrecord

        payloads = [
            encode_example_int64("input_ids", list(range(10 * i, 10 * i + 8)))
            for i in range(4)
        ]
        path = str(tmp_path / "rec.tfrecord")
        write_tfrecord(path, payloads, valid_crc=valid)
        return path, payloads

    def test_crc32c_check_vector(self):
        from ddl_tpu.readers import crc32c

        # The spec's check vector, plus length edge cases around the
        # 8-byte slicing boundary.
        assert crc32c(b"123456789") == 0xE3069283
        assert crc32c(b"") == 0
        import zlib

        data = bytes(range(256)) * 3 + b"tail"
        # Cross-check slicing-by-8 against a per-byte reference.
        ref = 0xFFFFFFFF
        from ddl_tpu.readers import _make_crc32c_tables

        t0 = _make_crc32c_tables()[0]
        for b in data:
            ref = int(t0[(ref ^ b) & 0xFF]) ^ (ref >> 8)
        assert crc32c(data) == ref ^ 0xFFFFFFFF
        assert crc32c(data) != (zlib.crc32(data) & 0xFFFFFFFF)  # crc32c != crc32

    def test_valid_file_reads_with_verification(self, tmp_path):
        from ddl_tpu.readers import iter_tfrecords

        path, payloads = self._write(tmp_path)
        assert list(iter_tfrecords(path, verify_crc=True)) == payloads

    def test_corrupt_payload_raises_with_context(self, tmp_path):
        from ddl_tpu.readers import iter_tfrecords

        path, _ = self._write(tmp_path)
        data = bytearray(open(path, "rb").read())
        data[20] ^= 0xFF  # inside record 0's payload
        open(path, "wb").write(bytes(data))
        with pytest.raises(IntegrityError, match="offset 0"):
            list(iter_tfrecords(path, verify_crc=True))

    def test_corrupt_length_crc_raises(self, tmp_path):
        from ddl_tpu.readers import iter_tfrecords

        path, _ = self._write(tmp_path, valid=False)  # zeroed CRCs
        with pytest.raises(IntegrityError, match="length-crc"):
            list(iter_tfrecords(path, verify_crc=True))

    def test_opt_out_knob_skips_validation(self, tmp_path, monkeypatch):
        from ddl_tpu.readers import iter_tfrecords

        path, payloads = self._write(tmp_path, valid=False)
        assert list(iter_tfrecords(path, verify_crc=False)) == payloads
        monkeypatch.setenv("DDL_TPU_TFRECORD_CRC", "0")
        assert list(iter_tfrecords(path)) == payloads
        monkeypatch.setenv("DDL_TPU_TFRECORD_CRC", "1")
        with pytest.raises(IntegrityError):
            list(iter_tfrecords(path))


# ---------------------------------------------------------------------------
# Randomized multi-fault soak (make chaos).
# ---------------------------------------------------------------------------


def _random_plan(seed: int) -> FaultPlan:
    """2 seeded faults drawn from the locally-replayable matrix."""
    rng = np.random.default_rng(seed)
    kinds = [
        (FaultKind.PRODUCER_CRASH, "producer.fill", 0.0),
        (FaultKind.PRODUCER_SLOWDOWN, "producer.fill", 0.3),
        (FaultKind.SPURIOUS_SHUTDOWN, "producer.fill", 0.0),
        (FaultKind.RING_CORRUPTION, "producer.commit", 3.0),
    ]
    specs = []
    for pick in rng.choice(len(kinds), size=2, replace=False):
        kind, site, param = kinds[int(pick)]
        specs.append(
            FaultSpec(site, kind, at=int(rng.integers(2, 6)), param=param)
        )
    return FaultPlan(specs, seed=seed)


@pytest.mark.slow
class TestChaosSoak:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_multi_fault_byte_identical(self, seed):
        plan = _random_plan(seed)
        windows, wd, m = drain_numpy(plan, n_epochs=8)
        assert_byte_identical(windows, 8)
        assert list(wd.failures) == []
        assert plan.fired, "no scheduled fault ever fired"

    @pytest.mark.parametrize("producer_cls,site", [
        (PatternProducer, "producer.fill"),
        # Write-once producers: the crash fires mid-inplace-fill with a
        # torn shm slot behind it — the respawn must re-fill it, never
        # serve it (tier-1 has the THREAD twin; this one crosses the
        # real spawn boundary over the native shm ring).
        (InplacePatternProducer, "pusher.inplace_fill"),
    ])
    def test_process_mode_crash_respawn_with_exported_plan(
        self, producer_cls, site
    ):
        """PROCESS mode: the plan crosses the spawn boundary via
        DDL_TPU_FAULT_PLAN and the spawned producer injects its own
        crash; elastic recovery still delivers the exact stream."""
        plan = FaultPlan(
            [FaultSpec(site, FaultKind.PRODUCER_CRASH, at=3)]
        )
        m = Metrics()

        @distributed_dataloader(n_producers=1, mode="process")
        def main(env):
            wd = Watchdog(
                env.workers, poll_interval_s=0.2, stall_budget_s=60.0,
                respawn=True, metrics=m,
            ).start()
            try:
                loader = DistributedDataLoader(
                    producer_cls(), batch_size=N_DATA,
                    connection=env.connection, n_epochs=6,
                    output="numpy", timeout_s=120.0, metrics=m,
                )
                windows = []
                for _ in range(6):
                    for cols in loader:
                        windows.append(
                            np.hstack([np.asarray(c) for c in cols])
                        )
                        loader.mark(Marker.END_OF_BATCH)
                    loader.mark(Marker.END_OF_EPOCH)
            finally:
                wd.stop()
            return windows, wd

        with faults.armed(plan, export=True):
            windows, wd = main()
        assert_byte_identical(windows, 6)
        # Each spawned incarnation re-arms the plan from the env with a
        # fresh hit counter, so late incarnations may crash (and heal)
        # again — the count is timing-dependent, the DATA never is.
        assert len(wd.respawns) >= 1 and set(wd.respawns) == {1}
        assert list(wd.failures) == []
