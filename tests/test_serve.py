"""Multi-tenant ingest service suite (ddl_tpu/serve, ISSUE 11).

Three layers:

- **units** — TenantSpec validation, the deficit-round-robin scheduler
  (grant/charge/replenish, byte + slot budgets, the non-blocking probe),
  the autoscaler policy machine over a fake cluster (hysteresis bands,
  sustain, cooldown, the never-empty floor, placement replans), and
  ``ElasticCluster.drain_host``.
- **fairness** — concurrent consumers over the shared scheduler: two
  REAL loaders with skewed demand rotating over their pools, asserting
  neither starves past its budget (the gap PR 9's single-consumer pool
  tests left open), plus a thread-hammer weight-proportionality check.
- **chaos** — the two new fault kinds at their sites: ``TENANT_BURST``
  at ``serve.admit`` (the burster pays, neighbours don't) and
  ``SCALE_DECISION_DELAY`` at ``serve.scale`` (delayed decision, never a
  wrong one), wired as tier-1 chaos-matrix rows; and an e2e leg where
  the autoscaler grows a real THREAD pipeline's pool mid-stream with
  byte-identical delivery.
"""

import threading
import time

import numpy as np
import pytest

from ddl_tpu import (
    DataProducerOnInitReturn,
    DistributedDataLoader,
    Marker,
    ProducerFunctionSkeleton,
    distributed_dataloader,
)
from ddl_tpu import faults
from ddl_tpu.cluster import (
    ClusterSupervisor,
    ClusterView,
    ElasticCluster,
    HostInfo,
    LinkCosts,
)
from ddl_tpu.exceptions import (
    DDLError,
    ShutdownRequested,
    StallTimeoutError,
    TenantBurst,
)
from ddl_tpu.faults import FaultKind, FaultPlan, FaultSpec
from ddl_tpu.observability import Metrics
from ddl_tpu.serve import (
    AdmissionController,
    Autoscaler,
    AutoscalerPolicy,
    FairShareScheduler,
    TenantSpec,
)

ROWS, VALS = 8, 4


class PatternProducer(ProducerFunctionSkeleton):
    """Deterministic per-producer window content: window k from producer
    p is ``p * 1000 + k`` everywhere — byte-correctness is checkable on
    any served subsequence regardless of pool churn."""

    inplace_fill = True

    def __init__(self, fill_latency_s: float = 0.0):
        self.fill_latency_s = fill_latency_s

    def on_init(self, producer_idx=1, **kw):
        self.idx = producer_idx
        self.k = 0
        return DataProducerOnInitReturn(
            nData=ROWS, nValues=VALS, shape=(ROWS, VALS), splits=(VALS,)
        )

    def post_init(self, my_ary, **kw):
        my_ary[:] = 0.0

    def execute_function(self, my_ary, **kw):
        if self.fill_latency_s:
            time.sleep(self.fill_latency_s)
        my_ary[:] = float(self.idx * 1000 + self.k)
        self.k += 1


def assert_pattern_windows(wins):
    """Every served window is a constant plane p*1000+k — intact bytes."""
    for w in wins:
        v = w.ravel()[0]
        np.testing.assert_array_equal(w, np.full_like(w, v))
        assert v >= 1000.0  # producer_idx >= 1


# ---------------------------------------------------------------------------
# Units: specs + scheduler
# ---------------------------------------------------------------------------


class TestTenantSpec:
    def test_validation(self):
        with pytest.raises(DDLError):
            TenantSpec("")
        with pytest.raises(DDLError):
            TenantSpec("a.b")  # dots alias the metrics namespace
        with pytest.raises(DDLError):
            TenantSpec("a", weight=0.0)
        with pytest.raises(DDLError):
            TenantSpec("a", byte_budget_per_s=-1)
        with pytest.raises(DDLError):
            TenantSpec("a", slot_budget=-1)
        TenantSpec("ok", weight=2.5, byte_budget_per_s=1e6, slot_budget=3)


class TestScheduler:
    def test_register_unregister_and_gauge(self):
        m = Metrics()
        s = FairShareScheduler(metrics=m)
        s.register(TenantSpec("a"))
        s.register(TenantSpec("b"))
        assert m.gauge("serve.tenants") == 2
        with pytest.raises(DDLError):
            s.register(TenantSpec("a"))
        s.unregister("a")
        assert m.gauge("serve.tenants") == 1
        assert s.tenants() == ["b"]

    def test_unknown_tenant_admit_raises(self):
        s = FairShareScheduler()
        with pytest.raises(DDLError):
            s.admit("ghost", 1.0)

    def test_single_tenant_never_waits_long(self):
        """A sole tenant's multi-quantum windows replenish through
        instant logical rounds, not 50 ms-per-quantum sleeps."""
        m = Metrics()
        s = FairShareScheduler(quantum_bytes=1 << 20, metrics=m)
        s.register(TenantSpec("solo"))
        t0 = time.perf_counter()
        for _ in range(5):
            s.admit("solo", 5.0)
            s.note_served("solo", 8 << 20)  # 8 quanta per window
        assert time.perf_counter() - t0 < 1.0
        assert m.counter("serve.rounds") >= 5
        assert m.counter("ingest.solo.windows") == 5
        assert m.counter("ingest.solo.bytes") == 5 * (8 << 20)

    def test_nonblocking_probe_raises_when_throttled(self):
        """timeout_s <= 0 is the lookahead-deepening probe: a budget-
        blocked tenant gets an immediate StallTimeoutError, never a
        wait (the deepening loop treats it as not-committed-yet)."""
        clock = [0.0]
        s = FairShareScheduler(clock=lambda: clock[0])
        s.register(TenantSpec("t", byte_budget_per_s=1000.0))
        s.admit("t", 0.0)  # fresh bucket: grantable
        s.note_served("t", 5000)  # 5 seconds of budget consumed
        with pytest.raises(StallTimeoutError):
            s.admit("t", 0.0)
        clock[0] += 10.0  # bucket refills with the (injected) clock
        s.admit("t", 0.0)

    def test_byte_budget_is_wall_clock_not_rounds(self):
        """Replenish rounds must never bypass the rate budget: a
        budget-blocked sole waiter times out instead of round-spinning
        itself grantable."""
        clock = [0.0]
        s = FairShareScheduler(clock=lambda: clock[0])
        s.register(TenantSpec("t", byte_budget_per_s=100.0))
        s.admit("t", 0.0)
        s.note_served("t", 1000)  # 10 s of budget in one window

        waited = {}

        def try_admit():
            try:
                s.admit("t", 0.2)
                waited["granted"] = True
            except StallTimeoutError:
                waited["granted"] = False

        th = threading.Thread(target=try_admit)
        th.start()
        # Let the waiter park, then advance the injected clock past its
        # deadline WITHOUT refilling enough budget (0.2 s * 100 B/s).
        time.sleep(0.1)
        clock[0] += 0.3
        th.join(5.0)
        assert waited == {"granted": False}

    def test_slot_budget_caps_grants_per_round(self):
        """slot_budget=1 holds a tenant to one window per round while a
        competitor is PARKED in admit — the concurrency brake on top of
        the byte share.  Deterministic: the competitor's waiting state
        is pinned directly (a thread-timing version of this test is
        exactly the race the pin removes), and released to prove the
        cap is per-round, not permanent."""
        s = FairShareScheduler(quantum_bytes=1 << 30)  # bytes never bind
        s.register(TenantSpec("capped", slot_budget=1))
        s.register(TenantSpec("free"))
        s.admit("capped", 0.0)
        s.note_served("capped", 100)  # the round's one slot is spent
        st_free = s._state("free")
        st_free.waiting = 1  # a backlogged, grantable competitor
        with pytest.raises(StallTimeoutError):
            # The cap holds: a round may not advance past a grantable
            # waiter, and without a round the slot counter never resets.
            s.admit("capped", 0.0)
        st_free.waiting = 0
        # Competitor gone: the round advances and the cap resets.
        s.admit("capped", 0.0)
        s.note_served("capped", 100)

    def test_weight_proportional_service(self):
        """Two backlogged tenants with 2:1 weights settle at ~2:1 served
        bytes — the DRR quantum scaling."""
        s = FairShareScheduler(quantum_bytes=1 << 16)
        s.register(TenantSpec("heavy", weight=2.0))
        s.register(TenantSpec("light", weight=1.0))
        served = {"heavy": 0, "light": 0}
        window = 1 << 16  # one quantum per window

        def run(name, n):
            for _ in range(n):
                s.admit(name, 10.0)
                served[name] += window
                s.note_served(name, window)

        th = threading.Thread(target=run, args=("heavy", 40))
        tl = threading.Thread(target=run, args=("light", 40))
        th.start(), tl.start()
        th.join(30.0), tl.join(30.0)
        assert served == {"heavy": 40 * window, "light": 40 * window}

    def test_admission_wait_metrics_accumulate(self):
        m = Metrics()
        s = FairShareScheduler(metrics=m)
        s.register(TenantSpec("t"))
        s.admit("t", 1.0)
        s.note_served("t", 10)
        assert m.counter("serve.admissions") == 1
        assert m.timer("serve.admission_wait").count == 1
        assert m.timer("ingest.t.admission_wait").count == 1

    def test_note_served_after_unregister_is_harmless(self):
        s = FairShareScheduler()
        s.register(TenantSpec("t"))
        s.admit("t", 1.0)
        s.unregister("t")
        s.note_served("t", 100)  # mid-flight teardown: no raise


class TestAdmissionController:
    def test_register_report_close(self):
        m = Metrics()
        ctl = AdmissionController(metrics=m)
        a = ctl.register(TenantSpec("a"))
        b = ctl.register(TenantSpec("b", weight=2.0))
        a.admit(1.0), a.note_served(1 << 20)
        b.admit(1.0), b.note_served(2 << 20)
        rep = ctl.report()
        assert set(rep["tenants"]) == {"a", "b"}
        assert rep["tenants"]["a"]["bytes"] == float(1 << 20)
        assert rep["tenants"]["b"]["windows"] == 1.0
        assert rep["admissions"] == 2.0
        # report() refreshed the per-tenant stall gauges north_star reads
        assert m.gauge("serve.stall.a") >= 0.0
        assert a.metrics()["bytes"] == float(1 << 20)
        ctl.close()
        assert ctl.scheduler.tenants() == []

    def test_shared_cache_handle(self):
        store = object()
        ctl = AdmissionController(cache=store)
        assert ctl.cache is store


# ---------------------------------------------------------------------------
# Units: autoscaler policy machine
# ---------------------------------------------------------------------------


def loader_view(host_ids, n_shards=8):
    return ClusterView.bootstrap(
        [HostInfo(h, loader_ranks=(h + 1,)) for h in host_ids],
        n_shards=n_shards,
    )


class FakeCluster:
    """Duck-typed resize target: supervisor.view + rejoin/drain, no
    rings — the policy machine under test, not the ladder."""

    def __init__(self, host_ids):
        self.supervisor = ClusterSupervisor(loader_view(host_ids),
                                            metrics=Metrics())
        self.rejoins = []
        self.drains = []

    def rejoin_host(self, host):
        self.rejoins.append(host.host_id)
        return self.supervisor.rejoin(host)

    def drain_host(self, host_id):
        self.drains.append(host_id)
        host = self.supervisor.view.host(host_id)
        self.supervisor.declare_host_loss(host_id)
        return host


def make_scaler(cluster, sig, clock, m=None, standby=(), **pol):
    policy = AutoscalerPolicy(**{
        "up_stall_fraction": 0.3, "down_stall_fraction": 0.1,
        "sustain_s": 1.0, "cooldown_s": 2.0, "min_hosts": 1, **pol,
    })
    return Autoscaler(
        cluster, standby=standby, policy=policy, metrics=m or Metrics(),
        clock=lambda: clock[0], signal=lambda: dict(sig),
    )


class TestAutoscalerPolicy:
    def test_policy_validation(self):
        with pytest.raises(DDLError):
            AutoscalerPolicy(up_stall_fraction=0.1, down_stall_fraction=0.2)
        with pytest.raises(DDLError):
            AutoscalerPolicy(min_hosts=0)
        with pytest.raises(DDLError):
            AutoscalerPolicy(sustain_s=-1)

    def test_sustained_demand_scales_up_and_records_reaction(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.9, "queue_depth": 0.0}
        m = Metrics()
        fc = FakeCluster([0])
        sc = make_scaler(fc, sig, clock, m,
                         standby=[HostInfo(1, loader_ranks=(2,))])
        assert sc.step() is None  # first sighting only starts the timer
        clock[0] = 0.5
        assert sc.step() is None  # not sustained yet
        clock[0] = 1.1
        assert sc.step() == "up"
        assert fc.rejoins == [1]
        assert m.counter("serve.scale_ups") == 1
        assert m.timer("serve.scale_up_reaction").count == 1
        assert m.gauge("serve.pool_hosts") == 2
        assert sc.standby == []

    def test_one_noisy_sample_never_scales(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.9}
        fc = FakeCluster([0])
        sc = make_scaler(fc, sig, clock,
                         standby=[HostInfo(1, loader_ranks=(2,))])
        sc.step()
        sig["stall_fraction"] = 0.0  # noise gone before sustain_s
        clock[0] = 0.5
        sc.step()
        sig["stall_fraction"] = 0.9  # the sustain timer must restart
        clock[0] = 1.2
        assert sc.step() is None
        assert fc.rejoins == []

    def test_dead_band_holds_state(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.2}  # between 0.1 and 0.3
        fc = FakeCluster([0, 1])
        sc = make_scaler(fc, sig, clock,
                         standby=[HostInfo(2, loader_ranks=(3,))])
        for t in (0.0, 1.0, 2.0, 5.0):
            clock[0] = t
            assert sc.step() is None
        assert fc.rejoins == [] and fc.drains == []

    def test_cooldown_spaces_actions(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.9}
        fc = FakeCluster([0])
        sc = make_scaler(
            fc, sig, clock,
            standby=[HostInfo(1, loader_ranks=(2,)),
                     HostInfo(2, loader_ranks=(3,))],
        )
        sc.step()
        clock[0] = 1.1
        assert sc.step() == "up"
        clock[0] = 2.5  # sustained again, but inside cooldown (2.0 after t=1.1)
        sc.step()
        clock[0] = 3.0
        assert sc.step() is None
        clock[0] = 4.5  # cooldown passed AND demand sustained since 2.5
        assert sc.step() == "up"
        assert fc.rejoins == [1, 2]

    def test_sustained_idle_drains_newest_loader_host(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.0}
        m = Metrics()
        fc = FakeCluster([0, 1, 2])
        sc = make_scaler(fc, sig, clock, m, cooldown_s=0.0)
        sc.step()
        clock[0] = 1.1
        assert sc.step() == "down"
        assert fc.drains == [2]
        assert m.counter("serve.scale_downs") == 1
        assert [h.host_id for h in sc.standby] == [2]

    def test_never_empty_floor(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.0}
        fc = FakeCluster([0, 1])
        sc = make_scaler(fc, sig, clock, cooldown_s=0.0, min_hosts=2)
        sc.step()
        clock[0] = 1.5
        assert sc.step() is None
        assert fc.drains == []

    def test_trainer_hosts_are_never_drained(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.0}
        fc = FakeCluster([0])
        # Host 5 both loads and trains; host 0 is the loader-only one
        # left after it — but draining 5 would take trainers down.
        fc.supervisor.rejoin(
            HostInfo(5, loader_ranks=(6,), trainer_ranks=(0,))
        )
        sc = make_scaler(fc, sig, clock, cooldown_s=0.0)
        sc.step()
        clock[0] = 1.5
        assert sc.step() == "down"
        assert fc.drains == [0]

    def test_demand_without_standby_is_a_noop(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.9}
        fc = FakeCluster([0])
        sc = make_scaler(fc, sig, clock, standby=[])
        sc.step()
        clock[0] = 1.5
        assert sc.step() is None

    def test_max_hosts_ceiling(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.9}
        fc = FakeCluster([0, 1])
        sc = make_scaler(fc, sig, clock, max_hosts=2,
                         standby=[HostInfo(2, loader_ranks=(3,))])
        sc.step()
        clock[0] = 1.5
        assert sc.step() is None
        assert fc.rejoins == []

    def test_queue_depth_is_a_second_up_signal(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.0, "queue_depth": 7.0}
        fc = FakeCluster([0])
        sc = make_scaler(fc, sig, clock, up_queue_depth=4.0,
                         standby=[HostInfo(1, loader_ranks=(2,))])
        sc.step()
        clock[0] = 1.1
        assert sc.step() == "up"

    def test_resize_reruns_placement(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.9}
        fc = FakeCluster([0])
        costs = LinkCosts.islands([[0, 1]], 8e9, 1e9)
        sc = Autoscaler(
            fc, standby=[HostInfo(1, loader_ranks=(2,))],
            policy=AutoscalerPolicy(sustain_s=0.0, cooldown_s=0.0),
            metrics=Metrics(), clock=lambda: clock[0],
            signal=lambda: dict(sig), link_costs=costs,
        )
        assert sc.last_placement is None
        clock[0] = 0.1
        assert sc.step() == "up"
        assert sc.last_placement is not None

    def test_failed_rejoin_keeps_the_reserve_entry(self):
        clock = [0.0]
        sig = {"stall_fraction": 0.9}

        class ExplodingCluster(FakeCluster):
            def rejoin_host(self, host):
                raise RuntimeError("channel died mid-rejoin")

        fc = ExplodingCluster([0])
        sc = make_scaler(fc, sig, clock, sustain_s=0.0, cooldown_s=0.0)
        sc._standby = [HostInfo(1, loader_ranks=(2,))]
        assert sc.step() is None
        assert [h.host_id for h in sc.standby] == [1]

    def test_windowed_signal_sees_a_fresh_burst(self):
        """The default signal is windowed: a long quiet history must not
        dilute a new burst below the band (the cumulative stall_fraction
        would)."""
        m = Metrics()
        fc = FakeCluster([0])
        clock = [1000.0]  # long elapsed history on the registry
        sc = Autoscaler(fc, metrics=m, clock=lambda: clock[0])
        clock[0] = 1001.0
        m.add_time("consumer.wait", 0.9)  # 0.9 s of stall in a 1 s window
        sig = sc._windowed_signal()
        assert sig["stall_fraction"] > 0.8

    def test_windowed_signal_excludes_admission_waits(self):
        """A tenant parked by its own byte budget is throttled, not
        starved: its admission wait must not read as ingest demand
        (one over-budget tenant could otherwise inflate the fleet)."""
        m = Metrics()
        fc = FakeCluster([0])
        clock = [0.0]
        sc = Autoscaler(fc, metrics=m, clock=lambda: clock[0])
        clock[0] = 1.0
        # The whole window's "stall" was spent at the admission gate
        # (the gate's wait is timed into consumer.wait by the loader).
        m.add_time("consumer.wait", 0.9)
        m.add_time("serve.admission_wait", 0.9)
        sig = sc._windowed_signal()
        assert sig["stall_fraction"] < 0.05


class TestDrainHost:
    def test_drain_floor_refuses_last_loader_host(self):
        m = Metrics()
        sup = ClusterSupervisor(loader_view([0]), metrics=m)
        ec = ElasticCluster(sup, metrics=m)
        with pytest.raises(DDLError):
            ec.drain_host(0)

    def test_drain_unknown_host_raises(self):
        sup = ClusterSupervisor(loader_view([0, 1]), metrics=Metrics())
        ec = ElasticCluster(sup, metrics=Metrics())
        with pytest.raises(KeyError):
            ec.drain_host(7)

    def test_drain_shrinks_view_and_returns_standby_info(self):
        m = Metrics()
        sup = ClusterSupervisor(loader_view([0, 1]), metrics=m)
        ec = ElasticCluster(sup, metrics=m)
        info = ec.drain_host(1)
        assert info.host_id == 1 and info.loader_ranks == (2,)
        assert [h.host_id for h in sup.view.hosts] == [0]
        assert m.counter("cluster.host_drains") == 1
        # A PLANNED departure must never inflate the failure counter
        # alerting keys on.
        assert m.counter("cluster.host_losses") == 0
        # The drained host's shards moved to the survivor.
        assert sup.view.ranges_of(0) and not sup.view.ranges_of(1)
        # And the round trip: rejoin re-admits it at a fresh fence.
        epoch = sup.view.epoch
        ec.rejoin_host(info)
        assert sup.view.epoch == epoch + 1
        assert [h.host_id for h in sup.view.hosts] == [0, 1]


# ---------------------------------------------------------------------------
# Chaos: the two new fault kinds (tier-1 matrix rows)
# ---------------------------------------------------------------------------


class TestServeFaults:
    def test_tenant_burst_charges_the_burster_not_the_neighbour(self):
        """TENANT_BURST at serve.admit: the bursting tenant absorbs its
        own phantom bytes (waits out replenish rounds) while the
        neighbour's admissions proceed untouched — the isolation
        property the tenancy chaos leg rides."""
        m = Metrics()
        s = FairShareScheduler(quantum_bytes=1 << 20, metrics=m)
        s.register(TenantSpec("burster"))     # index 0
        s.register(TenantSpec("neighbour"))   # index 1
        plan = FaultPlan([
            FaultSpec("serve.admit", FaultKind.TENANT_BURST,
                      at=1, producer_idx=0, param=float(4 << 20)),
        ])
        with faults.armed(plan):
            s.admit("burster", 5.0)   # absorbs the 4 MiB phantom spike
            s.admit("neighbour", 1.0)
        assert plan.fired and plan.fired[0][1] == "tenant_burst"
        assert m.counter("serve.tenant_bursts") == 1
        assert m.counter("ingest.burster.bursts") == 1
        assert m.counter("ingest.neighbour.bursts") == 0
        # The burster recovered via replenish rounds, not a timeout.
        assert m.counter("serve.rounds") >= 1

    def test_tenant_burst_respects_producer_idx_selection(self):
        s = FairShareScheduler(metrics=Metrics())
        s.register(TenantSpec("a"))  # index 0
        s.register(TenantSpec("b"))  # index 1
        plan = FaultPlan([
            FaultSpec("serve.admit", FaultKind.TENANT_BURST,
                      producer_idx=1, param=1024.0),
        ])
        with faults.armed(plan):
            s.admit("a", 1.0)
        assert plan.fired == []  # tenant 0's admit never matches idx 1

    def test_scale_decision_delay_slows_but_never_corrupts(self):
        """SCALE_DECISION_DELAY at serve.scale: the decision lands late
        (param seconds) but is the SAME decision."""
        clock = [0.0]
        sig = {"stall_fraction": 0.9}
        fc = FakeCluster([0])
        sc = make_scaler(fc, sig, clock, sustain_s=0.0, cooldown_s=0.0,
                         standby=[HostInfo(1, loader_ranks=(2,))])
        plan = FaultPlan([
            FaultSpec("serve.scale", FaultKind.SCALE_DECISION_DELAY,
                      at=1, param=0.15),
        ])
        clock[0] = 0.1
        t0 = time.perf_counter()
        with faults.armed(plan):
            out = sc.step()
        assert time.perf_counter() - t0 >= 0.15
        assert out == "up" and fc.rejoins == [1]
        assert plan.fired[0][1] == "scale_decision_delay"

    def test_burst_exception_carries_bytes(self):
        e = TenantBurst("boom", burst_bytes=123.0)
        assert e.burst_bytes == 123.0


# ---------------------------------------------------------------------------
# Fairness: concurrent consumers over the shared scheduler (the PR 9
# pool-test gap: rotation fairness with MORE than one consumer).
# ---------------------------------------------------------------------------


class TestConcurrentConsumerFairness:
    def test_two_tenants_skewed_demand_neither_starves(self):
        """Two REAL loaders — separate envs, one shared FairShareScheduler
        — with heavily skewed demand: the hog wants 4x the windows and
        polls as fast as it can, under a byte budget; the meek tenant is
        unbudgeted.  Neither starves past its budget: the meek stream
        completes promptly (well before the throttled hog, with zero
        admission timeouts) and the hog's end-to-end rate provably
        respects its byte budget THROUGH the loader binding — the
        enforcement is at the ring-acquire seam, not advisory.  (The
        strict per-round interleave bound is the deterministic
        slot-budget unit above; wall-clock thread timing can't pin it.)
        """
        m = Metrics()
        ctl = AdmissionController(
            scheduler=FairShareScheduler(
                quantum_bytes=ROWS * VALS * 4, metrics=m
            ),
            metrics=m,
        )
        window_bytes = ROWS * VALS * 4  # float32 windows: 128 B
        budget = 8.0 * window_bytes  # hog capped at ~8 windows/s
        hog = ctl.register(TenantSpec("hog", byte_budget_per_s=budget))
        meek = ctl.register(TenantSpec("meek"))
        n_meek = 6
        n_hog = 4 * n_meek
        done_t = {}
        errors = []

        def run_tenant(tenant, n_epochs):
            @distributed_dataloader(n_producers=2, mode="thread")
            def main(env):
                loader = DistributedDataLoader(
                    PatternProducer(), batch_size=ROWS,
                    connection=env.connection, n_epochs=n_epochs,
                    output="numpy", timeout_s=30.0, metrics=m,
                )
                tenant.bind(loader)
                wins = []
                for _ in range(n_epochs):
                    for (win,) in loader:
                        wins.append(win.copy())
                        loader.mark(Marker.END_OF_BATCH)
                    loader.mark(Marker.END_OF_EPOCH)
                return wins

            try:
                t0 = time.monotonic()
                assert_pattern_windows(main())
                done_t[tenant.name] = time.monotonic() - t0
            except (ShutdownRequested, KeyboardInterrupt):
                raise
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append((tenant.name, e))

        th = threading.Thread(target=run_tenant, args=(hog, n_hog))
        tm = threading.Thread(target=run_tenant, args=(meek, n_meek))
        th.start(), tm.start()
        tm.join(60.0), th.join(60.0)
        assert errors == [], errors
        assert m.counter("ingest.hog.windows") == n_hog
        assert m.counter("ingest.meek.windows") == n_meek
        assert m.counter("ingest.hog.bytes") == n_hog * window_bytes
        # The hog's byte budget bit: 24 windows at 8 windows/s of budget
        # (1 s of initial burst allowance) cannot finish in under ~2 s.
        floor_s = (n_hog * window_bytes - budget) / budget * 0.5
        assert done_t["hog"] >= floor_s, done_t
        # The meek tenant was never starved behind the hog's demand: it
        # finished long before the budget-throttled hog.
        assert done_t["meek"] < done_t["hog"], done_t
        # And its admission waits stayed trivial (no DRR round ever
        # parked it behind the hog's backlog for long).
        assert m.timer("ingest.meek.admission_wait").total_s < 1.0

    def test_fast_forward_is_not_admitted_or_charged(self):
        """Checkpoint-resume replay discards windows the tenant never
        receives: it must neither pass the admission gate nor charge
        the tenant's budget/counters — a byte-budgeted tenant would
        otherwise spend ~history/budget wall time replaying."""
        m = Metrics()
        ctl = AdmissionController(metrics=m)
        window_bytes = ROWS * VALS * 4
        # Budget = 1 window/s: charging 4 replayed windows would park
        # the first REAL admit for seconds; the run must stay instant.
        tenant = ctl.register(
            TenantSpec("resume", byte_budget_per_s=float(window_bytes))
        )

        @distributed_dataloader(n_producers=2, mode="thread")
        def main(env):
            loader = DistributedDataLoader(
                PatternProducer(), batch_size=ROWS,
                connection=env.connection, n_epochs=8,
                output="numpy", timeout_s=30.0, metrics=m,
            )
            tenant.bind(loader)
            t0 = time.monotonic()
            loader.fast_forward(4)
            (win,) = loader[0]  # the first SERVED window is admitted
            loader.mark(Marker.END_OF_BATCH)
            dt = time.monotonic() - t0
            loader.shutdown()
            return dt

        dt = main()
        assert dt < 2.0, f"resume replay was rate-limited ({dt:.2f}s)"
        assert m.counter("consumer.windows_skipped") == 4
        # Only the served window reached the tenant's ledger.
        assert m.counter("ingest.resume.windows") == 1
        assert m.counter("ingest.resume.bytes") == window_bytes

    def test_admission_spends_from_the_acquire_timeout_budget(self):
        """One acquisition, ONE timeout_s: an admission wait consumes
        from the same budget the ring acquire gets, so a throttled
        tenant cannot silently double the documented stall ceiling."""
        m = Metrics()

        class SlowGate:
            def admit(self, timeout_s):
                time.sleep(0.4)  # eats most of the 0.6 s budget

            def note_served(self, nbytes):
                pass

        @distributed_dataloader(n_producers=1, mode="thread")
        def main(env):
            # fill_latency 2 s: the producer cannot commit within the
            # budget, so the acquire must exhaust the REMAINDER only.
            loader = DistributedDataLoader(
                PatternProducer(2.0), batch_size=ROWS,
                connection=env.connection, n_epochs=1,
                output="numpy", timeout_s=0.6, metrics=m,
            )
            loader.bind_admission(SlowGate())
            t0 = time.monotonic()
            with pytest.raises(StallTimeoutError):
                loader[0]
            dt = time.monotonic() - t0
            loader.shutdown()
            return dt

        dt = main()
        assert 0.4 <= dt < 1.1, (
            f"acquisition took {dt:.2f}s — the admission wait did not "
            "spend from the ring acquire's timeout budget"
        )

    def test_admission_preserves_byte_identity(self):
        """The gate schedules acquisitions; it must never change data —
        admission-on and admission-off streams are byte-identical."""

        def run(with_admission):
            m = Metrics()
            ctl = AdmissionController(metrics=m) if with_admission else None

            @distributed_dataloader(n_producers=2, mode="thread")
            def main(env):
                loader = DistributedDataLoader(
                    PatternProducer(), batch_size=ROWS,
                    connection=env.connection, n_epochs=6,
                    output="numpy", timeout_s=30.0, metrics=m,
                )
                if ctl is not None:
                    ctl.register(TenantSpec("only")).bind(loader)
                out = []
                for _ in range(6):
                    for (win,) in loader:
                        out.append(win.copy())
                        loader.mark(Marker.END_OF_BATCH)
                    loader.mark(Marker.END_OF_EPOCH)
                return out

            return main()

        gated, free = run(True), run(False)
        assert len(gated) == len(free)
        for a, b in zip(gated, free):
            np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# E2E: autoscaler grows a live pipeline's pool mid-stream
# ---------------------------------------------------------------------------


class TestAutoscalerE2E:
    def test_scale_up_joins_standby_host_mid_stream_byte_identical(self):
        """4-producer THREAD env; view starts with hosts {0,1} and hosts
        {2,3} standing by (their producers run from t0, filling rings
        nobody drains).  A forced demand signal scales the pool up
        mid-stream; the loader rotates onto the new rings at the next
        boundary and every window — old pool or new — arrives intact."""
        m = Metrics()
        n_epochs = 12

        @distributed_dataloader(n_producers=4, mode="thread")
        def main(env):
            view = ClusterView.bootstrap(
                [HostInfo(0, loader_ranks=(1,)),
                 HostInfo(1, loader_ranks=(2,))],
                n_shards=8,
            )
            sup = ClusterSupervisor(view, lease_s=60.0, metrics=m)
            elastic = ElasticCluster(sup, metrics=m)
            loader = DistributedDataLoader(
                PatternProducer(), batch_size=ROWS,
                connection=env.connection, n_epochs=n_epochs,
                output="numpy", timeout_s=30.0, metrics=m,
                cluster=elastic,
            )
            sig = {"stall_fraction": 0.0}
            sc = Autoscaler(
                elastic,
                standby=[HostInfo(2, loader_ranks=(3,)),
                         HostInfo(3, loader_ranks=(4,))],
                policy=AutoscalerPolicy(sustain_s=0.0, cooldown_s=0.0),
                metrics=m, signal=lambda: dict(sig),
            )
            wins, targets = [], set()
            for ep in range(n_epochs):
                for (win,) in loader:
                    wins.append(win.copy())
                    targets.add(int(win.ravel()[0] // 1000))
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
                if ep == 3:
                    sig["stall_fraction"] = 0.9  # the burst arrives
                    assert sc.step() == "up"
                    sig["stall_fraction"] = 0.0
            return wins, targets

        wins, targets = main()
        assert len(wins) == n_epochs
        assert_pattern_windows(wins)
        # The standby host's ring really entered rotation mid-stream.
        assert 3 in targets, targets
        assert m.counter("serve.scale_ups") == 1
        assert m.counter("consumer.pool_updates") >= 2
        assert m.gauge("serve.pool_hosts") == 3
