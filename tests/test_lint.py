"""The lint gate: ddl-lint self-test + zero-findings gate over the tree.

Two halves:

- **Self-test**: a fixture snippet per check, each containing exactly one
  violation, asserting every ``DDL0xx`` code actually fires (a silently
  dead checker would otherwise let the gate rot into a no-op), plus
  clean counterparts asserting the checkers stay quiet on compliant
  code, plus suppression/config-layer tests.
- **Gate**: ``run_paths(["ddl_tpu", "tests"])`` must return zero
  findings — reintroducing any violation fails the tier-1 suite.
"""

import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:  # tools.* import under any pytest cwd
    sys.path.insert(0, str(REPO_ROOT))

from tools.ddl_lint import ALL_CODES, LintConfig, run_paths  # noqa: E402
from tools.ddl_lint.checkers import REGISTRY  # noqa: E402
from tools.ddl_lint.config import _parse_toml_subset, load_config  # noqa: E402

# One snippet per code; each must trigger EXACTLY its own code (plus
# whatever other codes the same hazard legitimately implies — listed in
# EXPECTED_EXTRA below).
VIOLATIONS = {
    "DDL001": """
        import jax

        @jax.jit
        def step(x):
            print(x)          # host I/O at trace time
            return x + 1
    """,
    "DDL002": """
        import jax

        seen = []

        @jax.jit
        def step(x):
            seen.append(x)    # tracer leaks into post-trace python
            return x + 1
    """,
    "DDL003": """
        import jax

        def augment(batches):
            out = []
            for b in batches:
                k = jax.random.PRNGKey(0)   # same key every iteration
                out.append(jax.random.normal(k, b.shape) + b)
            return out
    """,
    "DDL004": """
        import time

        def wait_for_peer(path):
            while True:               # no deadline, no shutdown check
                if _exists(path):
                    break
                time.sleep(0.01)
    """,
    "DDL005": """
        import time

        class DistributedDataLoader:
            def _acquire_current(self):
                while not self._ring().poll_drain_ready():
                    time.sleep(0.001)   # dead device time per window
    """,
    "DDL006": """
        import threading

        _build_lock = threading.Lock()
        _sweep_lock = threading.Lock()

        def rebuild():
            with _sweep_lock:
                with _build_lock:       # inverts declared hierarchy
                    pass
    """,
    "DDL024": """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()   # invisible to LOCK_ORDER
    """,
    "DDL007": """
        def teardown(ch):
            try:
                ch.close()
            except Exception:       # swallows ShutdownRequested
                pass
    """,
    "DDL008": """
        import ctypes

        lib = ctypes.CDLL("libfoo.so")
        lib.foo_create.restype = ctypes.c_void_p
        lib.foo_create.argtypes = [ctypes.c_char_p]
        lib.foo_close.argtypes = [ctypes.c_void_p]   # no restype
    """,
    "DDL009": """
        import enum

        class Msg(enum.Enum):
            DATA = 1
            EOF = 2
            ABORT = 3

        def dispatch(m):
            if m is Msg.DATA:
                return "d"
            elif m is Msg.EOF:
                return "e"
            # no ABORT branch, no else: silently dropped
    """,
    "DDL010": """
        import jax

        def run(batches, f):
            out = []
            for b in batches:
                out.append(jax.jit(f)(b))   # re-wrap per iteration
            return out
    """,
    "DDL011": """
        import numpy as np

        class DeviceIngestor:
            def put_batch(self, batch, splits):
                staged = np.array(batch, copy=True)  # fresh per-batch copy
                return self._transfer(staged)
    """,
    "DDL012": """
        def drain(q, done, worker):
            done.wait()          # parks forever if the peer dies
            worker.join()        # ditto
            return q.get()       # ditto (empty queue)
    """,
    "DDL013": """
        _shard_cache = {}

        def decoded(path):
            if path not in _shard_cache:
                _shard_cache[path] = _load(path)   # append-only memo
            return _shard_cache[path]
    """,
    "DDL014": """
        import jax

        def forward(params, x, layers):
            layer_fn = jax.checkpoint(_layer)   # silent full recompute
            for layer in layers:
                x = layer_fn(x, layer)
            return x
    """,
    "DDL015": """
        import numpy as np

        class FileShardProducer:
            def _load_next(self, my_ary):
                arr = self._shard()
                perm = self._rng.permutation(len(arr))
                np.copyto(my_ary, arr[perm])   # fancy-index temp + copy
    """,
    "DDL016": """
        import jax
        import numpy as np

        class IciDistributor:
            def distribute(self, block):
                host = jax.device_get(block)   # D2H round-trip per window
                return self._fan_out(host)
    """,
    "DDL017": """
        import jax

        def make_train_step(loss_fn, optimizer):
            def apply_step(params, opt_state, batch):
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
                updates, opt_state = optimizer.update(grads, opt_state)
                return params, opt_state, loss

            return jax.jit(apply_step)   # params + opt state undonated
    """,
    "DDL018": """
        import time

        class ClusterSupervisor:
            def run(self):
                while self._live:
                    self.sweep()         # no deadline, no lease expiry
                    time.sleep(0.5)
    """,
    "DDL019": """
        class FairShareScheduler:
            def admit(self, name, timeout_s):
                for t in self._tenants.values():
                    t.granted.wait(0.05)   # per-tenant wait fan-out
    """,
    "DDL020": """
        import jax

        class Trainer:
            def _fused_stream_loop(self, loader, stream, state, step):
                for win in stream:
                    jax.block_until_ready(win)   # exposes the transfer
                    state, losses = step(state, win)
                    self.losses.append(float(losses.mean()))  # sync

        class IciDistributor:
            def _distribute_planned(self, ticket):
                return fanout_wait(ticket, sync=True)  # forced wait
    """,
    "DDL021": """
        class ThreadExchangeShuffler:
            def _encode_lane(self, rows):
                # decode-then-requantize: the fp32 temp between encode
                # and send that erases the wire win
                raw = decode_window(rows, None, rows.shape, "f4", "int8")
                return pack_rows(raw, "int8")

        class CodecBackend:
            def open(self, path):
                data = self.inner.open(path).read()
                return self.codec.decode_bytes(data)   # unbounded decode
    """,
    "DDL022": """
        import json

        import numpy as np

        class LoaderCheckpoint:
            def save(self, path):
                with open(path, "w") as f:   # torn on any mid-write crash
                    json.dump(self.__dict__, f)

        def save_train_state(state, path):
            np.save(path, state.params)      # straight to the final path
    """,
    "DDL023": """
        import collections

        class SpanLog:
            def __init__(self):
                self._events = collections.deque()   # no maxlen bound

            def record(self, ev):
                self._events.append(ev)              # grows per event

        class PrefetchIterator:
            def __next__(self):
                for sample in self._batch:
                    obs_spans.record("s", 1, 2, 0.0)  # span per SAMPLE
                return sample
    """,
    "DDL025": """
        class ElasticCluster:
            def _send_adoptions(self, view, suspend_exchange):
                for rank in view.loader_ranks():
                    msg = ShardAdoption(
                        ranges=view.ranges_of(1), view_epoch=view.epoch,
                    )
                    conn.send_control(rank - 1, msg)   # raw: lossy wire

            def _on_rank_respawned(self, rank):
                conn.channel.send(ReplayRequest(seq=0))  # raw, direct
    """,
    "DDL026": """
        class Autoscaler:
            def step(self):
                # direct poke through an attribute chain: unjournaled
                self.controller.scheduler.note_served("t0", 1 << 20)

        def rebalance(snapshot):
            s = FairShareScheduler(quantum_bytes=1 << 20)
            s.adopt_state(snapshot)          # local ctor, tainted name
            s.revoke_inflight(1.0)

        FairShareScheduler().register(spec)  # module-level drive-by
    """,
    "DDL027": """
        class DistributedDataLoader:
            def prefetch(self, depth=2):     # literal default pins knob
                it = PrefetchIterator(
                    self.windows(), self._ingestor, depth=4,
                )
                return it

        class Trainer:
            def fit(self, loader, *, prefetch_depth=8):  # kwonly literal
                pool = StagingPool(max_per_key=16)
                return loader
    """,
}

# A hazard snippet may legitimately imply a second code (none today, but
# the self-test structure tolerates it without weakening the exactness
# check for everyone else).
EXPECTED_EXTRA = {code: set() for code in VIOLATIONS}
# DDL006's inversion fixture necessarily constructs bare primitives (the
# checker keys on the lock_order variable names): DDL024 fires alongside.
EXPECTED_EXTRA["DDL006"] = {"DDL024"}

CLEAN = {
    "DDL001": """
        import jax

        @jax.jit
        def step(x):
            jax.debug.print("x={x}", x=x)   # sanctioned trace-safe print
            return x + 1

        def host_side(y):
            print(y)        # host code may print freely
            y.block_until_ready()
    """,
    "DDL003": """
        import jax

        def augment(key, batches):
            out = []
            for b in batches:
                key, sub = jax.random.split(key)   # carried key
                out.append(jax.random.normal(sub, b.shape) + b)
            return out
    """,
    "DDL004": """
        import time

        def wait_for_peer(path, timeout_s, ring):
            deadline = time.monotonic() + timeout_s
            while True:
                if ring.is_shutdown():
                    raise ShutdownRequested()
                if _exists(path):
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(path)
                time.sleep(0.01)
    """,
    "DDL006": """
        import threading

        # DDL006 keys on the VARIABLE names in config lock_order, so this
        # fixture needs bare primitives (suppressed: the construction rule
        # is DDL024's concern, tested by its own fixture pair).
        _build_lock = threading.Lock()   # ddl-lint: disable=DDL024
        _sweep_lock = threading.Lock()   # ddl-lint: disable=DDL024

        def rebuild():
            with _build_lock:
                with _sweep_lock:       # declared order: build -> sweep
                    pass
    """,
    "DDL024": """
        from ddl_tpu.concurrency import named_condition, named_lock

        _registry_lock = named_lock("cache.registry")

        class Pool:
            def __init__(self):
                self._cv = named_condition("staging.executor.cv")
    """,
    "DDL007": """
        def teardown(ch):
            try:
                ch.close()
            except OSError:             # narrowed: signals propagate
                pass

        def guarded(ch):
            try:
                ch.close()
            except (ShutdownRequested, KeyboardInterrupt):
                raise
            except Exception:
                pass
    """,
    "DDL009": """
        import enum

        class Msg(enum.Enum):
            DATA = 1
            EOF = 2

        def dispatch(m):
            if m is Msg.DATA:
                return "d"
            elif m is Msg.EOF:
                return "e"
            else:
                raise ValueError(m)

        def dispatch_exhaustive(m):
            if m is Msg.DATA:
                return "d"
            elif m is Msg.EOF:
                return "e"
    """,
    "DDL011": """
        import numpy as np

        class DeviceIngestor:
            def put_batch(self, batch, splits):
                buf = self._pool.acquire(batch.shape, batch.dtype)
                np.copyto(buf, batch)          # pooled staging: sanctioned
                self.inp.zeros_count += 0      # "np" substring, not numpy
                return self._transfer(self.inp.zeros(0) or buf)

        def host_side_prep(batch):
            return np.array(batch, copy=True)  # not a hot-path function
    """,
    "DDL012": """
        def drain(q, done, worker, cfg, xs):
            if not done.wait(timeout=5.0):      # bounded event wait
                raise TimeoutError("producer never signalled")
            worker.join(5.0)                    # bounded (positional)
            worker.join(timeout_s=2.0)          # bounded (keyword)
            sep = ", ".join(xs)                 # str.join has an argument
            color = cfg.get("color")            # dict.get has an argument
            return q.get(timeout=5.0), sep, color
    """,
    "DDL013": """
        _BUDGET = 8
        _shard_cache = {}          # evicted below: bounded
        _REGISTRY = {}             # grown only at import time: not runtime

        _REGISTRY["local"] = object()

        def decoded(path):
            if path not in _shard_cache:
                if len(_shard_cache) >= _BUDGET:
                    _shard_cache.pop(next(iter(_shard_cache)))
                _shard_cache[path] = _load(path)
            return _shard_cache[path]

        class Counters:
            def __init__(self):
                self._counts = {}

            def incr(self, name):
                self._counts[name] = self._counts.get(name, 0) + 1

            def reset(self):
                self._counts.clear()   # reset site: bounded
    """,
    "DDL014": """
        import jax

        def forward(params, x, layers):
            layer_fn = jax.checkpoint(
                _layer, policy=jax.checkpoint_policies.nothing_saveable
            )   # the default, SPELLED OUT
            for layer in layers:
                x = layer_fn(x, layer)
            return x

        def load_state(path):
            return jax.checkpoint.restore(path)  # not the remat transform
    """,
    "DDL015": """
        import numpy as np

        class FileShardProducer:
            def _load_next(self, my_ary):
                arr = self._shard()
                perm = self._rng.permutation(len(arr))
                arr.take(perm, axis=0, out=my_ary)   # write-once gather

        class StreamBank:
            def execute_function(self, my_ary):
                # basic slice = view source: one copy total, sanctioned
                np.copyto(my_ary, self._bank[self._off : self._off + 4])

        class TFRecordTokenProducer:
            def _fill(self, my_ary):
                flat = my_ary.reshape(-1)
                flat[:4] = self._buf[:4]       # slice into the view

        def host_side(my_ary, arr, perm):
            np.copyto(my_ary, arr[perm])       # not a fill function
    """,
    "DDL016": """
        import jax
        import numpy as np

        class IciDistributor:
            def distribute(self, block):
                plan = self.plan(block.shape, np.dtype(block.dtype))
                return self._fan_out(block, plan)   # stays on device

        def debug_dump(block):
            return np.asarray(block)   # not a distribution path
    """,
    "DDL017": """
        import functools

        import jax

        def make_train_step(loss_fn, optimizer, donate=True):
            def apply_step(params, opt_state, batch):
                return optimizer.update(params, opt_state, batch)

            def init_fn(params):
                # compiled-copy idiom: fresh donat-able buffers (exempt)
                return jax.jit(lambda t: t, out_shardings=None)(params)

            step = functools.partial(
                jax.jit, donate_argnums=(0, 1) if donate else ()
            )(apply_step)
            return init_fn, step

        def make_multistep(loss_fn, optimizer):
            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def _run(params, opt_state, batch):
                return optimizer.update(params, opt_state, batch)

            return _run

        def helper_outside_builders(fn):
            return jax.jit(fn)   # not a configured train-step builder
    """,
    "DDL018": """
        import time

        class ClusterSupervisor:
            def run(self):
                deadline = time.monotonic() + self.budget_s
                while time.monotonic() < deadline:   # bounded sweep loop
                    self.sweep()

            def _run(self):
                while not self._stop.wait(self.poll_interval_s):
                    self.sweep()   # timed stop-event wait bounds it

            def wait_for_epoch(self, epoch):
                while self.view.epoch < epoch:
                    if self.leases.expired():   # lease query bounds it
                        break

        def helper_outside_cluster(sup):
            while True:
                sup.sweep()   # not a configured cluster loop
    """,
    "DDL019": """
        class FairShareScheduler:
            def admit(self, name, timeout_s):
                deadline = self._clock() + timeout_s
                while True:
                    states = []
                    for t in self._tenants.values():
                        states.append(t.snapshot())   # non-blocking body
                    if self._grantable(name, states):
                        break
                    if self._clock() >= deadline:
                        raise TimeoutError(name)
                    self._cond.wait(0.05)   # ONE bounded wait per pass

        class Autoscaler:
            def _helper_outside_config(self):
                for t in self._tenants:
                    t.done.wait(1.0)   # not a configured serve loop
    """,
    "DDL020": """
        import jax

        class Trainer:
            def _fused_stream_loop(self, loader, stream, state, step):
                pending = None
                for win in stream:
                    if pending is not None and not _value_ready(pending):
                        self.overlap += 1       # non-blocking probe: clean
                    state, losses = step(state, win)
                    loader.gate_release_on(losses)
                    nbytes = float(win.nbytes)  # host arithmetic: clean
                    self.bytes += nbytes
                    pending = losses
                return state

            def _sync_stream_loop(self, stream, state, step):
                for win in stream:
                    jax.block_until_ready(win)  # not a configured function

        class IciDistributor:
            def _distribute_planned(self, ticket):
                return fanout_wait(ticket)      # data-dependence wait: clean
    """,
    "DDL021": """
        class ThreadExchangeShuffler:
            def _encode_lane(self, rows):
                return pack_rows(rows, "int8")   # encode from RAW rows

            def _decode_lane(self, rows):
                # decode at the consumer edge, never re-encoded
                return unpack_rows(rows, max_output=1 << 20)

        class CodecBackend:
            def open(self, path):
                data = self.inner.open(path).read()
                return self.codec.decode_bytes(data, max_output=1 << 30)

        def helper_outside_wire_path(rows):
            raw = decode_window(rows, None, rows.shape, "f4", "int8")
            return pack_rows(raw, "int8")   # not a configured function
    """,
    "DDL022": """
        import json

        class LoaderCheckpoint:
            def save(self, path):
                atomic_file_write(          # the sanctioned primitive
                    path, json.dumps(self.__dict__).encode()
                )

            @staticmethod
            def load(path):
                with open(path) as f:       # reads stay clean
                    return json.load(f)

        def save_train_state(state, path):
            blob = _serialize(state)
            atomic_file_write(path, blob)

        def helper_outside_config(path, data):
            with open(path, "w") as f:      # not a configured function
                f.write(data)
    """,
    "DDL023": """
        import collections

        class SpanLog:
            def __init__(self):
                self._events = collections.deque(maxlen=1024)  # bounded
                self._shipped = 0

            def record(self, ev):
                self._events.append(ev)     # bounded ring: drops oldest

        class PrefetchIterator:
            def __next__(self):
                obs_spans.record("w", 1, 2, 0.0)   # per WINDOW: outside
                for sample in self._batch:
                    self._count += 1               # plain work is fine
                return sample

        class NotABufferClass:
            def __init__(self):
                self._items = []            # not in the configured set

            def add(self, x):
                self._items.append(x)
    """,
    "DDL025": """
        class ElasticCluster:
            def _send_adoptions(self, view, suspend_exchange):
                for rank in view.loader_ranks():
                    msg = ShardAdoption(
                        ranges=view.ranges_of(1), view_epoch=view.epoch,
                    )
                    conn.send_control_acked(rank - 1, msg)  # the seam

            def _on_rank_respawned(self, rank):
                conn.send_control_acked(rank - 1, ReplayRequest(seq=0))

        def helper_outside_config(conn, rank):
            conn.send_control(rank, ShardAdoption(ranges=(), view_epoch=0))
    """,
    "DDL026": """
        class Tenant:
            def note_served(self, nbytes):
                # sanctioned: the tenancy facade IS the seam
                self.controller.scheduler.note_served(self.name, nbytes)

        class IngestFabric:
            def _apply(self, payload):
                self.scheduler.admit(payload.job_id, payload.timeout_s)

        def read_only(sched):
            state = sched.export_state()     # reads are unrestricted
            return state["tenants"]

        def other_registry(plugins, spec):
            plugins.register(spec)           # not a scheduler receiver
    """,
    "DDL027": """
        class DistributedDataLoader:
            def prefetch(self, depth=None):  # None = read the registry
                if depth is None:
                    depth = envspec.get("DDL_TPU_PREFETCH_DEPTH")
                return PrefetchIterator(
                    self.windows(), self._ingestor, depth=depth,
                )

        class Trainer:
            def fit(self, loader, *, prefetch_depth=None):
                resolved = config.prefetch_depth
                loader.prefetch(depth=resolved)
                return loader

        def unconfigured_helper():
            # not in tuned_knob_functions: literals are fine here
            return PrefetchIterator(iter([]), ing, depth=3)
    """,
}


def lint_snippet(tmp_path, code, snippet, config=None):
    f = tmp_path / f"fixture_{code.lower()}.py"
    f.write_text(textwrap.dedent(snippet))
    return run_paths([str(f)], config=config or LintConfig())


class TestSelfTest:
    def test_registry_covers_every_published_code(self):
        assert set(REGISTRY) == set(ALL_CODES)

    @pytest.mark.parametrize("code", sorted(VIOLATIONS))
    def test_each_code_fires_on_its_fixture(self, tmp_path, code):
        findings = lint_snippet(tmp_path, code, VIOLATIONS[code])
        fired = {f.code for f in findings}
        assert code in fired, f"{code} did not fire on its fixture"
        stray = fired - {code} - EXPECTED_EXTRA[code]
        assert not stray, f"unexpected extra findings {stray}: {findings}"

    @pytest.mark.parametrize("code", sorted(CLEAN))
    def test_clean_counterparts_stay_quiet(self, tmp_path, code):
        findings = lint_snippet(tmp_path, code, CLEAN[code])
        assert findings == [], findings

    def test_findings_carry_location_and_render(self, tmp_path):
        findings = lint_snippet(tmp_path, "DDL007", VIOLATIONS["DDL007"])
        f = findings[0]
        assert f.line > 1 and f.code == "DDL007"
        assert f"{f.path}:{f.line}" in f.render()

    def test_syntax_error_reports_ddl000(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def f(:\n")
        findings = run_paths([str(bad)], config=LintConfig())
        assert [f.code for f in findings] == ["DDL000"]

    def test_bare_baseexception_swallow_is_flagged(self, tmp_path):
        """`except BaseException: pass` must not exempt itself by naming
        the signal it swallows — protection has to be a distinct earlier
        handler or a re-raise."""
        src = """
            def teardown(ch):
                try:
                    ch.close()
                except BaseException:
                    pass
        """
        findings = lint_snippet(tmp_path, "DDL007", src)
        assert [f.code for f in findings] == ["DDL007"]

    def test_ddl013_instance_level_cache_is_flagged(self, tmp_path):
        """`self.attr = {}` grown across methods with no eviction fires
        too — the instance-scoped variant of the module-level fixture."""
        src = """
            class ShardIndex:
                def __init__(self):
                    self._by_path = {}

                def lookup(self, path):
                    entry = self._by_path.setdefault(path, _load(path))
                    return entry
        """
        findings = lint_snippet(tmp_path, "DDL013", src)
        assert [f.code for f in findings] == ["DDL013"]
        assert "ShardIndex._by_path" in findings[0].message

    def test_ddl013_rebind_inside_function_counts_as_reset(self, tmp_path):
        """A method that reassigns the dict (epoch-boundary reset) bounds
        it — the rebind is an eviction site, not a second definition."""
        src = """
            class WindowIndex:
                def __init__(self):
                    self._windows = {}

                def add(self, k, v):
                    self._windows[k] = v

                def roll_epoch(self):
                    self._windows = {}
        """
        findings = lint_snippet(tmp_path, "DDL013", src)
        assert findings == [], findings

    def test_ddl015_assignment_and_concat_forms_fire(self, tmp_path):
        """The slice-assignment spelling of the double copy fires too,
        including through a .reshape() of the materialized temp."""
        src = """
            import numpy as np

            class TFRecordTokenProducer:
                def _fill(self, my_ary):
                    chunks = self._chunks()
                    my_ary[:] = np.concatenate(chunks).reshape(4, 4)
        """
        findings = lint_snippet(tmp_path, "DDL015", src)
        assert [f.code for f in findings] == ["DDL015"]
        assert "concatenate" in findings[0].message

    def test_ddl015_respects_configured_fill_list(self, tmp_path):
        """A function outside producer_fill_functions stays clean — the
        check is repo policy (config'd hot list), not a global ban."""
        src = """
            import numpy as np

            class CustomProducer:
                def _fill(self, my_ary):
                    arr = self._shard()
                    np.copyto(my_ary, arr[self._perm()])
        """
        cfg = LintConfig(producer_fill_functions=["KnownProducer._fill"])
        findings = lint_snippet(tmp_path, "DDL015", src, config=cfg)
        assert findings == [], findings
        cfg = LintConfig(producer_fill_functions=["CustomProducer._fill"])
        findings = lint_snippet(tmp_path, "DDL015", src, config=cfg)
        assert [f.code for f in findings] == ["DDL015"]

    def test_ddl016_asarray_and_bound_device_get_fire(self, tmp_path):
        """Both host-round-trip spellings: a blocking np.asarray
        materialization and device_get through a bound jax handle
        (self._jax.device_get — how framework classes hold jax)."""
        src = """
            import numpy as np

            class IciDistributor:
                def _onto_mesh(self, ring_out, plan):
                    shards = [np.asarray(s.data)     # host materialize
                              for s in ring_out.addressable_shards]
                    return self._assemble(shards, plan)

                def put(self, arr, device_put):
                    block = device_put(arr, self.anchor)
                    return self._jax.device_get(block)   # D2H fetch
        """
        findings = lint_snippet(tmp_path, "DDL016", src)
        assert [f.code for f in findings] == ["DDL016", "DDL016"]
        assert "asarray" in findings[0].message
        assert "device_get" in findings[1].message

    def test_ddl016_respects_configured_device_path_list(self, tmp_path):
        """A function outside device_path_functions stays clean — the
        check is repo policy (config'd hot list), not a global ban on
        device_get."""
        src = """
            import jax

            class CustomTier:
                def spread(self, block):
                    return jax.device_get(block)
        """
        cfg = LintConfig(device_path_functions=["OtherTier.spread"])
        findings = lint_snippet(tmp_path, "DDL016", src, config=cfg)
        assert findings == [], findings
        cfg = LintConfig(device_path_functions=["CustomTier.spread"])
        findings = lint_snippet(tmp_path, "DDL016", src, config=cfg)
        assert [f.code for f in findings] == ["DDL016"]

    def test_ddl017_partial_and_decorator_forms(self, tmp_path):
        """Both jit-construction spellings the builders use are checked:
        a bare partial(jax.jit) missing donation fires, while donation
        on the partial (the builders' real form) passes — and a
        donation-less jit in a CONFIGURED method fires via the
        Class.method qualification."""
        src = """
            import functools

            import jax

            class StepFactory:
                def make_train_step(self, apply_step):
                    return functools.partial(jax.jit)(apply_step)
        """
        cfg = LintConfig(train_step_functions=["StepFactory.make_train_step"])
        findings = lint_snippet(tmp_path, "DDL017", src, config=cfg)
        assert [f.code for f in findings] == ["DDL017"]
        cfg = LintConfig(train_step_functions=["Other.make_train_step"])
        findings = lint_snippet(tmp_path, "DDL017", src, config=cfg)
        assert findings == [], findings

    def test_ddl017_explicit_empty_donation_passes(self, tmp_path):
        """donate_argnums=() is an explicit decision, not the hazard —
        only the OMITTED kwarg fires."""
        src = """
            import jax

            def make_train_step(apply_step):
                return jax.jit(apply_step, donate_argnums=())
        """
        findings = lint_snippet(tmp_path, "DDL017", src)
        assert findings == [], findings

    def test_ddl018_respects_configured_cluster_loop_list(self, tmp_path):
        """A loop outside cluster_loop_functions stays clean (the check
        is repo policy, not a global while-loop ban), and the deadline/
        lease vocabulary is what licenses a configured one."""
        src = """
            class CustomPlane:
                def pump(self):
                    while self._live:
                        self._drain_once()
        """
        cfg = LintConfig(cluster_loop_functions=["OtherPlane.pump"])
        findings = lint_snippet(tmp_path, "DDL018", src, config=cfg)
        assert findings == [], findings
        cfg = LintConfig(cluster_loop_functions=["CustomPlane.pump"])
        findings = lint_snippet(tmp_path, "DDL018", src, config=cfg)
        assert [f.code for f in findings] == ["DDL018"]

    def test_ddl018_timed_wait_and_lease_query_pass(self, tmp_path):
        """The two sanctioned bounding idioms the shipped supervisor
        uses: a timed stop-event wait, and a lease-table query; a
        deadline-free spin in the same configured class still fires."""
        src = """
            class ClusterSupervisor:
                def run(self):
                    while not self._stop.wait(self.poll_interval_s):
                        self.sweep()

                def _run(self):
                    while self.leases.expired() == []:
                        self.sweep()

                def wait_for_epoch(self, epoch):
                    while self.view.epoch < epoch:
                        self._spin_hint()   # unbounded: spins forever
        """
        findings = lint_snippet(tmp_path, "DDL018", src)
        assert [f.code for f in findings] == ["DDL018"]
        assert "wait_for_epoch" in findings[0].message

    def test_ddl019_respects_configured_serve_loop_list(self, tmp_path):
        """The fan-out ban is repo policy scoped to serve_loop_functions
        — the same wait-in-a-for shape outside the config stays clean,
        and even a TIMED per-tenant wait fires inside it (per-iteration
        timeouts multiply by the tenant count)."""
        src = """
            class CustomGate:
                def pump(self):
                    for t in self._tenants:
                        t.turn.wait(0.01)
        """
        cfg = LintConfig(serve_loop_functions=["OtherGate.pump"])
        findings = lint_snippet(tmp_path, "DDL019", src, config=cfg)
        assert findings == [], findings
        cfg = LintConfig(serve_loop_functions=["CustomGate.pump"])
        findings = lint_snippet(tmp_path, "DDL019", src, config=cfg)
        assert [f.code for f in findings] == ["DDL019"]

    def test_ddl019_sleep_and_join_fanouts_fire_while_dict_get_passes(
        self, tmp_path
    ):
        """time.sleep / .join inside the tenant loop are the same
        fan-out; dict .get() reads stay clean (snapshot-compute-act is
        the sanctioned shape)."""
        src = """
            import time

            class Autoscaler:
                def step(self):
                    for t in self._tenants:
                        time.sleep(0.01)

                def _run(self):
                    for t in self._threads:
                        t.join(1.0)
        """
        findings = lint_snippet(tmp_path, "DDL019", src)
        assert sorted(f.code for f in findings) == ["DDL019", "DDL019"]
        clean = """
            class Autoscaler:
                def step(self):
                    for name in self._tenants:
                        st = self._states.get(name)
                        if st is not None:
                            self._judge(st)
        """
        findings = lint_snippet(tmp_path, "DDL019", clean)
        assert findings == [], findings

    def test_ddl021_respects_configured_wire_path_list(self, tmp_path):
        """The decode-then-requantize ban is scoped to
        wire_path_functions — the same shape outside the config stays
        clean, and a directly NESTED decode inside an encode call fires
        without needing a named temp."""
        src = """
            class CustomWire:
                def send(self, rows):
                    return pack_rows(
                        decode_window(rows, None, rows.shape, "f4", "int8"),
                        "int8",
                    )
        """
        cfg = LintConfig(wire_path_functions=["OtherWire.send"])
        findings = lint_snippet(tmp_path, "DDL021", src, config=cfg)
        assert findings == [], findings
        cfg = LintConfig(wire_path_functions=["CustomWire.send"])
        findings = lint_snippet(tmp_path, "DDL021", src, config=cfg)
        assert [f.code for f in findings] == ["DDL021"]

    def test_ddl021_named_temp_alone_fires(self, tmp_path):
        """The canonical decode-then-requantize form — decode assigned
        to a local name, name fed to an encode call — must fire on its
        own (regression: the single-sweep walk visited statements in
        reverse source order and never saw the assignment first)."""
        src = """
            class ThreadExchangeShuffler:
                def _encode_lane(self, rows):
                    raw = decode_window(rows, None, rows.shape, "f4", "int8")
                    return pack_rows(raw, "int8")
        """
        findings = lint_snippet(tmp_path, "DDL021", src)
        assert [f.code for f in findings] == ["DDL021"]

    def test_ddl021_positional_bound_passes_kwargless_fires(self, tmp_path):
        """encode_bytes(data, 3) fills the positional bound slot —
        clean; compress(data) with neither kwarg nor second positional
        is unbounded — fires."""
        src = """
            import zlib

            class DataPusher:
                def _encode_and_commit(self, view):
                    a = self.codec.encode_bytes(view, 3)     # positional
                    b = zlib.compress(view)                  # unbounded
                    return a, b
        """
        findings = lint_snippet(tmp_path, "DDL021", src)
        assert [f.code for f in findings] == ["DDL021"]

    def test_ddl022_respects_configured_writer_list(self, tmp_path):
        """The bare-write ban is scoped to checkpoint_write_functions;
        pathlib in-place writers and write-mode kwargs fire too."""
        src = """
            class CustomCkpt:
                def persist(self, path, blob):
                    path.write_bytes(blob)

                def persist_kw(self, path, blob):
                    with open(path, mode="wb") as f:
                        f.write(blob)
        """
        cfg = LintConfig(checkpoint_write_functions=["OtherCkpt.persist"])
        findings = lint_snippet(tmp_path, "DDL022", src, config=cfg)
        assert findings == [], findings
        cfg = LintConfig(checkpoint_write_functions=[
            "CustomCkpt.persist", "CustomCkpt.persist_kw",
        ])
        findings = lint_snippet(tmp_path, "DDL022", src, config=cfg)
        assert sorted(f.code for f in findings) == ["DDL022", "DDL022"]

    def test_ddl022_read_and_nonliteral_mode_pass(self, tmp_path):
        """Reads, non-literal modes (never guessed), and writes inside
        a NESTED def (checked when IT is configured) stay clean."""
        src = """
            def save_train_state(state, path, mode):
                with open(path) as f:            # read
                    _ = f.read()
                with open(path, mode) as f:      # non-literal mode
                    _ = f
                def _inner(p, data):
                    with open(p, "w") as f:      # nested def: not this fn
                        f.write(data)
                return _inner
        """
        findings = lint_snippet(tmp_path, "DDL022", src)
        assert findings == [], findings

    def test_ddl023_respects_configured_lists(self, tmp_path):
        """Both halves are config-scoped: buffer classes and per-sample
        hot functions outside the lists stay quiet; inside, they fire."""
        src = """
            import collections

            class MyLog:
                def __init__(self):
                    self._ring = collections.deque()

                def note(self, ev):
                    self._ring.append(ev)

            class MyFeed:
                def pop(self):
                    for s in self._batch:
                        obs_spans.mark("s", 1, 2)
                    return s
        """
        findings = lint_snippet(tmp_path, "DDL023", src)
        assert findings == [], findings  # neither name is configured
        cfg = LintConfig(
            obs_event_buffer_classes=["MyLog"],
            per_sample_hot_functions=["MyFeed.pop"],
        )
        findings = lint_snippet(tmp_path, "DDL023", src, config=cfg)
        assert sorted(f.code for f in findings) == ["DDL023", "DDL023"]

    def test_ddl023_sees_annotated_assignments(self, tmp_path):
        """The shipped buffer classes construct their rings via
        ANNOTATED assignments — an Assign-only pass would verify
        nothing about the real tree (review catch, this PR)."""
        src = """
            import collections

            class SpanLog:
                def __init__(self):
                    self._events: collections.deque = collections.deque()

                def record(self, ev):
                    self._events.append(ev)
        """
        findings = lint_snippet(tmp_path, "DDL023", src)
        assert [f.code for f in findings] == ["DDL023"]
        bounded = src.replace(
            "collections.deque()", "collections.deque(maxlen=8)"
        )
        assert lint_snippet(tmp_path, "DDL023", bounded) == []

    def test_ddl023_reconstruction_must_stay_bounded(self, tmp_path):
        """A buffer bounded in __init__ but REBUILT unbounded elsewhere
        (a reset() that forgets the maxlen) is still a finding."""
        src = """
            import collections

            class SpanLog:
                def __init__(self):
                    self._events = collections.deque(maxlen=64)

                def clear(self):
                    self._events = collections.deque()   # bound lost

                def record(self, ev):
                    self._events.append(ev)
        """
        findings = lint_snippet(tmp_path, "DDL023", src)
        assert [f.code for f in findings] == ["DDL023"]

    def test_nonexistent_config_file_is_an_error(self, tmp_path):
        f = tmp_path / "ok.py"
        f.write_text("x = 1\n")
        with pytest.raises(FileNotFoundError):
            run_paths([str(f)], config_file=str(tmp_path / "nope.toml"))

    def test_nonexistent_path_is_an_error_not_clean(self, tmp_path):
        """A typo'd path must fail loudly — a silent empty run would turn
        the gate into a permanent no-op that reports clean forever."""
        with pytest.raises(FileNotFoundError):
            run_paths([str(tmp_path / "no_such_dir")], config=LintConfig())

    def test_same_named_unrelated_enums_do_not_false_positive(
        self, tmp_path
    ):
        """Two different enums sharing a bare class name must not union
        their members: an exhaustive dispatch over one of them stays
        clean (the ambiguous name is dropped from DDL009 checking)."""
        (tmp_path / "a.py").write_text(textwrap.dedent("""
            import enum

            class Msg(enum.Enum):
                DATA = 1
                EOF = 2

            def dispatch(m):
                if m is Msg.DATA:
                    return "d"
                elif m is Msg.EOF:
                    return "e"
        """))
        (tmp_path / "b.py").write_text(textwrap.dedent("""
            import enum

            class Msg(enum.Enum):
                PING = 1
                PONG = 2
        """))
        assert run_paths([str(tmp_path)], config=LintConfig()) == []

    def test_ddl008_audits_stored_lib_handle_calls(self, tmp_path):
        """The repo's real call idiom — `self._lib = _load_native()` then
        `self._lib.fn(...)` — must be audited, not just bare CDLL vars."""
        f = tmp_path / "handle.py"
        f.write_text(textwrap.dedent("""
            import ctypes

            def _load():
                lib = ctypes.CDLL("libx.so")
                lib.x_open.restype = ctypes.c_void_p
                lib.x_open.argtypes = [ctypes.c_char_p]
                return lib

            class Ring:
                def __init__(self):
                    self._lib = _load()
                    self._h = self._lib.x_open(b"n")

                def poke(self):
                    self._lib.x_poke(self._h)   # never declared
        """))
        findings = run_paths([str(f)], config=LintConfig())
        assert [f.code for f in findings] == ["DDL008"]
        assert "x_poke" in findings[0].message


class TestSuppressionAndConfig:
    def test_inline_disable_comment(self, tmp_path):
        src = VIOLATIONS["DDL007"].replace(
            "except Exception:", "except Exception:  # ddl-lint: disable=DDL007"
        )
        assert lint_snippet(tmp_path, "DDL007", src) == []

    def test_inline_disable_other_code_does_not_mask(self, tmp_path):
        src = VIOLATIONS["DDL007"].replace(
            "except Exception:", "except Exception:  # ddl-lint: disable=DDL001"
        )
        findings = lint_snippet(tmp_path, "DDL007", src)
        assert [f.code for f in findings] == ["DDL007"]

    def test_pragma_inside_string_is_not_a_suppression(self, tmp_path):
        src = VIOLATIONS["DDL007"] + (
            '\n        PRAGMA = "# ddl-lint: disable=DDL007"\n'
        )
        findings = lint_snippet(tmp_path, "DDL007", src)
        assert [f.code for f in findings] == ["DDL007"]

    def test_config_disable(self, tmp_path):
        cfg = LintConfig(disable=["DDL007"])
        assert lint_snippet(tmp_path, "DDL007", VIOLATIONS["DDL007"], cfg) == []

    def test_per_path_ignores(self, tmp_path):
        sub = tmp_path / "vendored"
        sub.mkdir()
        f = sub / "third_party.py"
        f.write_text(textwrap.dedent(VIOLATIONS["DDL007"]))
        cfg = LintConfig(per_path_ignores={str(sub): ["DDL007"]})
        assert run_paths([str(f)], config=cfg) == []

    def test_toml_subset_parser_reads_our_section(self):
        tables = _parse_toml_subset(
            textwrap.dedent(
                """
                [project]
                name = "x"  # unrelated, any TOML allowed here

                [tool.ddl_lint]
                disable = [
                    "DDL001",  # inline comments inside arrays must parse
                    "DDL002",
                ]
                hot_path_classes = ["A", "B"]  # trailing comment
                lock_order = ["has#hash", "b"]

                [tool.ddl_lint.per_path_ignores]
                "tests/" = ["DDL005"]
                """
            )
        )
        assert tables["tool.ddl_lint"]["disable"] == ["DDL001", "DDL002"]
        assert tables["tool.ddl_lint"]["hot_path_classes"] == ["A", "B"]
        # `#` inside a quoted string is content, not a comment
        assert tables["tool.ddl_lint"]["lock_order"] == ["has#hash", "b"]
        assert tables["tool.ddl_lint.per_path_ignores"]["tests/"] == [
            "DDL005"
        ]

    def test_load_config_from_pyproject(self, tmp_path):
        py = tmp_path / "pyproject.toml"
        py.write_text(
            "[tool.ddl_lint]\n"
            'disable = ["DDL010"]\n'
            'lock_order = ["a_lock", "b_lock"]\n'
        )
        cfg = load_config(py)
        assert "DDL010" in cfg.disable
        assert cfg.lock_order == ["a_lock", "b_lock"]
        assert "DDL010" not in cfg.enabled_codes()

    def test_shipped_pyproject_loads_every_list_key(self):
        """Every configured checker list in the REPO's pyproject must
        survive load_config — a key parsed but never copied onto
        LintConfig silently reverts its checker to defaults (the
        wire_path_functions regression, PR 14)."""
        import dataclasses

        repo_cfg = load_config(REPO_ROOT / "pyproject.toml")
        raw = _parse_toml_subset(
            (REPO_ROOT / "pyproject.toml").read_text()
        ).get("tool.ddl_lint", {})
        field_names = {f.name for f in dataclasses.fields(repo_cfg)}
        for key, val in raw.items():
            if key in ("enable", "disable") or not isinstance(val, list):
                continue
            assert key in field_names, f"unknown [tool.ddl_lint] key {key}"
            assert getattr(repo_cfg, key) == list(val), (
                f"[tool.ddl_lint] {key} parsed from pyproject but not "
                "loaded onto LintConfig (add it to load_config)"
            )


class TestGate:
    def test_tree_is_clean(self):
        """THE gate: the shipped tree must lint clean.  Any reintroduced
        DDL0xx violation in ddl_tpu/ or tests/ fails tier-1 here."""
        findings = run_paths(
            [str(REPO_ROOT / "ddl_tpu"), str(REPO_ROOT / "tests")]
        )
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_gate_would_catch_a_reintroduction(self, tmp_path):
        """The gate's teeth, demonstrated end to end: a tree containing
        one known violation does NOT lint clean with the repo config."""
        victim = tmp_path / "regressed.py"
        victim.write_text(textwrap.dedent(VIOLATIONS["DDL008"]))
        findings = run_paths(
            [str(victim)],
            config_file=str(REPO_ROOT / "pyproject.toml"),
        )
        assert any(f.code == "DDL008" for f in findings)
