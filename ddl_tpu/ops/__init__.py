"""TPU kernels (Pallas) for the hot ops.

The reference ran no device compute at all (SURVEY §0: it is a data loader;
"no model code").  ddl_tpu's consumer side does, so the ops that dominate
its flagship training loop get hand-written TPU kernels where XLA's
automatic fusion leaves throughput on the table — flash attention being the
canonical case (the T×T score matrix must never round-trip HBM).

Everything here runs in Pallas ``interpret`` mode on CPU (used by the test
suite's virtual mesh) and compiles to Mosaic on real TPUs.
"""

from ddl_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_with_lse,
)

__all__ = ["flash_attention", "flash_attention_with_lse"]
