"""Pallas device-side global shuffle: the epoch exchange on the mesh.

``ThreadExchangeShuffler`` moves the two exchange lanes peer-to-peer on
the HOST — host memcpys and DCN hops on data that is about to be H2D'd
anyway (ROADMAP item 2).  These kernels run the same permutation
exchange ON-DEVICE: each instance's exchange block (lane A + lane B,
``2 * half`` rows) lands once on its ring device, and two remote-DMA
steps move lane A forward along the shared permutation (``i -> p[i]``)
and lane B backward (``i -> pinv[i]``) — byte-identical to the host
rendezvous exchange because both sides derive the permutation from
``exchange_permutation(n, seed, round)`` (ddl_tpu.shuffle).

Kernel shape constraints (the ``ops/ici_fanout.py`` discipline):

- **Permutation-shaped steps.**  Interpret mode (the CPU virtual-mesh
  tier-1 path) discharges a remote DMA as a collective: every device in
  the axis must execute every ``dma_start`` in lockstep, and each
  step's target map must deliver exactly one copy per device.  An
  exchange permutation is bijective (and a derangement), so both lane
  steps are valid target maps by construction — no clamping or sink
  chunks needed, unlike the fan-out ring.
- **Scalar-prefetch routes.**  The permutation changes every round;
  baking it into the kernel would recompile per round.  The routes
  array ``[p, pinv]`` (2, n) int32 rides scalar prefetch instead
  (``PrefetchScalarGridSpec(num_scalar_prefetch=1)``), so one compiled
  program serves every round of a geometry and ``device_id`` is read
  from SMEM per step.
- **Double buffering.**  DMA semaphores are parity pairs
  (``sem[t % 2]``): step ``t`` starts its send, then waits step
  ``t-1``'s — lane B crosses the links while lane A's send drains
  (the ``ici_fanout`` idiom; the waited descriptor's slice/target are
  irrelevant, only its semaphore is consumed).
- **Landing slots.**  Two concurrently-running collective kernels on a
  chip must not share barrier semaphores, so the exchange reserves its
  own per-slot Mosaic ``collective_id`` pair — distinct from the
  fan-out's (11, 13)/(12, 14) — and callers riding a landing slot
  alternate ``slot`` exactly like ``fanout_start``/``fanout_wait``.
  The split surface is :func:`exchange_start` / :func:`exchange_wait`:
  start dispatches the ring program device-side and returns
  immediately; the wait is the consumer's first use of the value.

Off-TPU the wrappers run ``interpret=True`` (how tier-1 proves byte
identity against the host path on the CPU virtual mesh); on a pod the
same kernels compile through Mosaic.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddl_tpu._compat import shard_map
from ddl_tpu.ops.ici_fanout import (
    AXIS,
    N_SLOTS,
    _check_slot,
    _ring_mesh,
    interpret_default,
)

#: Mosaic collective ids for the exchange kernel, indexed by landing
#: slot — must differ from every other collective kernel that can be in
#: flight on the chip at the same time (the fan-out holds 11-14).
_EXCHANGE_COLLECTIVE_IDS = (15, 16)

#: The two lane steps of one exchange round (grid size): step 0 moves
#: lane A along ``p``, step 1 moves lane B along ``pinv``.
_N_LANES = 2


def _exchange_kernel(routes_ref, in_ref, out_ref, send_sem, recv_sem, *,
                     half: int):
    """One exchange round: two permutation-shaped remote-DMA steps.

    ``routes_ref`` is the scalar-prefetched (2, n) int32 ``[p, pinv]``;
    step ``t`` sends this device's rows ``[t*half, (t+1)*half)`` to
    device ``routes[t, me]`` and receives the same lane slice from its
    inverse — a full permutation per step, so interpret mode's
    one-copy-per-device lockstep invariant holds by construction.
    """
    t = pl.program_id(0)
    last_t = pl.num_programs(0) - 1
    me = lax.axis_index(AXIS)

    def _send_op(step):
        # Slice + target always describe the CURRENT step's lane; the
        # parity wait below only consumes step t-1's send semaphore, for
        # which the descriptor's slice/target are irrelevant (the
        # ici_fanout idiom).
        return pltpu.make_async_remote_copy(
            src_ref=in_ref.at[pl.ds(t * half, half)],
            dst_ref=out_ref.at[pl.ds(t * half, half)],
            send_sem=send_sem.at[step % 2],
            recv_sem=recv_sem.at[step % 2],
            device_id=routes_ref[t, me],
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    op = _send_op(t)
    op.start()
    op.wait_recv()

    # Double buffer: start step t's DMA before draining step t-1's —
    # lane B is on the links while lane A's send completes.
    @pl.when(t >= 1)
    def _wait_prev():
        _send_op(t - 1).wait_send()

    @pl.when(t == last_t)
    def _drain():
        _send_op(t).wait_send()


@functools.lru_cache(maxsize=64)
def _exchange_call(devices: Tuple[Any, ...], half: int, cols: int,
                   dtype_name: str, interpret: bool, slot: int = 0):
    """Jitted shard_map'ed ring exchange over ``devices``: inputs are
    the (2, n) int32 routes (replicated) and the global
    (n * 2 * half, cols) P(x) lane blocks; output has the same global
    shape with both lanes exchanged.  Cached per geometry — the routes
    are DATA, so every round of a geometry reuses one program."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _ring_mesh(devices)
    dtype = np.dtype(dtype_name)
    kern = functools.partial(_exchange_kernel, half=half)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(_N_LANES,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((2,))] * 2,
    )
    call = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((_N_LANES * half, cols), dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=_EXCHANGE_COLLECTIVE_IDS[slot]
        ),
    )
    fn = shard_map(
        call, mesh=mesh, in_specs=(P(None, None), P(AXIS)),
        out_specs=P(AXIS), check_vma=False,
    )
    spec = NamedSharding(mesh, P(AXIS))
    rspec = NamedSharding(mesh, P(None, None))
    return jax.jit(fn, in_shardings=(rspec, spec), out_shardings=spec)


@functools.lru_cache(maxsize=64)
def _exchange_xla_call(devices: Tuple[Any, ...], half: int, cols: int,
                       dtype_name: str, perm: Tuple[int, ...]):
    """XLA reference variant: two ``lax.ppermute`` lanes over the ring
    mesh (the ``parallel.collectives._build_sendrecv_step`` idiom on the
    producer-side block layout).  Cached per permutation — the A/B
    baseline and the non-Pallas fallback impl."""
    from jax import numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddl_tpu.shuffle import inverse_permutation

    mesh = _ring_mesh(devices)
    p = np.array(perm)
    pinv = inverse_permutation(p)
    fwd = tuple((int(i), int(pi)) for i, pi in enumerate(p))
    bwd = tuple((int(i), int(pi)) for i, pi in enumerate(pinv))

    def shard_fn(block):
        # block: (2 * half, cols) — this instance's lane A + lane B.
        a = lax.ppermute(block[:half], AXIS, fwd)
        b = lax.ppermute(block[half:], AXIS, bwd)
        return jnp.concatenate([a, b], axis=0)

    fn = shard_map(
        shard_fn, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS),
        check_vma=False,
    )
    spec = NamedSharding(mesh, P(AXIS))
    return jax.jit(fn, in_shardings=spec, out_shardings=spec)


def as_exchange_input(blocks: Sequence[np.ndarray],
                      devices: Sequence[Any]) -> Any:
    """Land per-instance lane blocks on their ring devices and assemble
    the SPMD global (n * 2 * half, cols) P(x) input — the H2D landing
    edge of the exchange (the host touches the rows exactly once; every
    subsequent hop rides ICI)."""
    devices = tuple(devices)
    n_dev = len(devices)
    if len(blocks) != n_dev:
        raise ValueError(
            f"need one lane block per ring device ({n_dev}), got "
            f"{len(blocks)}"
        )
    from jax.sharding import NamedSharding, PartitionSpec as P

    rows, cols = blocks[0].shape
    shards = [jax.device_put(b, d) for b, d in zip(blocks, devices)]
    return jax.make_array_from_single_device_arrays(
        (n_dev * rows, cols),
        NamedSharding(_ring_mesh(devices), P(AXIS)),
        shards,
    )


def exchange_output_blocks(out: Any,
                           devices: Sequence[Any]) -> List[np.ndarray]:
    """Fetch the exchanged lane blocks back to the host, one per ring
    position — the D2H edge where the fabric hands rows back to each
    producer's private pool (the exchange's only other host touch)."""
    devices = tuple(devices)
    n_dev = len(devices)
    rows = out.shape[0] // n_dev
    by_start: Dict[int, Any] = {
        (s.index[0].start or 0): s.data for s in out.addressable_shards
    }
    return [np.asarray(by_start[i * rows]) for i in range(n_dev)]


def exchange_ring(gin: Any, devices: Sequence[Any], routes: np.ndarray,
                  interpret: Optional[bool] = None, slot: int = 0) -> Any:
    """Run one Pallas ring exchange round over the assembled global
    input.  ``routes`` is the (2, n) int32 ``[p, pinv]`` for this round
    (data, not code — no per-round recompile).  ``slot`` selects the
    landing slot (collective-id pair), as in ``fanout_replicate``."""
    devices = tuple(devices)
    slot = _check_slot(slot)
    n_dev = len(devices)
    if n_dev == 1:
        return gin
    if interpret is None:
        interpret = interpret_default(devices)
    rows = gin.shape[0] // n_dev
    half = rows // _N_LANES
    routes = np.ascontiguousarray(routes, dtype=np.int32)
    if routes.shape != (_N_LANES, n_dev):
        raise ValueError(
            f"routes must be (2, {n_dev}) [p, pinv], got {routes.shape}"
        )
    call = _exchange_call(
        devices, half, gin.shape[1], np.dtype(gin.dtype).name, interpret,
        slot,
    )
    return call(routes, gin)


def exchange_xla(gin: Any, devices: Sequence[Any],
                 perm: Sequence[int]) -> Any:
    """Run one XLA ``ppermute`` exchange round (the A/B baseline and
    the ``shuffle_impl=xla`` path) over the assembled global input."""
    devices = tuple(devices)
    n_dev = len(devices)
    if n_dev == 1:
        return gin
    rows = gin.shape[0] // n_dev
    half = rows // _N_LANES
    call = _exchange_xla_call(
        devices, half, gin.shape[1], np.dtype(gin.dtype).name,
        tuple(int(x) for x in perm),
    )
    return call(gin)


@dataclasses.dataclass(frozen=True)
class ExchangeTicket:
    """A started (dispatched, possibly still in flight) exchange round.

    ``value`` is the kernel output as an ASYNC device value — the ring
    program is enqueued at :func:`exchange_start` and its DMA
    semaphores are hardware-waited, so the exchange hides under
    whatever step is running (the ``FanoutTicket`` discipline: at most
    one in-flight round per ``slot``)."""

    value: Any
    impl: str  #: "ring" | "xla"
    slot: int


def exchange_start(impl: str, gin: Any, devices: Sequence[Any],
                   perm: Sequence[int], *, slot: int = 0,
                   interpret: Optional[bool] = None) -> ExchangeTicket:
    """Start an exchange round into landing slot ``slot``; never waits.

    The start half of the split start/wait surface (the PR-12
    ``fanout_start``/``fanout_wait`` + ``gate_release_on`` protocol):
    the round's ring program is dispatched here and runs under the
    in-flight train step — a shuffle the trainer never waits for.
    Pair with :func:`exchange_wait`."""
    slot = _check_slot(slot)  # fail BEFORE dispatching side effects
    if impl == "ring":
        from ddl_tpu.shuffle import inverse_permutation

        p = np.asarray(perm)  # ddl-lint: disable=DDL016 - scalar-prefetch route table (host metadata), not window rows
        routes = np.stack([p, inverse_permutation(p)]).astype(np.int32)
        out = exchange_ring(
            gin, devices, routes, interpret=interpret, slot=slot
        )
    elif impl == "xla":
        out = exchange_xla(gin, devices, perm)
    else:
        raise ValueError(f"impl must be ring|xla, got {impl!r}")
    return ExchangeTicket(value=out, impl=impl, slot=slot)


def exchange_wait(ticket: ExchangeTicket, sync: bool = False) -> Any:
    """The wait half: the real wait is the DATA DEPENDENCE — the first
    use of the returned value drains the slot's DMA semaphores on
    device.  ``sync=True`` forces a host ``block_until_ready`` (the
    fabric's bring-up/fallback boundary, where an async DMA failure
    must surface inside the degradation ladder rather than at a remote
    consumer's sync point)."""
    if sync:
        jax.block_until_ready(ticket.value)
    return ticket.value


def exchange_wire_bytes(n: int, half: int, cols: int, dtype: Any) -> int:
    """Raw bytes one device round moves over ICI links: two lanes of
    ``half`` rows per device, every device sending each step — the
    honest numerator for per-leg utilization math."""
    if n <= 1 or half < 1:
        return 0
    row = cols * np.dtype(dtype).itemsize
    return _N_LANES * n * half * row


def plan_exchange(n: int, num_exchange: int, cols: int, dtype: Any,
                  wire_dtype: Optional[str] = None,
                  n_devices: Optional[int] = None) -> Dict[str, Any]:
    """Price one exchange round, per leg, device vs host.

    The host path's DCN-tier legs may ride the PR-13 wire
    (``plan_distribution(wire_dtype=)`` composition): its per-row cost
    is the ENCODED row + its scale stripe (``parallel.ici.wire_cols``),
    while the device legs move raw rows over ICI (on-device lossy
    re-quantization would break the exchange's exact byte identity, so
    the device tier only engages on the raw wire).  ``plannable`` is
    the geometry gate the shuffler consults before its first round —
    an unplannable geometry latches the host fallback for the
    shuffler's life (``shuffle.device_fallbacks``)."""
    from ddl_tpu import wire as _wire
    from ddl_tpu.parallel.ici import wire_cols

    dtype = np.dtype(dtype)
    half = num_exchange // 2
    wd = _wire.resolve_wire_dtype(wire_dtype)
    if wd != "raw" and not _wire.lossy_supported(dtype):
        wd = "raw"
    raw_row = cols * dtype.itemsize
    host_row = wire_cols(cols, dtype, wd)
    legs = []
    for lane in ("lane_a", "lane_b"):
        legs.append({
            "leg": lane,
            "rows": n * half,
            "ici_bytes": n * half * raw_row,
            "host_bytes_raw": n * half * raw_row,
            "host_bytes_wire": n * half * host_row,
        })
    plannable = n >= 2 and half >= 1
    why = None
    if n < 2:
        why = "single instance: nothing to exchange"
    elif half < 1:
        why = f"num_exchange {num_exchange} leaves no lane rows"
    if plannable and n_devices is not None and n_devices < n:
        plannable = False
        why = (
            f"ring needs {n} devices for {n} instances, have {n_devices}"
        )
    return {
        "plannable": plannable,
        "why_not": why,
        "n": n,
        "half": half,
        "cols": cols,
        "dtype": dtype.name,
        "wire_dtype": wd,
        "legs": legs,
        "ici_bytes": sum(leg["ici_bytes"] for leg in legs),
        "host_bytes_raw": sum(leg["host_bytes_raw"] for leg in legs),
        "host_bytes_wire": sum(leg["host_bytes_wire"] for leg in legs),
    }
