"""Causal flash attention as Pallas TPU kernels (forward + backward).

Blockwise attention with online softmax (the same math as
``parallel/ring_attention.py``, which runs it *across* devices; these
kernels run it *within* one device so the (T, T) score matrix never leaves
VMEM):

- Forward: grid = (batch, heads, Q blocks, KV blocks); the innermost KV
  axis is sequential on TPU, so running max / denominator / output
  accumulate in VMEM scratch across KV steps and the output block is
  written once, on the last step.  The per-row logsumexp is emitted as a
  residual for the backward pass.
- Backward (the standard two-kernel flash backward): dQ accumulates over
  KV blocks for a fixed Q block; dK/dV accumulate over Q blocks for a
  fixed KV block.  Probabilities are recomputed from the saved logsumexp —
  nothing quadratic is ever materialised.  Under GQA the per-Q-head dK/dV
  are summed over each query-head group outside the kernel.
- K/V stay compact under grouped-query attention — the head index map
  divides by ``kv_repeat``.
- Causal masking uses global token positions; blocks strictly above the
  diagonal skip their matmuls entirely (``pl.when``), saving ~half the
  FLOPs.

The public wrapper pads ragged sequence lengths to the block size (padded
keys are masked out, padded query rows sliced off) and falls back to
``interpret=True`` off-TPU, which is how the CPU test suite validates it
bit-for-bit against the dense oracle.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128  # TPU vector lane count: scratch accumulators are (bq, 128)


def _positions(i, j, block_q, block_k):
    q_pos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return q_pos, k_pos


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                *, scale: float, causal: bool, block_q: int, block_k: int,
                seq_len: int, precision):
    i = pl.program_id(2)  # Q block
    j = pl.program_id(3)  # KV block (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Block (i, j) is live unless it lies strictly above the causal diagonal.
    live = (j * block_k <= i * block_q + block_q - 1) if causal else (j >= 0)

    @pl.when(live)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32)  # (bq, d)
        k = k_ref[0, 0].astype(jnp.float32)  # (bk, d)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        ) * scale  # (bq, bk)

        q_pos, k_pos = _positions(i, j, block_q, block_k)
        invalid = k_pos >= seq_len  # padded keys
        if causal:
            invalid |= k_pos > q_pos
        s = jnp.where(invalid, _NEG_INF, s)

        m_prev = jnp.max(m_ref[:], axis=-1)  # lanes replicated -> any reduce
        l_prev = jnp.max(l_ref[:], axis=-1)
        m_cur = jnp.max(s, axis=-1)
        m_next = jnp.maximum(m_prev, m_cur)
        # Fully-masked-so-far rows keep m at -inf; zero the exponent shift so
        # exp() sees finite args, and zero those probabilities explicitly.
        safe_m = jnp.where(m_next <= _NEG_INF / 2, 0.0, m_next)
        alpha = jnp.exp(jnp.where(m_prev <= _NEG_INF / 2, _NEG_INF,
                                  m_prev - safe_m))
        p = jnp.exp(s - safe_m[:, None])
        p = jnp.where(invalid, 0.0, p)

        l_next = alpha * l_prev + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        m_ref[:] = jnp.broadcast_to(m_next[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_next[:, None], l_ref.shape)

    @pl.when(j == pl.num_programs(3) - 1)
    def _finish():
        m = jnp.max(m_ref[:], axis=-1)
        l = jnp.max(l_ref[:], axis=-1)
        # logsumexp residual; -inf marks rows with no valid keys.
        lse = jnp.where(
            l > 0.0, jnp.where(m <= _NEG_INF / 2, 0.0, m) + jnp.log(l),
            _NEG_INF,
        )
        lse_ref[0, 0] = lse[:, None]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)


def _recompute_p(q, k, lse, i, j, *, scale, causal, block_q, block_k,
                 seq_len, precision):
    """p_ij = exp(s_ij - lse_i), zeroed on masked/padded/empty rows."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    ) * scale
    q_pos, k_pos = _positions(i, j, block_q, block_k)
    invalid = (k_pos >= seq_len) | (q_pos >= seq_len)
    if causal:
        invalid |= k_pos > q_pos
    empty = lse <= _NEG_INF / 2  # (bq,)
    p = jnp.exp(s - jnp.where(empty, 0.0, lse)[:, None])
    return jnp.where(invalid | empty[:, None], 0.0, p)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale: float, causal: bool, block_q: int,
               block_k: int, seq_len: int, precision):
    i = pl.program_id(2)  # Q block
    j = pl.program_id(3)  # KV block (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = (j * block_k <= i * block_q + block_q - 1) if causal else (j >= 0)

    @pl.when(live)
    def _accum():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        p = _recompute_p(
            q, k, lse_ref[0, 0][:, 0], i, j, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_len=seq_len,
            precision=precision,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )  # (bq, bk)
        ds = p * (dp - delta_ref[0, 0]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )

    @pl.when(j == pl.num_programs(3) - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_acc, dv_acc, *, scale: float, causal: bool,
                block_q: int, block_k: int, seq_len: int, precision):
    j = pl.program_id(2)  # KV block
    i = pl.program_id(3)  # Q block (innermost, sequential)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = (j * block_k <= i * block_q + block_q - 1) if causal else (i >= 0)

    @pl.when(live)
    def _accum():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        p = _recompute_p(
            q, k, lse_ref[0, 0][:, 0], i, j, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_len=seq_len,
            precision=precision,
        )  # (bq, bk)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )  # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        ds = p * (dp - delta_ref[0, 0]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )

    @pl.when(i == pl.num_programs(3) - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _prep(q, k, v, block_q, block_k):
    """Common layout work: (B,T,H,D)→(B,H,T,D), tile-aligned blocks, pads."""
    B, T, H, D = q.shape
    tile = {4: 8, 2: 16, 1: 32}.get(jnp.dtype(q.dtype).itemsize, 8)
    align = lambda n: -(-n // tile) * tile  # noqa: E731
    block_q = min(block_q, align(max(T, 1)))
    block_k = min(block_k, align(max(T, 1)))
    pad_q = (-T) % block_q
    pad_k = (-T) % block_k
    qt = jnp.moveaxis(q, 2, 1)
    kt = jnp.moveaxis(k, 2, 1)
    vt = jnp.moveaxis(v, 2, 1)
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    return qt, kt, vt, block_q, block_k


def _precision_for(dtype):
    # f32 inputs get 6-pass MXU precision (err ~1e-6 vs the single-pass
    # bf16 default's ~5e-3 — enough to perturb small-key-count softmax
    # rows); bf16 inputs keep the fast default, as everywhere else.
    return (
        jax.lax.Precision.HIGHEST
        if dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )


def _fwd_impl(q, k, v, causal, kv_repeat, block_q, block_k, interpret):
    assert q.shape[2] == k.shape[2] * kv_repeat, (q.shape, k.shape, kv_repeat)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, T, H, D = q.shape
    qt, kt, vt, block_q, block_k = _prep(q, k, v, block_q, block_k)
    Tq, Tk = qt.shape[2], kt.shape[2]
    precision = _precision_for(q.dtype)
    kernel = functools.partial(
        _fwd_kernel, scale=1.0 / (D**0.5), causal=causal, block_q=block_q,
        block_k=block_k, seq_len=T, precision=precision,
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, D),
        lambda b, h, i, j, rep=kv_repeat: (b, h // rep, j, 0),
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, Tq // block_q, Tk // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            # Row residual carries a trailing singleton lane dim: TPU block
            # shapes need the last two dims tile-aligned or whole-array.
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom l
            pltpu.VMEM((block_q, D), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt)
    o = out[:, :, :T] if Tq != T else out
    return jnp.moveaxis(o, 1, 2), (out, lse, interpret, block_q, block_k)


def _bwd_impl(causal, kv_repeat, _block_q, _block_k, _interpret, res, do):
    # Resolved block sizes / interpret flag ride in the residuals so both
    # passes use identical values (the nondiff args are pre-resolution).
    q, k, v, out_padded, lse, interpret, block_q, block_k = res
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    qt, kt, vt, block_q, block_k = _prep(q, k, v, block_q, block_k)
    Tq, Tk = qt.shape[2], kt.shape[2]
    precision = _precision_for(q.dtype)

    dot = jnp.moveaxis(do, 2, 1)
    if Tq != T:
        dot = jnp.pad(dot, ((0, 0), (0, 0), (0, Tq - T), (0, 0)))
    # delta_i = rowsum(dO_i * O_i), the softmax-jacobian diagonal term.
    delta = jnp.sum(
        dot.astype(jnp.float32) * out_padded.astype(jnp.float32), axis=-1,
        keepdims=True,
    )  # (B, H, Tq, 1)

    common = dict(
        scale=1.0 / (D**0.5), causal=causal, block_q=block_q,
        block_k=block_k, seq_len=T, precision=precision,
    )
    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, D),
        lambda b, h, i, j, rep=kv_repeat: (b, h // rep, j, 0),
    )
    row_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=(B, H, Tq // block_q, Tk // block_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    # dK/dV: grid transposed so the Q axis is innermost (sequential).
    q_spec_t = pl.BlockSpec((1, 1, block_q, D), lambda b, h, j, i: (b, h, i, 0))
    kv_spec_t = pl.BlockSpec(
        (1, 1, block_k, D),
        lambda b, h, j, i, rep=kv_repeat: (b, h // rep, j, 0),
    )
    row_spec_t = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b, h, j, i: (b, h, i, 0)
    )
    out_kv_t = pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, i: (b, h, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **common),
        grid=(B, H, Tk // block_k, Tq // block_q),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[out_kv_t, out_kv_t],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, Tk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    if Tq != T:
        dq = dq[:, :, :T]
    if Tk != T:
        dk = dk[:, :, :T]
        dv = dv[:, :, :T]
    dq = jnp.moveaxis(dq, 1, 2)
    # Per-Q-head dK/dV collapse onto the compact KV heads (GQA group sum).
    if kv_repeat > 1:
        dk = dk.reshape(B, Hkv, kv_repeat, T, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, kv_repeat, T, D).sum(axis=2)
    dk = jnp.moveaxis(dk, 1, 2)
    dv = jnp.moveaxis(dv, 1, 2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    kv_repeat: int = 1,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over (B, T, H, D) queries.

    k/v are compact GQA tensors of shape (B, T, H // kv_repeat, D).  Output
    matches ``parallel.ring_attention.attention_reference`` up to fp
    accumulation order; fully differentiable (flash backward kernels).
    Off-TPU the kernels run in Pallas interpret mode.
    """
    out, _ = _fwd_impl(q, k, v, causal, kv_repeat, block_q, block_k, interpret)
    return out


def _vjp_fwd(q, k, v, causal, kv_repeat, block_q, block_k, interpret):
    out, (out_padded, lse, ipret, bq, bk) = _fwd_impl(
        q, k, v, causal, kv_repeat, block_q, block_k, interpret
    )
    return out, (q, k, v, out_padded, lse, ipret, bq, bk)


flash_attention.defvjp(_vjp_fwd, _bwd_impl)
