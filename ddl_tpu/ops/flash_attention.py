"""Causal flash attention as Pallas TPU kernels (forward + backward).

Blockwise attention with online softmax (the same math as
``parallel/ring_attention.py``, which runs it *across* devices; these
kernels run it *within* one device so the (T, T) score matrix never leaves
VMEM):

- Forward: grid = (batch, heads, Q blocks, KV blocks); the innermost KV
  axis is sequential on TPU, so running max / denominator / output
  accumulate in VMEM scratch across KV steps and the output block is
  written once, on the last step.  The per-row logsumexp is emitted as a
  residual for the backward pass and (via
  :func:`flash_attention_with_lse`) for cross-device online-softmax
  combination — ring attention calls this kernel once per ring step and
  merges steps with the logsumexp identity.
- Backward (the standard two-kernel flash backward): dQ accumulates over
  KV blocks for a fixed Q block; dK/dV accumulate over Q blocks for a
  fixed KV block.  Probabilities are recomputed from the saved logsumexp —
  nothing quadratic is ever materialised.  An incoming lse cotangent
  (from the ring combine) folds into the score gradient as
  ``ds += p * dlse`` (since d lse_i / d s_ik = p_ik).  Under GQA the
  per-Q-head dK/dV are summed over each query-head group outside the
  kernel.
- Global-position offsets ride in as scalar-prefetch arguments (they are
  traced values inside a ring ``lax.scan``), so causal masking uses global
  token positions and blocks strictly above the (global) diagonal skip
  their matmuls via ``pl.when`` — a ring step that is entirely in the
  masked future costs DMAs but no FLOPs.
- K/V stay compact under grouped-query attention — the head index map
  divides by ``kv_repeat``.

The public wrappers pad ragged sequence lengths to the block size (padded
keys are masked out, padded query rows sliced off) and fall back to
``interpret=True`` off-TPU, which is how the CPU test suite validates them
bit-for-bit against the dense oracle.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128  # TPU vector lane count: scratch accumulators are (bq, 128)


def _positions(offs_ref, i, j, block_q, block_k):
    """(global q, global k, local q, local k) position grids."""
    q_loc = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_loc = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return offs_ref[0] + q_loc, offs_ref[1] + k_loc, q_loc, k_loc


def _live(offs_ref, i, j, block_q, block_k, causal):
    """False only when block (i, j) lies strictly above the global causal
    diagonal (then every entry is masked and the matmuls can be skipped)."""
    if not causal:
        return j >= 0  # traced True
    return (
        offs_ref[1] + j * block_k
        <= offs_ref[0] + i * block_q + block_q - 1
    )


def _crosses_diag(offs_ref, i, j, block_q, block_k, causal):
    """True when block (i, j) straddles the global causal diagonal (some
    entries masked, some not).  Interior blocks — fully below the diagonal
    — skip mask construction entirely: the two (bq, bk) position grids,
    compares, and selects are the kernel's dominant VPU cost after exp."""
    if not causal:
        return j < 0  # traced False
    return (
        offs_ref[1] + (j + 1) * block_k - 1
        > offs_ref[0] + i * block_q
    )


def _seg_invalid(seg):
    """(bq, bk) True where query and key belong to different packed
    segments.  ``seg`` is the (seg_q_ref, seg_k_ref) pair of (1,1,b,1)
    int32 blocks, or None when the batch is unpacked."""
    sq = seg[0][0, 0][:, 0]  # (bq,)
    sk = seg[1][0, 0][:, 0]  # (bk,)
    return sq[:, None] != sk[None, :]


def _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_ref, l_ref, acc_ref, *, scale: float, causal: bool,
                block_q: int, block_k: int, kv_len: int, precision,
                seg=None):
    i = pl.program_id(2)  # Q block
    j = pl.program_id(3)  # KV block (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _scores():
        return _block_scores(q_ref, k_ref, scale, precision)  # (bq, bk) f32

    def _update(s):
        """Online-softmax accumulate of one score block into m/l/acc."""
        v = v_ref[0, 0]
        m_prev = jnp.max(m_ref[:], axis=-1)  # lanes replicated -> any reduce
        l_prev = jnp.max(l_ref[:], axis=-1)
        m_cur = jnp.max(s, axis=-1)
        m_next = jnp.maximum(m_prev, m_cur)
        # Fully-masked-so-far rows keep m at -inf; zero the exponent shift
        # so exp() sees finite args.  Masked scores are the finite
        # _NEG_INF, so exp(s - safe_m) underflows to exactly 0 for them —
        # no explicit zeroing select is needed.
        safe_m = jnp.where(m_next <= _NEG_INF / 2, 0.0, m_next)
        alpha = jnp.exp(jnp.where(m_prev <= _NEG_INF / 2, _NEG_INF,
                                  m_prev - safe_m))
        p = jnp.exp(s - safe_m[:, None])

        l_next = alpha * l_prev + jnp.sum(p, axis=-1)
        # p drops to the input dtype for the MXU (standard flash practice;
        # the fp32 path keeps p fp32 since v.dtype is fp32 there).
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        m_ref[:] = jnp.broadcast_to(m_next[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_next[:, None], l_ref.shape)

    # Two real branches (pl.when lowers to an scf.if, executed
    # conditionally — a value-level lax.cond computed both sides):
    # interior blocks fully below the diagonal with no padded keys skip
    # mask construction entirely, the dominant VPU cost after exp.
    live = _live(offs_ref, i, j, block_q, block_k, causal)
    needs_mask = (
        _crosses_diag(offs_ref, i, j, block_q, block_k, causal)
        | ((j + 1) * block_k > kv_len)
    )
    if seg is not None:
        # Packed segments can differ anywhere — every live block masks.
        needs_mask = needs_mask | (j >= 0)

    @pl.when(live & needs_mask)
    def _attend_masked():
        s = _scores()
        q_pos, k_pos, _, k_loc = _positions(offs_ref, i, j, block_q, block_k)
        invalid = k_loc >= kv_len  # padded keys
        if causal:
            invalid |= k_pos > q_pos
        if seg is not None:
            invalid |= _seg_invalid(seg)
        _update(jnp.where(invalid, _NEG_INF, s))

    @pl.when(live & jnp.logical_not(needs_mask))
    def _attend_fast():
        _update(_scores())

    @pl.when(j == pl.num_programs(3) - 1)
    def _finish():
        m = jnp.max(m_ref[:], axis=-1)
        l = jnp.max(l_ref[:], axis=-1)
        # logsumexp residual; -inf marks rows with no valid keys.
        lse = jnp.where(
            l > 0.0, jnp.where(m <= _NEG_INF / 2, 0.0, m) + jnp.log(l),
            _NEG_INF,
        )
        lse_ref[0, 0] = lse[:, None]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)


def _block_scores(q_ref, k_ref, scale, precision):
    """Scaled q·kᵀ of the current blocks, fp32 accumulation with operands
    in the input dtype (bf16 runs the MXU at full rate; fp32 would quarter
    it) — shared by the forward and both backward kernels."""
    return jax.lax.dot_general(
        q_ref[0, 0], k_ref[0, 0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    ) * scale


def _bwd_p_dispatch(offs_ref, q_ref, k_ref, lse_ref, i, j, accum, *,
                    scale, causal, block_q, block_k, seq_len, kv_len,
                    precision, seg=None):
    """Backward-pass block dispatch shared by the dQ and dK/dV kernels:
    dead blocks skipped, boundary blocks recompute p with full masking,
    interior blocks use the bare ``exp(s - lse)`` fast path (statement-
    level ``pl.when`` — real branches, unlike a value-level cond which
    Mosaic computes on both sides)."""
    live = _live(offs_ref, i, j, block_q, block_k, causal)
    needs_mask = _needs_mask_bwd(
        offs_ref, i, j, block_q, block_k, causal, seq_len, kv_len
    )
    if seg is not None:
        needs_mask = needs_mask | (j >= 0)  # packed: every block masks

    def scores():
        return _block_scores(q_ref, k_ref, scale, precision)

    @pl.when(live & needs_mask)
    def _accum_masked():
        accum(_p_masked(
            offs_ref, scores(), lse_ref[0, 0][:, 0], i, j, causal=causal,
            block_q=block_q, block_k=block_k, seq_len=seq_len,
            kv_len=kv_len, seg=seg,
        ))

    @pl.when(live & jnp.logical_not(needs_mask))
    def _accum_fast():
        accum(jnp.exp(scores() - lse_ref[0, 0][:, 0][:, None]))


def _p_masked(offs_ref, s, lse, i, j, *, causal, block_q, block_k,
              seq_len, kv_len, seg=None):
    """p = exp(s - lse) with mask/padding/empty-row handling (the slow,
    boundary-block path — interior blocks use the bare exp)."""
    q_pos, k_pos, q_loc, k_loc = _positions(offs_ref, i, j, block_q, block_k)
    invalid = (k_loc >= kv_len) | (q_loc >= seq_len)
    if causal:
        invalid |= k_pos > q_pos
    if seg is not None:
        invalid |= _seg_invalid(seg)
    empty = lse <= _NEG_INF / 2  # (bq,)
    p = jnp.exp(s - jnp.where(empty, 0.0, lse)[:, None])
    return jnp.where(invalid | empty[:, None], 0.0, p)


def _needs_mask_bwd(offs_ref, i, j, block_q, block_k, causal, seq_len,
                    kv_len):
    """True unless block (i, j) is interior: fully below the diagonal with
    no padded keys/queries.  Interior blocks cannot contain masked entries
    or globally-empty rows (the block itself supplies valid keys), so
    ``exp(s - lse)`` is exact there and mask construction is skipped."""
    return (
        _crosses_diag(offs_ref, i, j, block_q, block_k, causal)
        | ((j + 1) * block_k > kv_len)
        | ((i + 1) * block_q > seq_len)
    )


def _dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dlse_ref, dq_ref, dq_acc, *, scale: float, causal: bool,
               block_q: int, block_k: int, seq_len: int, kv_len: int,
               precision, seg=None):
    i = pl.program_id(2)  # Q block
    j = pl.program_id(3)  # KV block (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _accum(p):
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )  # (bq, bk) fp32
        ds = p * (dp - delta_ref[0, 0] + dlse_ref[0, 0]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )

    _bwd_p_dispatch(
        offs_ref, q_ref, k_ref, lse_ref, i, j, _accum, scale=scale,
        causal=causal, block_q=block_q, block_k=block_k, seq_len=seq_len,
        kv_len=kv_len, precision=precision, seg=seg,
    )

    @pl.when(j == pl.num_programs(3) - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dlse_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                causal: bool, block_q: int, block_k: int, seq_len: int,
                kv_len: int, precision, seg=None):
    j = pl.program_id(2)  # KV block
    i = pl.program_id(3)  # Q block (innermost, sequential)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _accum(p):
        q = q_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )  # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        ds = p * (dp - delta_ref[0, 0] + dlse_ref[0, 0]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )

    _bwd_p_dispatch(
        offs_ref, q_ref, k_ref, lse_ref, i, j, _accum, scale=scale,
        causal=causal, block_q=block_q, block_k=block_k, seq_len=seq_len,
        kv_len=kv_len, precision=precision, seg=seg,
    )

    @pl.when(i == pl.num_programs(3) - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


# Packed-segment kernel adapters: same bodies, two extra int32 input refs
# (query-/key-segment blocks) spliced in by position.  Separate entry
# points keep the unpacked kernels' ref layout byte-identical.


def _fwd_kernel_seg(offs_ref, q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref,
                    lse_ref, m_ref, l_ref, acc_ref, **kw):
    _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref,
                l_ref, acc_ref, seg=(sq_ref, sk_ref), **kw)


def _dq_kernel_seg(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dlse_ref, sq_ref, sk_ref, dq_ref, dq_acc,
                   **kw):
    _dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dlse_ref, dq_ref, dq_acc, seg=(sq_ref, sk_ref), **kw)


def _dkv_kernel_seg(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dlse_ref, sq_ref, sk_ref, dk_ref, dv_ref,
                    dk_acc, dv_acc, **kw):
    _dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dlse_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                seg=(sq_ref, sk_ref), **kw)


def _prep(q, k, v, block_q, block_k):
    """Common layout work: (B,T,H,D)→(B,H,T,D), tile-aligned blocks, pads."""
    B, Tq0, H, D = q.shape
    Tk0 = k.shape[1]
    tile = {4: 8, 2: 16, 1: 32}.get(jnp.dtype(q.dtype).itemsize, 8)
    align = lambda n: -(-n // tile) * tile  # noqa: E731
    block_q = min(block_q, align(max(Tq0, 1)))
    block_k = min(block_k, align(max(Tk0, 1)))
    pad_q = (-Tq0) % block_q
    pad_k = (-Tk0) % block_k
    qt = jnp.moveaxis(q, 2, 1)
    kt = jnp.moveaxis(k, 2, 1)
    vt = jnp.moveaxis(v, 2, 1)
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    return qt, kt, vt, block_q, block_k


def _precision_for(dtype):
    # f32 inputs get 6-pass MXU precision (err ~1e-6 vs the single-pass
    # bf16 default's ~5e-3 — enough to perturb small-key-count softmax
    # rows); bf16 inputs keep the fast default, as everywhere else.
    return (
        jax.lax.Precision.HIGHEST
        if dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )


def _offsets_arr(q_offset, k_offset):
    return jnp.stack(
        [jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)]
    )


def _prep_seg(seg, T_padded):
    """(B, T) segment ids → (B, 1, T_padded, 1) int32 for block mapping.
    Pad rows get -1; padded keys are independently masked by ``kv_len``
    and padded query rows are sliced off the output."""
    B, T = seg.shape
    s = jnp.asarray(seg, jnp.int32)
    if T_padded != T:
        s = jnp.pad(s, ((0, 0), (0, T_padded - T)), constant_values=-1)
    return s[:, None, :, None]


def _seg_specs(block_q, block_k, transposed: bool = False):
    """Block specs for the (B, 1, T, 1) segment-id arrays (no head axis).

    ``transposed``: the dK/dV grid is (b, h, KV block, Q block), so the
    Q-block index is grid axis 3 and the KV-block index axis 2."""
    if transposed:
        sq = pl.BlockSpec(
            (1, 1, block_q, 1), lambda b, h, j, i, *_refs: (b, 0, i, 0)
        )
        sk = pl.BlockSpec(
            (1, 1, block_k, 1), lambda b, h, j, i, *_refs: (b, 0, j, 0)
        )
        return sq, sk
    sq = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b, h, i, j, *_refs: (b, 0, i, 0)
    )
    sk = pl.BlockSpec(
        (1, 1, block_k, 1), lambda b, h, i, j, *_refs: (b, 0, j, 0)
    )
    return sq, sk


def _fwd_impl(q, k, v, offsets, causal, kv_repeat, block_q, block_k,
              interpret, seg_q=None, seg_k=None):
    assert q.shape[2] == k.shape[2] * kv_repeat, (q.shape, k.shape, kv_repeat)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, T, H, D = q.shape
    Tkv = k.shape[1]
    qt, kt, vt, block_q, block_k = _prep(q, k, v, block_q, block_k)
    Tq, Tk = qt.shape[2], kt.shape[2]
    precision = _precision_for(q.dtype)
    packed = seg_q is not None
    common = dict(
        scale=1.0 / (D**0.5), causal=causal, block_q=block_q,
        block_k=block_k, kv_len=Tkv, precision=precision,
    )
    kernel = functools.partial(
        _fwd_kernel_seg if packed else _fwd_kernel, **common
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, D),
        lambda b, h, i, j, *_refs, rep=kv_repeat: (b, h // rep, j, 0),
    )
    q_spec = pl.BlockSpec(
        (1, 1, block_q, D), lambda b, h, i, j, *_refs: (b, h, i, 0)
    )
    row_spec = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b, h, i, j, *_refs: (b, h, i, 0)
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    inputs = [qt, kt, vt]
    if packed:
        sq_spec, sk_spec = _seg_specs(block_q, block_k)
        in_specs += [sq_spec, sk_spec]
        inputs += [_prep_seg(seg_q, Tq), _prep_seg(seg_k, Tk)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, H, Tq // block_q, Tk // block_k),
        in_specs=in_specs,
        out_specs=[q_spec, row_spec],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom l
            pltpu.VMEM((block_q, D), jnp.float32),  # output accumulator
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(offsets, *inputs)
    o = out[:, :, :T] if Tq != T else out
    return (
        jnp.moveaxis(o, 1, 2),
        lse[:, :, :T, 0],
        (out, lse, interpret, block_q, block_k),
    )


def _bwd_impl(causal, kv_repeat, _block_q, _block_k, _interpret, res, cts):
    do, dlse = cts
    # Resolved block sizes / interpret flag ride in the residuals so both
    # passes use identical values (the nondiff args are pre-resolution).
    (q, k, v, offsets, out_padded, lse, interpret, block_q, block_k,
     seg_q, seg_k) = res
    B, T, H, D = q.shape
    Tkv, Hkv = k.shape[1], k.shape[2]
    qt, kt, vt, block_q, block_k = _prep(q, k, v, block_q, block_k)
    Tq, Tk = qt.shape[2], kt.shape[2]
    precision = _precision_for(q.dtype)
    packed = seg_q is not None

    dot = jnp.moveaxis(do, 2, 1)
    if Tq != T:
        dot = jnp.pad(dot, ((0, 0), (0, 0), (0, Tq - T), (0, 0)))
    # delta_i = rowsum(dO_i * O_i), the softmax-jacobian diagonal term.
    delta = jnp.sum(
        dot.astype(jnp.float32) * out_padded.astype(jnp.float32), axis=-1,
        keepdims=True,
    )  # (B, H, Tq, 1)
    # lse cotangent from the caller (zero for plain flash_attention; the
    # ring combine's weights make it nonzero there).
    dl = dlse.astype(jnp.float32)[..., None]  # (B, H, T, 1)
    if Tq != T:
        dl = jnp.pad(dl, ((0, 0), (0, 0), (0, Tq - T), (0, 0)))

    common = dict(
        scale=1.0 / (D**0.5), causal=causal, block_q=block_q,
        block_k=block_k, seq_len=T, kv_len=Tkv, precision=precision,
    )
    q_spec = pl.BlockSpec(
        (1, 1, block_q, D), lambda b, h, i, j, *_refs: (b, h, i, 0)
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, D),
        lambda b, h, i, j, *_refs, rep=kv_repeat: (b, h // rep, j, 0),
    )
    row_spec = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b, h, i, j, *_refs: (b, h, i, 0)
    )
    dq_in_specs = [q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec,
                   row_spec]
    dq_inputs = [qt, kt, vt, dot, lse, delta, dl]
    if packed:
        sq_spec, sk_spec = _seg_specs(block_q, block_k)
        dq_in_specs += [sq_spec, sk_spec]
        dq_inputs += [_prep_seg(seg_q, Tq), _prep_seg(seg_k, Tk)]
    dq = pl.pallas_call(
        functools.partial(_dq_kernel_seg if packed else _dq_kernel,
                          **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, Tq // block_q, Tk // block_k),
            in_specs=dq_in_specs,
            out_specs=q_spec,
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
        interpret=interpret,
    )(offsets, *dq_inputs)

    # dK/dV: grid transposed so the Q axis is innermost (sequential).
    q_spec_t = pl.BlockSpec(
        (1, 1, block_q, D), lambda b, h, j, i, *_refs: (b, h, i, 0)
    )
    kv_spec_t = pl.BlockSpec(
        (1, 1, block_k, D),
        lambda b, h, j, i, *_refs, rep=kv_repeat: (b, h // rep, j, 0),
    )
    row_spec_t = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b, h, j, i, *_refs: (b, h, i, 0)
    )
    out_kv_t = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, j, i, *_refs: (b, h, j, 0)
    )
    dkv_in_specs = [q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                    row_spec_t, row_spec_t]
    dkv_inputs = [qt, kt, vt, dot, lse, delta, dl]
    if packed:
        sq_spec_t, sk_spec_t = _seg_specs(block_q, block_k, transposed=True)
        dkv_in_specs += [sq_spec_t, sk_spec_t]
        dkv_inputs += [_prep_seg(seg_q, Tq), _prep_seg(seg_k, Tk)]
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel_seg if packed else _dkv_kernel,
                          **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, Tk // block_k, Tq // block_q),
            in_specs=dkv_in_specs,
            out_specs=[out_kv_t, out_kv_t],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, Tk, D), v.dtype),
        ],
        interpret=interpret,
    )(offsets, *dkv_inputs)

    if Tq != T:
        dq = dq[:, :, :T]
    if Tk != Tkv:
        dk = dk[:, :, :Tkv]
        dv = dv[:, :, :Tkv]
    dq = jnp.moveaxis(dq, 1, 2)
    # Per-Q-head dK/dV collapse onto the compact KV heads (GQA group sum).
    if kv_repeat > 1:
        dk = dk.reshape(B, Hkv, kv_repeat, Tkv, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, kv_repeat, Tkv, D).sum(axis=2)
    dk = jnp.moveaxis(dk, 1, 2)
    dv = jnp.moveaxis(dv, 1, 2)
    d_offsets = np.zeros((2,), jax.dtypes.float0)  # int arg: zero cotangent
    return dq, dk.astype(k.dtype), dv.astype(v.dtype), d_offsets


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_core(q, k, v, offsets, causal, kv_repeat, block_q, block_k,
                interpret):
    out, lse, _ = _fwd_impl(
        q, k, v, offsets, causal, kv_repeat, block_q, block_k, interpret
    )
    return out, lse


def _vjp_fwd(q, k, v, offsets, causal, kv_repeat, block_q, block_k,
             interpret):
    out, lse, (out_padded, lse_padded, ipret, bq, bk) = _fwd_impl(
        q, k, v, offsets, causal, kv_repeat, block_q, block_k, interpret
    )
    return (out, lse), (
        q, k, v, offsets, out_padded, lse_padded, ipret, bq, bk, None, None
    )


_flash_core.defvjp(_vjp_fwd, _bwd_impl)


# Packed-segment core: identical math plus the segment mask.  A separate
# custom_vjp keeps the unpacked core's signature (and its validated
# behavior) untouched; segment ids are integer inputs with float0
# cotangents, like ``offsets``.
@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _flash_core_seg(q, k, v, offsets, seg_q, seg_k, causal, kv_repeat,
                    block_q, block_k, interpret):
    out, lse, _ = _fwd_impl(
        q, k, v, offsets, causal, kv_repeat, block_q, block_k, interpret,
        seg_q=seg_q, seg_k=seg_k,
    )
    return out, lse


def _vjp_fwd_seg(q, k, v, offsets, seg_q, seg_k, causal, kv_repeat,
                 block_q, block_k, interpret):
    out, lse, (out_padded, lse_padded, ipret, bq, bk) = _fwd_impl(
        q, k, v, offsets, causal, kv_repeat, block_q, block_k, interpret,
        seg_q=seg_q, seg_k=seg_k,
    )
    return (out, lse), (
        q, k, v, offsets, out_padded, lse_padded, ipret, bq, bk,
        seg_q, seg_k,
    )


def _bwd_impl_seg(causal, kv_repeat, block_q, block_k, interpret, res, cts):
    dq, dk, dv, d_offsets = _bwd_impl(
        causal, kv_repeat, block_q, block_k, interpret, res, cts
    )
    seg_q, seg_k = res[-2], res[-1]
    d_seg_q = np.zeros(seg_q.shape, jax.dtypes.float0)
    d_seg_k = np.zeros(seg_k.shape, jax.dtypes.float0)
    return dq, dk, dv, d_offsets, d_seg_q, d_seg_k


_flash_core_seg.defvjp(_vjp_fwd_seg, _bwd_impl_seg)


def _default_blocks(T: int, block_q, block_k):
    """v5e-tuned defaults, sequence-length adaptive (measured fwd+bwd at
    B=4, H=16, D=128: bq=512 wins at T<=2k, bq=1024 wins at 4k/8k by
    ~10%).  Both directions compile within v5e's VMEM budget — the
    backward reuses the forward's resolved blocks.  On smaller-VMEM
    generations pass smaller blocks explicitly if Mosaic reports VMEM
    exhaustion."""
    if block_q is None:
        block_q = 512 if T <= 2048 else 1024
    if block_k is None:
        block_k = 1024
    return block_q, block_k


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    kv_repeat: int = 1,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Flash attention over (B, T, H, D) queries.

    k/v are compact GQA tensors of shape (B, T, H // kv_repeat, D).  Output
    matches ``parallel.ring_attention.attention_reference`` up to fp
    accumulation order; fully differentiable (flash backward kernels).
    Off-TPU the kernels run in Pallas interpret mode.  Default blocks are
    length-adaptive (see ``_default_blocks``).

    ``segment_ids`` (B, T) int32, values >= 0: packed-sequence masking —
    tokens attend only within their own segment (causality still applies
    on top).  The standard layout for LM pretraining feeds that pack
    multiple documents into one row.  Packed blocks always take the
    masked path, so packing trades the interior-block fast path for the
    mask; unpacked calls are entirely unaffected.
    """
    block_q, block_k = _default_blocks(q.shape[1], block_q, block_k)
    if segment_ids is not None:
        out, _ = _flash_core_seg(
            q, k, v, _offsets_arr(0, 0), segment_ids, segment_ids, causal,
            kv_repeat, block_q, block_k, interpret,
        )
        return out
    out, _ = _flash_core(
        q, k, v, _offsets_arr(0, 0), causal, kv_repeat, block_q, block_k,
        interpret,
    )
    return out


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_offset=0,
    k_offset=0,
    causal: bool = True,
    kv_repeat: int = 1,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Flash attention returning (out, logsumexp (B, H, T) fp32).

    ``q_offset`` / ``k_offset`` are GLOBAL token offsets (static or traced
    ints) added to the local positions for causal masking — ring attention
    passes its shard offsets here so each ring step masks against global
    positions.  Rows with every key masked return out == 0 and
    lse == -1e30; combine partial results with
    ``lse = logaddexp(lse_a, lse_b)`` and
    ``out = out_a·exp(lse_a-lse) + out_b·exp(lse_b-lse)``.

    ``segment_ids`` (B, Tq) / ``kv_segment_ids`` (B, Tk; defaults to
    ``segment_ids``): packed-sequence masking — ring attention passes its
    local query ids and the CURRENT rotating key-block ids.
    """
    block_q, block_k = _default_blocks(q.shape[1], block_q, block_k)
    if kv_segment_ids is not None and segment_ids is None:
        # Key-only ids have no sound default for the queries (mirroring
        # them silently mis-segments unpacked queries).
        raise ValueError(
            "kv_segment_ids requires segment_ids (the query-side ids)"
        )
    if segment_ids is not None:
        seg_k = kv_segment_ids if kv_segment_ids is not None else segment_ids
        return _flash_core_seg(
            q, k, v, _offsets_arr(q_offset, k_offset), segment_ids, seg_k,
            causal, kv_repeat, block_q, block_k, interpret,
        )
    return _flash_core(
        q, k, v, _offsets_arr(q_offset, k_offset), causal, kv_repeat,
        block_q, block_k, interpret,
    )
