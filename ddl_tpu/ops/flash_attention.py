"""Causal flash attention as a Pallas TPU kernel.

Blockwise attention with online softmax (the same math as
``parallel/ring_attention.py``, which runs it *across* devices; this kernel
runs it *within* one device so the (T, T) score matrix never leaves VMEM):

- grid = (batch, heads, Q blocks, KV blocks); the innermost KV axis is
  sequential on TPU, so running max / denominator / output accumulate in
  VMEM scratch across KV steps and the output block is written once, on the
  last step.
- K/V stay compact under grouped-query attention — the head index map
  divides by ``kv_repeat``, so each KV head's block is fetched from HBM
  once per Q-head group member but never materialised expanded.
- Causal masking uses global token positions; blocks strictly above the
  diagonal skip the matmul entirely (``pl.when``), saving ~half the FLOPs.

The public wrapper pads ragged sequence lengths to the block size (padded
keys are masked out, padded query rows sliced off) and falls back to
``interpret=True`` off-TPU, which is how the CPU test suite validates it
bit-for-bit against the dense oracle.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128  # TPU vector lane count: scratch accumulators are (bq, 128)


def _kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
            scale: float, causal: bool, block_q: int, block_k: int,
            seq_len: int, precision):
    i = pl.program_id(2)  # Q block
    j = pl.program_id(3)  # KV block (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Block (i, j) is live unless it lies strictly above the causal diagonal.
    live = (j * block_k <= i * block_q + block_q - 1) if causal else (j >= 0)

    @pl.when(live)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32)  # (bq, d)
        k = k_ref[0, 0].astype(jnp.float32)  # (bk, d)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        ) * scale  # (bq, bk)

        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        invalid = k_pos >= seq_len  # padded keys
        if causal:
            invalid |= k_pos > q_pos
        s = jnp.where(invalid, _NEG_INF, s)

        m_prev = jnp.max(m_ref[:], axis=-1)  # lanes replicated -> any reduce
        l_prev = jnp.max(l_ref[:], axis=-1)
        m_cur = jnp.max(s, axis=-1)
        m_next = jnp.maximum(m_prev, m_cur)
        # Fully-masked-so-far rows keep m at -inf; zero the exponent shift so
        # exp() sees finite args, and zero those probabilities explicitly.
        safe_m = jnp.where(m_next <= _NEG_INF / 2, 0.0, m_next)
        alpha = jnp.exp(jnp.where(m_prev <= _NEG_INF / 2, _NEG_INF,
                                  m_prev - safe_m))
        p = jnp.exp(s - safe_m[:, None])
        p = jnp.where(invalid, 0.0, p)

        l_next = alpha * l_prev + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        m_ref[:] = jnp.broadcast_to(m_next[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_next[:, None], l_ref.shape)

    @pl.when(j == pl.num_programs(3) - 1)
    def _finish():
        l = jnp.max(l_ref[:], axis=-1)
        l = jnp.where(l == 0.0, 1.0, l)  # rows with no valid keys -> 0 output
        o_ref[0, 0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    kv_repeat: int = 1,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over (B, T, H, D) queries.

    k/v are compact GQA tensors of shape (B, T, H // kv_repeat, D).  Output
    matches ``parallel.ring_attention.attention_reference`` up to fp
    accumulation order.  Off-TPU the kernel runs in Pallas interpret mode.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    assert H == Hkv * kv_repeat, (H, Hkv, kv_repeat)

    # Shrink oversized blocks only down to a tile-aligned size (sublane
    # tile: 8 for f32, 16 for bf16, 32 for 8-bit) — a block of raw T would
    # hand Mosaic a non-tile-aligned shape.
    tile = {4: 8, 2: 16, 1: 32}.get(jnp.dtype(q.dtype).itemsize, 8)
    align = lambda n: -(-n // tile) * tile  # noqa: E731
    block_q = min(block_q, align(max(T, 1)))
    block_k = min(block_k, align(max(T, 1)))
    pad_q = (-T) % block_q
    pad_k = (-T) % block_k
    # (B, H, T, D) layout so T and D are the tiled minor dims.
    qt = jnp.moveaxis(q, 2, 1)
    kt = jnp.moveaxis(k, 2, 1)
    vt = jnp.moveaxis(v, 2, 1)
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    Tq, Tk = qt.shape[2], kt.shape[2]

    grid = (B, H, Tq // block_q, Tk // block_k)
    # f32 inputs get 6-pass MXU precision (err ~1e-6 vs the single-pass
    # bf16 default's ~5e-3 — enough to perturb small-key-count softmax
    # rows); bf16 inputs keep the fast default, as everywhere else.
    precision = (
        jax.lax.Precision.HIGHEST
        if q.dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )
    kernel = functools.partial(
        _kernel,
        scale=1.0 / (D**0.5),
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        seq_len=T,
        precision=precision,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec(
                (1, 1, block_k, D),
                lambda b, h, i, j, rep=kv_repeat: (b, h // rep, j, 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, D),
                lambda b, h, i, j, rep=kv_repeat: (b, h // rep, j, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom l
            pltpu.VMEM((block_q, D), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt)
    if pad_q:
        out = out[:, :, :T]
    return jnp.moveaxis(out, 1, 2)
