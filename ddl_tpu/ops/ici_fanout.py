"""Pallas ICI fan-out kernels: device-side window distribution.

After six PRs every byte still entered the pod through one host's
``device_put`` — the window crossed H2D once and was then scattered by
XLA with no measurement or control of the ICI hop (ROADMAP item 1).
These kernels make that hop explicit: one source device's committed
window is replicated (ring broadcast) or sharded (ring scatter) across a
1-axis device ring entirely over ICI with ``pltpu.make_async_remote_copy``
DMAs, double-buffered so chunk N+1's DMA overlaps chunk N's wait.

Kernel shape constraints (why the code looks the way it does):

- **Permute-shaped steps.**  Interpret mode (the CPU virtual-mesh test
  path) discharges a remote DMA as a *collective*: every device in the
  axis must execute every ``dma_start`` in lockstep, and the target map
  of each step must deliver exactly one copy to every device
  (``jax/_src/pallas/mosaic/primitives.py`` gathers ``device_id`` with
  ``lax.all_gather`` and ``argmax``-selects the sender).  Role-gated
  sends (``pl.when(is_source)``) therefore deadlock under interpret —
  both kernels instead run a full right-rotation every step, with the
  chunk schedule clamped so devices ahead of / behind the pipeline send
  repeats of valid edge chunks.
- **Sink chunk.**  The rotation wraps: the ring tail sends to the
  source every step.  Early steps that send would carry garbage into
  the source's *live* window (a read-write race on real hardware), so
  the tail redirects its wrap-around send into a dedicated sink chunk
  past the payload — dead bytes on a link the broadcast cannot use
  anyway.
- **Double buffering.**  DMA semaphores are parity pairs (``sem[t % 2]``):
  step ``t`` starts its send, *then* waits step ``t-1``'s send — one
  send is always in flight while the previous one drains.  The scatter
  kernel's transit buffer is a ``(2, block)`` VMEM ping-pong for the
  same reason: the forward of step ``t`` reads the half the recv of
  step ``t`` is not writing.

- **Landing slots (fused step).**  The fused compute/ingest step keeps
  TWO windows' fan-outs in flight: window N+1's ring is dispatched at
  the entry of the step computing window N, and its DMA semaphores are
  waited on only at the next step's first use of the data.  Two
  concurrently-running collective kernels on a chip must not share
  barrier semaphores, so every wrapper takes a ``slot`` (< ``N_SLOTS``)
  selecting a *per-slot* Mosaic ``collective_id`` pair AND a per-slot
  set of cached landing buffers — the device-side landing slots.  The
  split start/wait surface is :func:`fanout_start` /
  :func:`fanout_wait`: start IS the async dispatch of the slot's ring
  program (the DMA ring is enqueued device-side and runs under the
  in-flight step), and the wait is deferred to the consumer's first
  use of the returned value (``sync=True`` forces a host
  ``block_until_ready`` — the bring-up validation path only).

The wrappers fall back to ``interpret=True`` off-TPU, which is how the
CPU suite validates byte identity against the host path (tier-1); on a
real pod the same kernels compile through Mosaic (``collective_id`` is
reserved per mode and slot).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax import lax
from jax import numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddl_tpu._compat import shard_map

#: The fan-out ring's private mesh axis (always 1-axis: interpret-mode
#: remote DMA only supports a single named dimension, and the
#: redistribution planner owns the mapping onto dp x fsdp x tp).
AXIS = "x"

#: Default chunk count for the broadcast pipeline.  More chunks deepen
#: the pipeline (per-chunk latency hides behind the ring) but add
#: (n_dev - 2) clamped edge sends of one chunk each; 4 is a reasonable
#: floor for the window sizes the loader moves (>= 8 MiB).
DEFAULT_CHUNKS = 4

#: Device-side landing slots the fused step may keep in flight at once.
#: Two is the double-buffer: window N+1's ring runs while window N's
#: output is being consumed; a third slot would buy nothing (the step
#: consuming window N-1 has already waited its data) and cost one more
#: pinned landing-buffer set per geometry.
N_SLOTS = 2

#: Mosaic collective ids (must differ between concurrently-used
#: collective kernels on a chip).  Indexed by landing slot: the fused
#: step keeps two ring programs in flight, and two kernels sharing a
#: ``collective_id`` would share barrier semaphores — the per-slot pair
#: is what makes the overlap sound on real hardware.
_BCAST_COLLECTIVE_IDS = (11, 13)
_SCATTER_COLLECTIVE_IDS = (12, 14)


def _bcast_kernel(in_ref, out_ref, send_sem, recv_sem, copy_sem, *,
                  src: int, n_dev: int, rows: int, n_chunks: int):
    """Pipelined ring broadcast: source's ``in_ref`` (n_chunks * rows
    payload rows) lands in every device's ``out_ref`` (payload + one
    sink chunk).  Grid = (n_chunks + n_dev - 2,) steps; device at ring
    position p forwards chunk ``clip(t - p)`` at step t."""
    t = pl.program_id(0)
    last_t = pl.num_programs(0) - 1
    me = lax.axis_index(AXIS)
    pos = lax.rem(me - src + n_dev, n_dev)
    right = lax.rem(me + 1, n_dev)
    c_src = jnp.clip(t - pos, 0, n_chunks - 1)
    # The ring tail's send wraps around to the source; redirect it into
    # the sink chunk so the live window is never overwritten mid-stream.
    c_dst = jnp.where(pos == n_dev - 1, n_chunks, c_src)

    # Source: stage chunk t of the window into its own out buffer BEFORE
    # forwarding it (the send below reads out_ref).
    @pl.when((pos == 0) & (t < n_chunks))
    def _stage():
        cp = pltpu.make_async_copy(
            in_ref.at[pl.ds(t * rows, rows)],
            out_ref.at[pl.ds(t * rows, rows)],
            copy_sem.at[t % 2],
        )
        cp.start()
        cp.wait()  # ddl-lint: disable=DDL012 - device-side DMA semaphore, not a host wait

    def _send_op(step):
        # One descriptor shape for start and the parity waits: the wait
        # only consumes semaphore signals sized like one chunk, so the
        # slice indices of the waited step are irrelevant.
        return pltpu.make_async_remote_copy(
            src_ref=out_ref.at[pl.ds(c_src * rows, rows)],
            dst_ref=out_ref.at[pl.ds(c_dst * rows, rows)],
            send_sem=send_sem.at[step % 2],
            recv_sem=recv_sem.at[step % 2],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    op = _send_op(t)
    op.start()
    op.wait_recv()

    # Double buffer: only after launching step t's DMA do we drain step
    # t-1's — chunk N+1 crosses the link while chunk N's wait runs.
    @pl.when(t >= 1)
    def _wait_prev():
        _send_op(t - 1).wait_send()

    @pl.when(t == last_t)
    def _drain():
        _send_op(t).wait_send()


def _scatter_kernel(in_ref, out_ref, transit, send_sem, recv_sem,
                    copy_sem, *, src: int, n_dev: int, rows: int):
    """Pipelined ring scatter: row-block ``b`` of the source's window
    lands on the device at ring position ``(b - src) % n_dev``.  Blocks
    are injected farthest-destination-first, so every device's own block
    arrives exactly at the last step (grid = (n_dev - 1,)).  Transit is
    a double-buffered VMEM ping-pong; the source's transit half receives
    the wrap-around garbage and is never read."""
    s = pl.program_id(0)
    last_s = pl.num_programs(0) - 1
    me = lax.axis_index(AXIS)
    pos = lax.rem(me - src + n_dev, n_dev)
    right = lax.rem(me + 1, n_dev)
    par = s % 2        # recv half this step
    prev = (s + 1) % 2  # send half this step (== recv half of step s-1)

    # Source stages the outgoing block (farthest destination first) into
    # the send half; destination position p's block is row-block
    # (src + p) % n_dev of the window.
    @pl.when(pos == 0)
    def _stage():
        blk = lax.rem(src + (n_dev - 1 - s), n_dev)
        cp = pltpu.make_async_copy(
            in_ref.at[pl.ds(blk * rows, rows)],
            transit.at[prev],
            copy_sem.at[par],
        )
        cp.start()
        cp.wait()  # ddl-lint: disable=DDL012 - device-side DMA semaphore, not a host wait

    def _send_op(step):
        return pltpu.make_async_remote_copy(
            src_ref=transit.at[(step + 1) % 2],
            dst_ref=transit.at[step % 2],
            send_sem=send_sem.at[step % 2],
            recv_sem=recv_sem.at[step % 2],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    op = _send_op(s)
    op.start()
    op.wait_recv()

    # Every non-source device's own block arrives exactly at the last
    # step: keep it.
    @pl.when((pos > 0) & (s == last_s))
    def _keep():
        cp = pltpu.make_async_copy(transit.at[par], out_ref, copy_sem.at[prev])
        cp.start()
        cp.wait()  # ddl-lint: disable=DDL012 - device-side DMA semaphore, not a host wait

    # The source's own block never travels the ring.
    @pl.when((pos == 0) & (s == 0))
    def _own():
        cp = pltpu.make_async_copy(
            in_ref.at[pl.ds(src * rows, rows)], out_ref, copy_sem.at[prev]
        )
        cp.start()
        cp.wait()  # ddl-lint: disable=DDL012 - device-side DMA semaphore, not a host wait

    @pl.when(s >= 1)
    def _wait_prev():
        _send_op(s - 1).wait_send()

    @pl.when(s == last_s)
    def _drain():
        _send_op(s).wait_send()


def interpret_default(devices: Sequence[Any]) -> bool:
    """Interpret (CPU-simulate) unless every ring device is a real TPU."""
    return any(getattr(d, "platform", "cpu") != "tpu" for d in devices)


# -- geometry helpers ---------------------------------------------------------


def bcast_grid(n_dev: int, n_chunks: int) -> int:
    """Broadcast pipeline depth: chunk c reaches ring position p at step
    p + c - 1, so the tail's last chunk lands at step n_dev + n_chunks - 3."""
    return n_chunks + n_dev - 2


def wire_bytes(mode: str, nbytes: int, n_dev: int,
               n_chunks: int = DEFAULT_CHUNKS,
               rows: Optional[int] = None) -> int:
    """Total bytes the fan-out moves over ICI links (including the
    clamped edge repeats and the sink-chunk wrap sends) — the honest
    numerator for link-utilization math.

    Pass ``rows`` (the 2D view's leading dim) when known: the broadcast
    pads rows up to a chunk multiple and every DMA moves whole padded
    chunks, so the rowless byte-ceil estimate underprices the wire
    whenever ``rows % n_chunks != 0``."""
    if n_dev <= 1:
        return 0
    if mode == "replicate":
        if rows:
            # ceil(rows/n_chunks) whole rows per chunk-send.
            chunk = -(-rows // n_chunks) * (nbytes // rows)
        else:
            chunk = -(-nbytes // n_chunks)
        return n_dev * bcast_grid(n_dev, n_chunks) * chunk
    if mode == "shard":
        block = nbytes // n_dev
        return n_dev * (n_dev - 1) * block
    raise ValueError(f"mode must be replicate|shard, got {mode!r}")


def payload_bytes(mode: str, nbytes: int, n_dev: int) -> int:
    """Bytes usefully *delivered* by the fan-out (what the consumer
    gains): n-1 windows for replicate, the off-source blocks for shard."""
    if n_dev <= 1:
        return 0
    if mode == "replicate":
        return (n_dev - 1) * nbytes
    if mode == "shard":
        return nbytes - nbytes // n_dev
    raise ValueError(f"mode must be replicate|shard, got {mode!r}")


# -- compiled-call cache ------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _ring_mesh(devices: Tuple[Any, ...]):
    from jax.sharding import Mesh

    return Mesh(np.array(devices), (AXIS,))


@functools.lru_cache(maxsize=64)
def _bcast_call(devices: Tuple[Any, ...], rows: int, cols: int,
                dtype_name: str, src: int, n_chunks: int, interpret: bool,
                slot: int = 0):
    """Jitted shard_map'ed broadcast over ``devices``: input global
    (n * R_pad, cols) P(x) [only the source's block is real], output
    global (n * (R_pad + rows_per_chunk), cols) P(x) [payload + sink]."""
    import jax.numpy as jnp  # noqa: F401 - dtype resolution namespace
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = len(devices)
    mesh = _ring_mesh(devices)
    dtype = np.dtype(dtype_name)
    chunk_rows = rows // n_chunks
    kern = functools.partial(
        _bcast_kernel, src=src, n_dev=n_dev, rows=chunk_rows,
        n_chunks=n_chunks,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(bcast_grid(n_dev, n_chunks),),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((2,))] * 3,
    )
    call = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((rows + chunk_rows, cols), dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=_BCAST_COLLECTIVE_IDS[slot]
        ),
    )
    fn = shard_map(
        call, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS),
        check_vma=False,
    )
    spec = NamedSharding(mesh, P(AXIS))
    return jax.jit(fn, in_shardings=spec, out_shardings=spec)


@functools.lru_cache(maxsize=64)
def _scatter_call(devices: Tuple[Any, ...], rows: int, cols: int,
                  dtype_name: str, src: int, interpret: bool,
                  slot: int = 0):
    """Jitted shard_map'ed scatter: input global (n * R, cols) P(x)
    [source block real], output global (R, cols) P(x) — row-block i on
    device i."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = len(devices)
    mesh = _ring_mesh(devices)
    dtype = np.dtype(dtype_name)
    block_rows = rows // n_dev
    kern = functools.partial(
        _scatter_kernel, src=src, n_dev=n_dev, rows=block_rows
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(n_dev - 1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, block_rows, cols), jnp.dtype(dtype)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    call = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((block_rows, cols), dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=_SCATTER_COLLECTIVE_IDS[slot]
        ),
    )
    fn = shard_map(
        call, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS),
        check_vma=False,
    )
    spec = NamedSharding(mesh, P(AXIS))
    return jax.jit(fn, in_shardings=spec, out_shardings=spec)


@functools.lru_cache(maxsize=8)
def _landing_buffers(devices: Tuple[Any, ...], rows: int, cols: int,
                     dtype_name: str, skip: int, slot: int = 0):
    """Per-device landing buffers for the non-source ring positions (the
    SPMD input needs a block on every device; only the source's carries
    data).  Cached per (geometry, landing slot) so steady-state windows
    allocate nothing — each entry PINS one window-sized block per
    non-source device in HBM for the cache's life, which is why (a) the
    cache is small (a loader cycles a handful of window geometries ×
    ``N_SLOTS`` landing slots, not 64) and (b) the redistribution plan
    prices the landing blocks — one set per IN-FLIGHT slot — into its
    asserted per-device peak.  Keying by ``slot`` keeps two in-flight
    ring programs off each other's input buffers, so XLA sees no shared
    operand ordering the dispatches."""
    zeros = np.zeros((rows, cols), np.dtype(dtype_name))
    return tuple(
        None if i == skip else jax.device_put(zeros, d)
        for i, d in enumerate(devices)
    )


def _as_ring_input(block: Any, devices: Tuple[Any, ...], rows: int,
                   cols: int, src: int, slot: int = 0):
    """Assemble the SPMD global input (n * rows, cols) P(x): the source
    block plus cached landing buffers — zero host traffic after the
    first call per (geometry, slot)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = len(devices)
    dtype_name = np.dtype(block.dtype).name
    landing = _landing_buffers(devices, rows, cols, dtype_name, src, slot)
    shards = [landing[i] if i != src else block for i in range(n_dev)]
    return jax.make_array_from_single_device_arrays(
        (n_dev * rows, cols),
        NamedSharding(_ring_mesh(devices), P(AXIS)),
        shards,
    )


# -- public wrappers ----------------------------------------------------------


def _check_slot(slot: int) -> int:
    slot = int(slot)
    if not 0 <= slot < N_SLOTS:
        raise ValueError(
            f"landing slot must be in [0, {N_SLOTS}), got {slot}"
        )
    return slot


def fanout_replicate(block: Any, devices: Sequence[Any], src: int = 0,
                     n_chunks: int = DEFAULT_CHUNKS,
                     interpret: Optional[bool] = None,
                     slot: int = 0) -> Any:
    """Broadcast a (rows, cols) device block to every ring device.

    ``block`` must live on ``devices[src]``.  Returns a global
    ``(n * rows, cols)`` array sharded one block per device, every block
    byte-identical to the source (callers reinterpret the shards — see
    :func:`replicated_view`).  Rows are padded up to a chunk multiple
    internally and sliced back off.  ``slot`` selects the landing slot
    (collective-id pair + cached landing buffers); callers keeping two
    fan-outs in flight must alternate slots.
    """
    devices = tuple(devices)
    n_dev = len(devices)
    # Validate BEFORE the single-device passthrough: a bad slot must
    # fail on the 1-device dev box, not first on a real ring.
    slot = _check_slot(slot)
    if n_dev == 1:
        return block
    if interpret is None:
        interpret = interpret_default(devices)
    rows, cols = block.shape
    n_chunks = max(1, min(n_chunks, rows))
    pad = (-rows) % n_chunks
    if pad:
        block = jnp.pad(block, ((0, pad), (0, 0)))
    rows_pad = rows + pad
    gin = _as_ring_input(block, devices, rows_pad, cols, src, slot)
    call = _bcast_call(
        devices, rows_pad, cols, np.dtype(block.dtype).name, src,
        n_chunks, interpret, slot,
    )
    out = call(gin)  # (n * (rows_pad + chunk), cols): payload + sink
    return _strip_blocks(out, devices, rows_pad + rows_pad // n_chunks,
                         rows)


def fanout_shard(block: Any, devices: Sequence[Any], src: int = 0,
                 interpret: Optional[bool] = None, slot: int = 0) -> Any:
    """Scatter a (rows, cols) device block: row-block ``i`` lands on
    ``devices[(src + ((i - src) % n)) % n]`` — i.e. block i on device i.

    ``rows`` must divide evenly by the ring size (the planner guarantees
    this or falls back).  Returns a global (rows, cols) array sharded
    P(x) over the ring.  ``slot`` selects the landing slot, as in
    :func:`fanout_replicate`.
    """
    devices = tuple(devices)
    n_dev = len(devices)
    slot = _check_slot(slot)  # before the passthrough, as in replicate
    if n_dev == 1:
        return block
    if interpret is None:
        interpret = interpret_default(devices)
    rows, cols = block.shape
    if rows % n_dev:
        raise ValueError(
            f"shard fan-out needs rows ({rows}) divisible by the ring "
            f"size ({n_dev})"
        )
    gin = _as_ring_input(block, devices, rows, cols, src, slot)
    call = _scatter_call(
        devices, rows, cols, np.dtype(block.dtype).name, src, interpret,
        slot,
    )
    return call(gin)


@dataclasses.dataclass(frozen=True)
class FanoutTicket:
    """A started (dispatched, possibly still in flight) fan-out.

    ``value`` is the kernel's output as an ASYNC device value: the ring
    program is enqueued device-side at :func:`fanout_start` and its DMA
    semaphores are waited on by the hardware, not the host — the host
    thread returns immediately and the consuming step's first use of
    ``value`` is the wait leg.  The ticket records which landing slot
    the window occupies so callers can assert the double-buffer
    discipline (at most one in-flight window per slot).
    """

    value: Any
    mode: str  #: "replicate" | "shard"
    slot: int


def fanout_start(mode: str, block: Any, devices: Sequence[Any],
                 src: int = 0, *, slot: int = 0,
                 n_chunks: int = DEFAULT_CHUNKS,
                 interpret: Optional[bool] = None) -> FanoutTicket:
    """Start a fan-out into landing slot ``slot``; never waits.

    The start half of the fused step's split start/wait surface: the
    ring program for window N+1 is dispatched here — at the entry of
    the step computing window N — and runs under that step.  Pair with
    :func:`fanout_wait`.
    """
    slot = _check_slot(slot)  # fail BEFORE dispatching side effects
    if mode == "replicate":
        out = fanout_replicate(
            block, devices, src=src, n_chunks=n_chunks,
            interpret=interpret, slot=slot,
        )
    elif mode == "shard":
        out = fanout_shard(
            block, devices, src=src, interpret=interpret, slot=slot
        )
    else:
        raise ValueError(f"mode must be replicate|shard, got {mode!r}")
    return FanoutTicket(value=out, mode=mode, slot=slot)


def fanout_wait(ticket: FanoutTicket, sync: bool = False) -> Any:
    """The wait half: hand the started fan-out's value to its consumer.

    The real wait is the DATA DEPENDENCE — the consuming step's first
    use of the returned value drains the slot's DMA semaphores on
    device, with the host never blocking.  ``sync=True`` forces a host
    ``block_until_ready`` and is reserved for the bring-up validation
    path (the first window of a geometry, where an async DMA failure
    must surface inside the distributor's fallback ladder rather than
    at the consumer's sync point).
    """
    if sync:
        jax.block_until_ready(ticket.value)
    return ticket.value


def _strip_blocks(out: Any, devices: Tuple[Any, ...], block_rows: int,
                  keep_rows: int) -> Any:
    """Reassemble a (n * block_rows, cols) P(x) kernel output into the
    same layout with each block truncated to ``keep_rows`` (drops chunk
    padding + the sink chunk) — one cached jitted slice per geometry.
    ``out`` already carries the ring's P(x) NamedSharding (the kernel's
    declared out_shardings), so it feeds the slice directly."""
    if block_rows == keep_rows:
        return out
    return _strip_call(
        devices, block_rows, keep_rows, out.shape[1],
        np.dtype(out.dtype).name,
    )(out)


@functools.lru_cache(maxsize=64)
def _strip_call(devices: Tuple[Any, ...], block_rows: int, keep_rows: int,
                cols: int, dtype_name: str):
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _ring_mesh(devices)
    spec = NamedSharding(mesh, P(AXIS))

    def body(x):
        return x[:keep_rows]

    fn = shard_map(
        body, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS),
        check_vma=False,
    )
    return jax.jit(fn, in_shardings=spec, out_shardings=spec)


def replicated_view(out: Any, devices: Sequence[Any]) -> Any:
    """Reinterpret a block-per-device broadcast result (n * rows, cols)
    as ONE logically-replicated (rows, cols) array — zero-copy: the
    per-device shards become the replicas."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    devices = tuple(devices)
    n_dev = len(devices)
    if n_dev == 1:
        return out
    rows = out.shape[0] // n_dev
    shards = sorted(
        out.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    return jax.make_array_from_single_device_arrays(
        (rows, out.shape[1]),
        NamedSharding(_ring_mesh(devices), P(None, None)),
        [s.data for s in shards],
    )
