"""End-to-end window integrity: checksummed (seq, producer) slot headers.

The transport hands windows from producer to consumer through shared
memory; nothing in PR 1/2 verified that the bytes that left
``DataPusher._commit_window`` are the bytes a training step consumes.
This module closes that gap:

- Every committed window carries a 32-byte trailer header —
  ``magic | crc32 | seq | producer | flags`` — written into the ring
  slot just past the payload (slots are allocated ``HEADER_BYTES``
  larger when integrity is on, so payload geometry and every existing
  ``slot_view[:payload]`` consumer are untouched).
- The consumer verifies the header at drain (magic, producer identity,
  the expected logical sequence number, and the payload CRC), and the
  staging executor re-verifies the CRC of its slot→staging copy before
  the slot can be released early (a producer overwriting a
  not-yet-copied slot is exactly the torn-read this catches).
- A corrupt slot is quarantined and replayed: the consumer re-requests
  the window from the producer over the control channel, which rewinds
  via the same deterministic-replay contract elastic respawn uses
  (``on_init`` → ``post_init`` → ``fast_forward(seq)``).  See
  ``DistributedDataLoader._quarantine_and_replay`` and
  docs/ROBUSTNESS.md for the degradation ladder.

CRC is ``zlib.crc32`` (C speed, ~fractions of a ms per MiB window —
measured noise next to the slot memcpy it guards).  ``DDL_TPU_INTEGRITY=0``
disables the whole layer: slots shrink back, commits and drains skip the
checksum, and the loader serves exactly the PR 2 byte path.

Header layout (little-endian, 32 of 32 reserved bytes used)::

    u32 magic   u32 crc32(payload [+ scales])   u64 seq
    u32 producer_idx   u32 flags   u32 wire_code   u32 scale_bytes

The last two fields are the WIRE-FORMAT extension (``ddl_tpu.wire``):
``wire_code`` names the payload's wire dtype (0 = raw — the value old
headers carry implicitly, so pre-wire rings verify unchanged) and
``scale_bytes`` sizes the blockwise-quantization scales that travel in
the TRAILER EXTENSION, the region immediately past this header
(slots for wire-encoded windows are committed with the *encoded*
payload size, so header + scales always fit inside the raw-sized
slot).  The CRC covers the encoded payload AND the scales — integrity
verifies the *quantized* bytes, so corruption detection survives the
dtype change: a flipped wire byte mismatches the committed CRC exactly
like flipped raw bytes, and the quarantine-and-replay ladder runs
unchanged.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import Optional

import numpy as np

#: Trailer size reserved past the payload in every ring slot.
HEADER_BYTES = 32

_MAGIC = 0x44444C57  # "DDLW"
_FMT = "<IIQIIII"
_FMT_BYTES = struct.calcsize(_FMT)  # 32 (wire_code + scale_bytes appended;
# the first 24 bytes keep the pre-wire layout, so old headers parse with
# wire_code == scale_bytes == 0 — i.e. raw)


def integrity_enabled(override: Optional[bool] = None) -> bool:
    """The ``DDL_TPU_INTEGRITY`` gate (default ON; ``0``/``off`` disables)."""
    from ddl_tpu.utils import env_flag

    return env_flag("DDL_TPU_INTEGRITY", override)


def window_crc(payload: np.ndarray) -> int:
    """CRC32 of a window payload (a C-contiguous uint8 view)."""
    return zlib.crc32(np.ascontiguousarray(payload)) & 0xFFFFFFFF


def wire_crc(slot_view: np.ndarray, payload_bytes: int,
             scale_bytes: int) -> int:
    """The committed CRC of a (possibly wire-encoded) slot: the payload
    fold continued over the trailer-extension scales.

    THE shared implementation for both sides of the contract — the
    producer's encoded commit and :func:`verify_window`'s drain check
    call this one function, so the fold order / region layout cannot
    desynchronize between them.  ``scale_bytes == 0`` degrades to the
    plain :func:`window_crc`.
    """
    crc = window_crc(slot_view[:payload_bytes])
    if scale_bytes:
        start = payload_bytes + HEADER_BYTES
        crc = zlib.crc32(
            np.ascontiguousarray(
                slot_view[start : start + scale_bytes]
            ),
            crc,
        ) & 0xFFFFFFFF
    return crc


@dataclasses.dataclass(frozen=True)
class WindowHeader:
    magic: int
    crc: int
    seq: int
    producer_idx: int
    flags: int
    #: Wire-format extension (``ddl_tpu.wire``): the payload's wire
    #: dtype code (0 = raw) and the byte length of the blockwise scales
    #: stored in the trailer extension past this header.
    wire_code: int = 0
    scale_bytes: int = 0

    @property
    def valid_magic(self) -> bool:
        return self.magic == _MAGIC

    @property
    def wire_dtype(self) -> str:
        """The payload's wire dtype name ("raw" for pre-wire headers)."""
        from ddl_tpu import wire

        return wire._CODE_TO_DTYPE.get(self.wire_code, "raw")


def blob_seq(digest: str) -> int:
    """Stable 64-bit sequence tag derived from a cache-entry digest.

    The disk cache tier (``ddl_tpu/cache/store.py``) reuses the ring-slot
    trailer machinery above for its on-disk entries, with this digest-
    derived value in the header's ``seq`` field: a spill file renamed or
    hard-linked across keys then fails :func:`verify_window`'s sequence
    check even when its payload CRC is intact — stale entries can never
    alias a foreign key.
    """
    return int(digest[:16], 16) & 0xFFFFFFFFFFFFFFFF


def write_header(
    slot_view: np.ndarray,
    payload_bytes: int,
    seq: int,
    producer_idx: int,
    crc: int,
    wire_code: int = 0,
    scale_bytes: int = 0,
) -> None:
    """Stamp the trailer header into ``slot_view`` past the payload.

    ``payload_bytes`` is the size of the bytes that actually travel —
    the *encoded* size for wire-formatted windows.  ``wire_code`` /
    ``scale_bytes`` describe the encoding (``ddl_tpu.wire``); the
    scales themselves are written separately
    (:func:`write_scales`), immediately past this header.
    """
    packed = struct.pack(
        _FMT, _MAGIC, crc, seq, producer_idx, 0, wire_code, scale_bytes
    )
    slot_view[payload_bytes : payload_bytes + _FMT_BYTES] = np.frombuffer(
        packed, dtype=np.uint8
    )


def read_header(slot_view: np.ndarray, payload_bytes: int) -> WindowHeader:
    raw = bytes(slot_view[payload_bytes : payload_bytes + _FMT_BYTES])
    magic, crc, seq, producer_idx, flags, wire_code, scale_bytes = (
        struct.unpack(_FMT, raw)
    )
    return WindowHeader(
        magic, crc, seq, producer_idx, flags, wire_code, scale_bytes
    )


def write_scales(
    slot_view: np.ndarray, payload_bytes: int, scales: np.ndarray
) -> None:
    """Write the blockwise-quantization scales into the trailer
    EXTENSION — the region immediately past the 32-byte header.  The
    caller stamps the matching ``scale_bytes`` via :func:`write_header`
    and folds the scales into the committed CRC
    (``crc32(scales, crc32(payload))`` — see :func:`verify_window`)."""
    raw = np.ascontiguousarray(scales).view(np.uint8).reshape(-1)
    start = payload_bytes + HEADER_BYTES
    slot_view[start : start + raw.nbytes] = raw


def read_scales(
    slot_view: np.ndarray, payload_bytes: int, scale_bytes: int
) -> np.ndarray:
    """The trailer extension's scales as a flat fp32 array (a copy —
    the slot may be released/overwritten while the decode is live)."""
    start = payload_bytes + HEADER_BYTES
    return (
        np.array(slot_view[start : start + scale_bytes])
        .view(np.float32)
    )


def verify_window(
    slot_view: np.ndarray,
    payload_bytes: int,
    expect_seq: int,
    expect_producer: int,
) -> Optional[str]:
    """Full drain-time check.  Returns a failure description, or None.

    Ordered cheap-to-expensive: magic (a producer that never stamped a
    header — torn commit or version skew), identity and sequencing (a
    dropped/duplicated/foreign window), then the payload CRC (flipped
    bytes).
    """
    hdr = read_header(slot_view, payload_bytes)
    if not hdr.valid_magic:
        return f"bad header magic 0x{hdr.magic:08x} (torn or unstamped commit)"
    if hdr.producer_idx != expect_producer:
        return (
            f"window from producer {hdr.producer_idx}, "
            f"expected producer {expect_producer}"
        )
    if hdr.seq != expect_seq:
        return f"window seq {hdr.seq}, expected {expect_seq} (drop/duplicate)"
    # The CRC covers the bytes that actually traveled: the (possibly
    # wire-encoded) payload, then the trailer-extension scales — so
    # corruption detection survives the dtype change (a flipped int8
    # wire byte or scale byte mismatches exactly like a raw one).
    got = wire_crc(slot_view, payload_bytes, hdr.scale_bytes)
    if got != hdr.crc:
        return (
            f"payload crc32 0x{got:08x} != committed 0x{hdr.crc:08x} "
            f"(seq {hdr.seq}, producer {hdr.producer_idx}, "
            f"wire {hdr.wire_dtype})"
        )
    return None
