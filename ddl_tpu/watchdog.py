"""Failure detection: producer liveness + pipeline progress watchdog.

The reference had no failure detection — a dead producer deadlocked the
trainer until an external timeout killed the job (SURVEY §5.3; its only
detector was the pytest 100 s timeout, reference ``tests/test_ddl.py:8``).
ddl_tpu layers three mechanisms:

1. Every transport wait is bounded (``StallTimeoutError``) — built into
   the rings.
2. Control channels detect peer death as EOF (``PipeChannel``).
3. This watchdog: a consumer-side monitor thread that periodically checks
   worker liveness and ring progress and invokes a callback (default: log
   + initiate shutdown) when a producer dies or stalls beyond its budget.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ddl_tpu.exceptions import ShutdownRequested, TransportError
from ddl_tpu.faults import fault_point
from ddl_tpu.observability import Metrics, metrics as default_metrics

logger = logging.getLogger("ddl_tpu")


class Watchdog:
    """Monitors a WorkerSet + its rings from the consumer side."""

    def __init__(
        self,
        workers: Any,  # ddl_tpu.env.WorkerSet
        poll_interval_s: float = 2.0,
        stall_budget_s: float = 120.0,
        on_failure: Optional[Callable[[str], None]] = None,
        respawn: bool = False,
        max_respawns: int = 3,
        replay_budget_per_window_s: float = 1.0,
        metrics: Optional[Metrics] = None,
        cluster: Any = None,
    ):
        """``respawn=True`` turns detection into recovery: a dead
        producer worker is replaced in place (``WorkerSet.respawn`` —
        rejoin the surviving ring, fast-forward to the recorded data
        position) up to ``max_respawns`` times before falling back to
        ``on_failure``.  The reference had neither detection nor
        recovery (SURVEY §5.3).

        ``cluster`` (a :class:`ddl_tpu.cluster.ClusterSupervisor`)
        extends the ladder cross-host: every poll also drives one
        membership sweep from this monitor thread, and workers whose
        HOST has left the view are the cluster ladder's to handle — the
        watchdog neither respawns them (a replacement would rejoin a
        ring the loader pool already dropped) nor escalates them to
        ``on_failure`` (the view change IS the handling).

        Recovery events record into ``metrics`` (``watchdog.respawns``,
        ``watchdog.failures``) so robustness regressions are visible in
        ``north_star_report`` and the bench JSON trajectories, not just
        in logs."""
        self.workers = workers
        self.poll_interval_s = poll_interval_s
        self.stall_budget_s = stall_budget_s
        self.on_failure = on_failure or self._default_on_failure
        self.respawn = respawn
        self.max_respawns = max_respawns
        self.replay_budget_per_window_s = replay_budget_per_window_s
        self.cluster = cluster
        self.metrics = metrics or default_metrics()
        self.respawns: List[int] = []  # producer_idx per respawn event
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Keyed by producer ring index: bounded by n_producers.
        self._last_progress: Dict[int, tuple] = {}  # ddl-lint: disable=DDL013
        self._last_change: Dict[int, float] = {}  # ddl-lint: disable=DDL013
        self.failures: List[str] = []
        self._dead_idx: Optional[int] = None  # set by check_once
        # ring index -> committed count at respawn time.  While present,
        # the replacement is fast-forward replaying (commits nothing),
        # so its stall budget is widened; the entry clears when the
        # committed count moves PAST the recorded value.
        self._replaying: Dict[int, float] = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Watchdog":
        self._thread = threading.Thread(
            target=self._run, name="ddl-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.poll_interval_s * 2 + 1)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- checks ------------------------------------------------------------

    def _default_on_failure(self, reason: str) -> None:
        logger.error("watchdog: %s — initiating shutdown", reason)
        try:
            self.workers.abort()
        except (ShutdownRequested, KeyboardInterrupt):
            raise
        except Exception:  # pragma: no cover - best effort
            pass

    def check_once(self) -> Optional[str]:
        """One sweep; returns a failure description or None."""
        # Chaos hook: a spurious ShutdownRequested / crash here exercises
        # the monitor loop's own teardown-vs-crash discrimination.
        fault_point("watchdog.sweep")
        rings = self.workers.connection.rings
        # Clean shutdown is initiated ring-by-ring (loader.shutdown() flags
        # rings sequentially), so a sweep landing mid-teardown may see some
        # rings flagged and some not while producer threads are already
        # exiting. Treat ANY shut-down ring as shutdown-in-progress rather
        # than flagging spurious "producer died" failures. Ring-like doubles
        # without is_shutdown() are treated as live.
        if rings and any(
            getattr(r, "is_shutdown", lambda: False)() for r in rings
        ):
            return None
        self._dead_idx = None
        # Workers of hosts that LEFT the cluster view are the host-level
        # ladder's to handle (ddl_tpu.cluster): a view change declared
        # them, the loader pool dropped their rings, and survivors
        # adopted their shard ranges — dead-by-design, not failures.
        lost = (
            self.cluster.lost_ranks() if self.cluster is not None
            else frozenset()
        )
        for i, t in enumerate(self.workers.threads):
            if i + 1 in lost:
                continue
            if not t.is_alive():
                self._dead_idx = i + 1
                return f"producer thread {i + 1} died"
        for i, p in enumerate(self.workers.processes):
            if i + 1 in lost:
                continue
            if p.exitcode is not None and p.exitcode != 0:
                self._dead_idx = i + 1
                return f"producer process {i + 1} exited with {p.exitcode}"
        now = time.monotonic()
        for i, ring in enumerate(rings):
            if i + 1 in lost:
                continue  # no progress expected from a departed host
            st = ring.stats()
            progress = (st["committed"], st["released"])
            if (
                i in self._replaying
                and st["committed"] > self._replaying[i]
            ):
                del self._replaying[i]  # first NEW commit ends the replay
            if self._last_progress.get(i) != progress:
                self._last_progress[i] = progress
                self._last_change[i] = now
            # A freshly respawned producer replays its predecessor's
            # windows before committing anything, and the default
            # fast_forward replays one execute_function per committed
            # window — replay time grows LINEARLY with run length.  The
            # grace therefore scales with the recorded committed count
            # (``replay_budget_per_window_s`` each, on top of a 10x base)
            # instead of a fixed multiplier, so a producer dying late in
            # a long run is not falsely escalated mid-replay.  Producers
            # with a cheap ``fast_forward`` override (seekable sources)
            # finish early and clear the grace on their first new commit.
            budget = self.stall_budget_s
            if i in self._replaying:
                budget = self.stall_budget_s * 10.0 + (
                    max(0.0, self._replaying[i])
                    * self.replay_budget_per_window_s
                )
            if (
                self._last_progress.get(i) == progress
                and st["committed"] == st["released"]  # producer owes one
                and now - self._last_change.get(i, now) > budget
            ):
                # A hung-but-alive PROCESS worker is replaceable too:
                # respawn() terminates it before starting the
                # replacement.  THREAD mode cannot kill a live thread —
                # WorkerSet.respawn refuses it and the failure falls
                # through to on_failure.
                self._dead_idx = i + 1
                return (
                    f"ring {i} made no progress for {budget}s "
                    f"(committed={st['committed']:.0f})"
                )
        return None

    def _run(self) -> None:
        # Workers that already exited cleanly (end of run) are expected;
        # only flag failures while the pipeline is supposed to be live.
        while not self._stop.wait(self.poll_interval_s):
            if self.cluster is not None:
                # Host-level ladder: one membership sweep per poll from
                # this monitor thread (lease refresh from liveness
                # sources, expiry → epoch-fenced view change).  Same
                # crash discipline as check_once below.
                try:
                    self.cluster.sweep()
                except (ShutdownRequested, KeyboardInterrupt):
                    return
                except Exception:
                    logger.exception(
                        "watchdog: cluster sweep raised; continuing"
                    )
            try:
                reason = self.check_once()
            except (ShutdownRequested, KeyboardInterrupt):
                # Teardown reached the monitor thread: stop monitoring,
                # do not mislabel it as a crashed sweep (DDL007).
                return
            except Exception:
                # A crashing sweep must never silently disable failure
                # detection; log and keep monitoring.
                logger.exception("watchdog: check_once raised; continuing")
                continue
            if reason is not None:
                if (
                    self.cluster is not None
                    and self._dead_idx is not None
                    and self._dead_idx in self.cluster.lost_ranks()
                ):
                    # Declared dead at host level between check_once and
                    # here: the view change owns it.
                    continue
                if (
                    self.respawn
                    and self._dead_idx is not None
                    and len(self.respawns) < self.max_respawns
                ):
                    idx = self._dead_idx
                    logger.warning(
                        "watchdog: %s — respawning producer %d "
                        "(%d/%d respawns used)",
                        reason, idx, len(self.respawns) + 1,
                        self.max_respawns,
                    )
                    try:
                        self.workers.respawn(idx)
                        self.respawns.append(idx)
                        self.metrics.incr("watchdog.respawns")
                        if self.cluster is not None:
                            # Cross-host ladder: the fresh incarnation
                            # must hear the CURRENT view's shard
                            # assignment (an adoption sent while the
                            # dead channel was mid-swap is lost).
                            self.cluster.rank_respawned(idx)
                        # Stall clock restarts at the respawn; the
                        # widened replay budget holds until the
                        # committed count moves past its current value.
                        self._last_change[idx - 1] = time.monotonic()
                        try:
                            committed = self.workers.connection.rings[
                                idx - 1
                            ].stats()["committed"]
                        except (TransportError, OSError, KeyError,
                                IndexError):  # pragma: no cover
                            committed = float("-inf")
                        self._replaying[idx - 1] = committed
                        continue
                    except (ShutdownRequested, KeyboardInterrupt):
                        return  # teardown mid-respawn: stop monitoring
                    except Exception:
                        logger.exception(
                            "watchdog: respawn of producer %d failed", idx
                        )
                self.failures.append(reason)
                self.metrics.incr("watchdog.failures")
                # Post-mortem artifact (ddl_tpu.obs): a watchdog
                # failure is terminal for the pipeline — dump the
                # flight ring before on_failure escalates (no-op when
                # no recorder is armed).
                from ddl_tpu.obs.recorder import flight_dump

                flight_dump(
                    "watchdog.failure",
                    # A stall-class failure is not per-producer; only a
                    # death/respawn path identifies one.
                    producer_idx=self._dead_idx,
                    metrics=self.metrics,
                    extra={"reason": reason},
                )
                self.on_failure(reason)
                return
