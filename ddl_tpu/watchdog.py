"""Failure detection: producer liveness + pipeline progress watchdog.

The reference had no failure detection — a dead producer deadlocked the
trainer until an external timeout killed the job (SURVEY §5.3; its only
detector was the pytest 100 s timeout, reference ``tests/test_ddl.py:8``).
ddl_tpu layers three mechanisms:

1. Every transport wait is bounded (``StallTimeoutError``) — built into
   the rings.
2. Control channels detect peer death as EOF (``PipeChannel``).
3. This watchdog: a consumer-side monitor thread that periodically checks
   worker liveness and ring progress and invokes a callback (default: log
   + initiate shutdown) when a producer dies or stalls beyond its budget.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("ddl_tpu")


class Watchdog:
    """Monitors a WorkerSet + its rings from the consumer side."""

    def __init__(
        self,
        workers: Any,  # ddl_tpu.env.WorkerSet
        poll_interval_s: float = 2.0,
        stall_budget_s: float = 120.0,
        on_failure: Optional[Callable[[str], None]] = None,
    ):
        self.workers = workers
        self.poll_interval_s = poll_interval_s
        self.stall_budget_s = stall_budget_s
        self.on_failure = on_failure or self._default_on_failure
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_progress: Dict[int, tuple] = {}
        self._last_change: Dict[int, float] = {}
        self.failures: List[str] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Watchdog":
        self._thread = threading.Thread(
            target=self._run, name="ddl-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.poll_interval_s * 2 + 1)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- checks ------------------------------------------------------------

    def _default_on_failure(self, reason: str) -> None:
        logger.error("watchdog: %s — initiating shutdown", reason)
        try:
            self.workers.abort()
        except Exception:  # pragma: no cover - best effort
            pass

    def check_once(self) -> Optional[str]:
        """One sweep; returns a failure description or None."""
        rings = self.workers.connection.rings
        # Clean shutdown is initiated ring-by-ring (loader.shutdown() flags
        # rings sequentially), so a sweep landing mid-teardown may see some
        # rings flagged and some not while producer threads are already
        # exiting. Treat ANY shut-down ring as shutdown-in-progress rather
        # than flagging spurious "producer died" failures. Ring-like doubles
        # without is_shutdown() are treated as live.
        if rings and any(
            getattr(r, "is_shutdown", lambda: False)() for r in rings
        ):
            return None
        for i, t in enumerate(self.workers.threads):
            if not t.is_alive():
                return f"producer thread {i + 1} died"
        for i, p in enumerate(self.workers.processes):
            if p.exitcode is not None and p.exitcode != 0:
                return f"producer process {i + 1} exited with {p.exitcode}"
        now = time.monotonic()
        for i, ring in enumerate(rings):
            st = ring.stats()
            progress = (st["committed"], st["released"])
            if self._last_progress.get(i) != progress:
                self._last_progress[i] = progress
                self._last_change[i] = now
            elif (
                st["committed"] == st["released"]  # producer owes a window
                and now - self._last_change.get(i, now) > self.stall_budget_s
            ):
                return (
                    f"ring {i} made no progress for {self.stall_budget_s}s "
                    f"(committed={st['committed']:.0f})"
                )
        return None

    def _run(self) -> None:
        # Workers that already exited cleanly (end of run) are expected;
        # only flag failures while the pipeline is supposed to be live.
        while not self._stop.wait(self.poll_interval_s):
            try:
                reason = self.check_once()
            except Exception:
                # A crashing sweep must never silently disable failure
                # detection; log and keep monitoring.
                logger.exception("watchdog: check_once raised; continuing")
                continue
            if reason is not None:
                self.failures.append(reason)
                self.on_failure(reason)
                return
