"""User extension surface: the producer-function skeleton.

API-compatible with reference ``ddl/datasetwrapper.py:4-19`` and
``ddl/datapusher.py:14-19``: users subclass :class:`ProducerFunctionSkeleton`,
override ``on_init`` (load the dataset, report geometry), ``post_init``
(write the first window) and ``execute_function`` (refill / in-place shuffle
each iteration).  Instances are constructed on the consumer and shipped to
producer workers by pickle (reference ``ddl/mpi_dataloader.py:130-136``),
so subclasses must be picklable.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any

import numpy as np


@dataclasses.dataclass
class DataProducerOnInitReturn:
    """Geometry a producer function reports from ``on_init``.

    Parity: reference ``ddl/datapusher.py:14-19``.

    Attributes:
      nData:   number of samples in one window (rows).
      nValues: flattened feature width per sample (columns).
      shape:   full window shape, normally ``(nData, nValues)``.
      splits:  column widths to re-split a batch into the user's tensor
               tuple, e.g. ``(3, 1, 1)`` for (x, y, weight)
               (reference ``tests/run_ddl.py:156-159``).
      dtype:   window element dtype.  The reference hardwired float32
               (``ddl/connection.py:105-106``, SURVEY Q5); here any numpy
               dtype is honoured end-to-end.
    """

    nData: int
    nValues: int
    shape: tuple[int, ...]
    splits: tuple[int, ...]
    dtype: Any = np.float32


class ProducerFunctionSkeleton(abc.ABC):
    """Abstract producer function (reference ``ddl/datasetwrapper.py:4``).

    Lifecycle inside a producer worker:

    1. ``on_init(producer_idx=..., n_producers=..., instance_idx=...,
       n_instances=...)`` → :class:`DataProducerOnInitReturn`.  Load/open the
       dataset shard for this worker here (lazily — this runs in the worker,
       not on the consumer).
    2. ``post_init(my_ary=...)`` → write the initial window contents into
       the provided array view (reference ``tests/run_ddl.py:152-161``).
    3. ``execute_function(my_ary=..., epoch=...)`` → called once per window
       refill; typically an in-place shuffle or the next chunk of a stream
       (reference ``tests/run_ddl.py:163-167``).

    All hooks accept ``**kwargs`` so the framework can grow the context it
    passes without breaking user subclasses.

    ``inplace_fill``: when True, ``my_ary`` is a direct view of the next
    free ring slot rather than a private array, and the commit copy is
    skipped — the zero-copy fill path (the reference's ``my_ary`` *was*
    the shared window, reference ``tests/run_ddl.py:152-161``; here that
    is opt-in because slots rotate).  Contract: ``execute_function`` must
    fully write ``my_ary`` every call — its prior content is the window
    from ``nslots`` iterations ago, not the previous one.

    ``supports_inplace_fill``: the soft variant — "every fill fully
    rewrites the window, hand me a slot view when you can".  The pusher
    then fills in place by default but silently keeps the private-array
    fill when a cross-instance global shuffle needs ``my_ary`` to
    persist, or when ``DDL_TPU_INPLACE=0`` opts out.  Every built-in
    reader advertises it (write-once producers, docs/PERF_NOTES.md).
    """

    inplace_fill: bool = False
    supports_inplace_fill: bool = False

    #: Wire-format capability (``ddl_tpu.wire``, opt-in per reader):
    #: ``"raw"`` (default) commits windows at their storage dtype;
    #: ``"bf16"`` / ``"int8"`` license the pusher to commit the
    #: blockwise-encoded wire payload instead (scales in the integrity
    #: trailer extension, decoded at the consumer edge) — valid only
    #: for float windows, and a LOSSY statement: set it on readers
    #: whose data tolerates the quantization (the loss-parity gate is
    #: the license — docs/PERF_NOTES.md "Wire format").  The
    #: ``DDL_TPU_WIRE_DTYPE`` env overrides either way.
    wire_dtype: str = "raw"

    @abc.abstractmethod
    def on_init(self, **kwargs: Any) -> DataProducerOnInitReturn:
        raise NotImplementedError

    def post_init(self, **kwargs: Any) -> None:
        """Fill the first window. Default: no-op (stream-style producers)."""

    def execute_function(self, **kwargs: Any) -> None:
        """Refill/refresh the window before each handoff. Default: no-op."""

    def adopt_shards(self, ranges: Any, **kwargs: Any) -> None:
        """Adopt shard ``ranges`` mid-run (cross-host elastic recovery,
        :mod:`ddl_tpu.cluster`): a view change re-partitioned a dead
        host's shard range onto this producer's host.  ``ranges`` is a
        tuple of half-open ``(start, stop)`` shard-index pairs — the
        receiving host's FULL post-change assignment, not a delta —
        with ``peer_idx``/``n_peers`` kwargs locating this producer
        among its host's loader ranks.  Default: no-op (producers that
        never partition by shard ignore adoption)."""

    def fast_forward(self, n: int, **kwargs: Any) -> None:
        """Advance the producer's data position by ``n`` windows without
        publishing them — elastic recovery replays a respawned worker to
        where its predecessor died.  Default: ``n`` ordinary
        ``execute_function`` calls with the same kwargs the hot loop
        passes (``my_ary`` plus the per-call ``iteration``), which is
        exact for any producer whose state advances only through that
        hook (seeded shuffles, stream cursors).  Producers with cheaper
        position arithmetic (e.g. a file offset) should override."""
        for i in range(n):
            self.execute_function(iteration=i, **kwargs)
