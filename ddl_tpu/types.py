"""Shared types for ddl_tpu.

Parity with reference ``ddl/types.py`` (``Marker`` at :35, metadata
dataclasses at :8/:16, ``MPI_Env`` at :25) — re-designed for a TPU topology:
instead of four MPI communicators there is a :class:`Topology` describing how
loader (producer) workers and trainer (consumer) processes map onto JAX
processes and the device mesh.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import TYPE_CHECKING, Any, Optional, Sequence

import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from ddl_tpu.datasetwrapper import ProducerFunctionSkeleton


class Marker(enum.Enum):
    """Progress markers the user reports to the dataloader.

    API-compatible with reference ``ddl/types.py:35-37``.  The user calls
    ``loader.mark(Marker.END_OF_BATCH)`` after every optimisation step and
    ``loader.mark(Marker.END_OF_EPOCH)`` after every epoch; window rotation
    and shutdown are driven off these marks
    (reference ``ddl/mpi_dataloader.py:89-102``).
    """

    END_OF_BATCH = 1
    END_OF_EPOCH = 2


class RunMode(enum.Enum):
    """How producer workers are realised.

    The reference had exactly one mode — MPI ranks bifurcated by the
    ``@distributed_dataloader`` decorator (reference ``ddl/ddl_env.py:100``).
    TPU-native modes:

    - THREAD: producers are threads inside the trainer process.  Makes
      single-process use first-class (fixes SURVEY Q9, where a single rank
      silently produced an empty loader, reference
      ``ddl/mpi_dataloader.py:173-174``).
    - PROCESS: producers are spawned host processes writing into a native
      shared-memory ring (the analog of MPI ``Win.Allocate_shared``,
      reference ``ddl/connection.py:115-131``).
    - MULTIHOST: PROCESS per host, plus cross-host global shuffle riding the
      device mesh (XLA all-to-all over ICI/DCN instead of
      ``Sendrecv_replace``, reference ``ddl/shuffle.py:92-108``).
    """

    THREAD = "thread"
    PROCESS = "process"
    MULTIHOST = "multihost"


@dataclasses.dataclass
class MetaData_Consumer_To_Producer:
    """Handshake payload: consumer → every producer.

    Parity: reference ``ddl/types.py:8-13``.  Carries the pickled user
    producer-function object (code-shipping by serialisation, reference
    ``ddl/mpi_dataloader.py:130-136``) plus the batch geometry.
    """

    data_producer_function: "ProducerFunctionSkeleton"
    batch_size: int
    n_epochs: int = 1
    global_shuffle_fraction_exchange: float = 0.0
    exchange_method: str = "sendrecv_replace"


@dataclasses.dataclass
class MetaData_Producer_To_Consumer:
    """Handshake payload: each producer → consumer.

    Parity: reference ``ddl/types.py:16-22``.  Reports the window geometry
    the producer computed from the user's ``on_init``
    (reference ``ddl/datapusher.py:66-81``).
    """

    producer_idx: int
    n_data: int
    n_values: int
    shape: tuple[int, ...]
    splits: tuple[int, ...]
    batches_per_window: int
    dtype: str = "float32"  # reference hardwired float32 (SURVEY Q5); we don't
    ring_ref: Any = None  # shm name (PROCESS) or WindowRing object (THREAD)
    #: This producer stamps checksummed window headers (ddl_tpu.integrity)
    #: past each slot payload; the consumer verifies at drain.  Carried in
    #: the handshake so producer and consumer always agree on slot layout.
    integrity: bool = False
    #: Wire format this producer's slots are committed in
    #: (``ddl_tpu.wire``): ``"raw"`` (the storage dtype) or the
    #: blockwise-encoded ``"bf16"``/``"int8"`` lossy tier — ``shape``/
    #: ``dtype`` above always describe the LOGICAL window; the consumer
    #: decodes at its edge.  Carried in the handshake so both sides
    #: agree on slot layout, exactly like ``integrity``.
    wire_dtype: str = "raw"


@dataclasses.dataclass
class ReplayRequest:
    """Consumer → producer: re-commit the window stream from ``seq``.

    Sent over the control channel when drain-time integrity verification
    quarantines a corrupt slot (``ddl_tpu.integrity``).  The producer
    rewinds with the same deterministic-replay recipe elastic respawn
    uses (``on_init`` → ``post_init`` → ``fast_forward(seq)``) and
    re-commits windows ``seq, seq+1, ...``; the consumer discards
    in-flight successors until the replayed ``seq`` arrives.
    """

    seq: int


@dataclasses.dataclass
class ShardAdoption:
    """Consumer → producer: adopt shard ``ranges`` as of cluster view
    ``view_epoch`` (cross-host elastic recovery, :mod:`ddl_tpu.cluster`).

    Sent over the control channel when a view change re-partitions a
    dead host's shard range across survivors.  ``ranges`` is the
    receiving producer's HOST-level range list (``(start, stop)``
    half-open shard-index pairs); ``peer_idx``/``n_peers`` locate the
    producer among its host's loader ranks so multi-producer hosts can
    subdivide.  ``suspend_exchange`` rides along: ``True`` degrades the
    cross-instance shuffle to node-local until rejoin (the documented
    ladder rung), ``False`` resumes it, ``None`` leaves it alone.
    Stale epochs (``view_epoch`` <= the last applied one) are ignored by
    the producer — view changes are fenced, never reordered.
    """

    ranges: tuple
    view_epoch: int
    peer_idx: int = 0
    n_peers: int = 1
    suspend_exchange: Optional[bool] = None


@dataclasses.dataclass
class ControlEnvelope:
    """Consumer → producer: one sequenced, fenced, acknowledged control
    command (:mod:`ddl_tpu.transport.envelope`).

    Wraps a control payload (:class:`ReplayRequest` /
    :class:`ShardAdoption`) in the at-least-once + dedup contract:
    ``(incarnation, seq)`` uniquely identifies the send across sender
    restarts, so the receiver can suppress duplicates (retry storms,
    the ``CONTROL_MSG_DUP`` chaos kind) while still re-acking them —
    the sender retries with exponential backoff until acked.  ``fence``
    carries the supervisor's fencing term (:mod:`ddl_tpu.cluster.
    supervision`): a receiver that has seen a newer term drops the
    payload unapplied (a zombie ex-leader's stale command), but still
    acks so the dead sender stops retrying.
    """

    seq: int
    incarnation: int
    fence: int
    payload: Any


@dataclasses.dataclass
class ControlAck:
    """Producer → consumer: acknowledgement of one
    :class:`ControlEnvelope` (:mod:`ddl_tpu.transport.envelope`).

    ``(incarnation, seq)`` echoes the envelope's dedup key so the
    sender clears exactly that pending retry.  ``dup`` marks a
    suppressed duplicate (applied once before; re-acked, not
    re-applied); ``fence_rejected`` marks a payload dropped by the
    fencing rule — both are terminal for the sender's retry loop.
    ``producer_idx`` names the acking producer for the consumer's
    muxed drain.
    """

    seq: int
    incarnation: int
    producer_idx: int = 0
    dup: bool = False
    fence_rejected: bool = False


@dataclasses.dataclass
class ObsReport:
    """Producer → consumer: one cross-process observability report
    (:mod:`ddl_tpu.obs` aggregation).

    Rides the same control channel as :class:`ReplayRequest` /
    :class:`ShardAdoption`.  ``snapshot`` is the worker registry's
    CUMULATIVE ``Metrics.snapshot()`` (so consumer-side merging is
    replace-based and can never double-count), ``hists`` its
    ``Metrics.hist_state()``, ``spans`` the armed SpanLog's event delta
    since the last report (empty when tracing is disarmed).
    ``report_idx`` is monotone per producer incarnation — the consumer
    drops stale reports (the ShardAdoption epoch-fence pattern);
    ``view_epoch`` carries the producer's cluster fence alongside.
    """

    producer_idx: int
    report_idx: int
    pid: int
    snapshot: dict
    hists: dict = dataclasses.field(default_factory=dict)
    spans: list = dataclasses.field(default_factory=list)
    view_epoch: int = 0


@dataclasses.dataclass(frozen=True)
class Topology:
    """Process/worker topology — the TPU-native replacement for ``MPI_Env``.

    The reference bundled four MPI communicators (reference
    ``ddl/types.py:25-32``); communicator *roles* map as:

    - ``comm_per_gpu_shm`` (one trainer + its producers on one node,
      reference ``ddl/ddl_env.py:58-67``)  →  (``instance_idx``, the set of
      ``n_producers`` local workers).  The reference's hard check that a
      block never spans nodes (``ddl_env.py:72-73``) holds by construction:
      producers are always local to their trainer host.
    - ``comm_nth_pusher`` (k-th producer of every instance, reference
      ``ddl/ddl_env.py:74-81``)  →  the global-shuffle peer group, realised
      on-device over the data-parallel mesh axis.
    - ``comm_global`` → `jax.distributed` / the process grid.
    """

    n_instances: int = 1
    instance_idx: int = 0
    n_producers: int = 2
    mode: RunMode = RunMode.THREAD
    #: Physical host identity (``ddl_tpu.cluster``): with multiple
    #: consumer processes per host (e.g. one per chip on a multi-chip
    #: host), ``instance_idx`` over-counts hosts — the membership view
    #: and placement engine need REAL host boundaries.  Defaults keep
    #: the historical one-consumer-per-host reading.
    host_id: int = 0
    n_hosts: int = 1

    def __post_init__(self) -> None:
        if self.n_instances < 1 or self.n_producers < 1:
            raise ValueError(
                f"need >=1 instance and >=1 producer, got "
                f"{self.n_instances=} {self.n_producers=}"
            )
        if not (0 <= self.instance_idx < self.n_instances):
            raise ValueError(f"{self.instance_idx=} out of range")
        if self.n_hosts < 1 or not (0 <= self.host_id < self.n_hosts):
            raise ValueError(
                f"{self.host_id=} out of range for {self.n_hosts=}"
            )
        # n_hosts may legitimately EXCEED n_instances: a single-host
        # THREAD/PROCESS run launched inside a multi-node allocation
        # still knows it is node k of N, and MPMD-style loader-only
        # hosts carry no consumer process at all (ddl_tpu.cluster).

    @property
    def world_size(self) -> int:
        """Total worker count, reference-rank-speak: (1+P) per instance."""
        return self.n_instances * (self.n_producers + 1)


@dataclasses.dataclass
class DDL_Env:
    """Per-run environment handed to the user's decorated main.

    Parity: the reference passed ``MPI_Env`` + ``Connection`` into the
    user function (reference ``ddl/ddl_env.py:115-116``); here the bundle is
    the topology plus the per-producer transport endpoints.
    """

    topology: Topology
    connection: Any  # ddl_tpu.transport Connection; Any to avoid cycle
    workers: Any = None  # ddl_tpu.env.WorkerSet (consumer side); for
    # liveness monitoring (Watchdog) and abort plumbing

    @property
    def is_consumer(self) -> bool:
        return True  # the decorated user function only ever runs on consumers


@dataclasses.dataclass
class WindowSpec:
    """Geometry of one producer's window (one ring slot payload).

    ``shape`` is (n_data, n_values) — samples are rows, feature columns are
    the concatenation the consumer re-splits with ``splits``
    (reference ``ddl/mpi_dataloader.py:195-197``).
    """

    shape: tuple[int, ...]
    dtype: np.dtype
    splits: tuple[int, ...]
    batch_size: int

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape)) * np.dtype(self.dtype).itemsize

    @property
    def batches_per_window(self) -> int:
        return int(self.shape[0]) // self.batch_size


def normalize_splits(splits: Sequence[int] | int, n_values: int) -> tuple[int, ...]:
    """Validate/normalise the column-split spec against the value width."""
    if isinstance(splits, int):
        splits = (splits,)
    splits = tuple(int(s) for s in splits)
    if sum(splits) != n_values:
        from ddl_tpu.exceptions import DoesNotMatchError

        raise DoesNotMatchError(
            splits, f"splits must sum to n_values={n_values}, got sum={sum(splits)}"
        )
    return splits


#: The cross-process control-channel protocol, declared as data so
#: ``tools/ddl_verify`` VP004 can check dispatch exhaustiveness: every
#: type listed here must have an ``isinstance`` arm in each configured
#: dispatcher for its direction, and every type a dispatcher matches
#: must be declared here (a new message class cannot ship half-wired).
#: The consumer's ABORT broadcast is a ``str`` sentinel, not a class
#: (``ddl_tpu.env.ABORT``) — it rides the same channel but is checked
#: by the dispatchers' string arm, outside these tuples.
CONSUMER_TO_PRODUCER_CONTROL = (ReplayRequest, ShardAdoption, ControlEnvelope)
PRODUCER_TO_CONSUMER_CONTROL = (ObsReport, ControlAck)
