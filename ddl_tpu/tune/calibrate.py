"""Boot-time calibration: measured costs in, a tuned overlay out.

The :class:`Calibrator` runs the same economics the operator-facing
probes print — ``probe_wire``'s break-even table
(:func:`ddl_tpu.wire.break_even_table`, one shared implementation) and
``probe_link_costs``'s pairwise bandwidth measurement (pluggable
``transfer``, exactly as the placement engine consumes it) — and turns
them into a :class:`TunedConfig`: an overlay of ``LoaderConfig`` fields
plus env exports for registry knobs that have no config field
(``DDL_TPU_DISTRIBUTE``).

Discipline:

- **Provenance.**  Every :class:`Decision` carries ``cost_source`` —
  ``measured`` (a probe ran and its numbers drove the pick),
  ``declared`` (the caller supplied costs; trusted, not verified), or
  ``default`` (budget exhausted or no probe possible; the shipped
  default stands).  The pattern is ``LinkCosts.source`` made universal:
  an operator reading the artifact can tell a measured win from a
  guess.
- **Deadline budget.**  The whole pass runs against ONE monotonic
  deadline (``DDL_TPU_TUNE_DEADLINE_S``); each probe checks the
  remaining budget before starting and the wire microbenchmark checks
  it between formats.  A probe that would overrun is skipped and its
  knob decided ``default`` — calibration can never stall training
  start (DDL018's rule applied to boot).
- **Audit.**  Each decision increments ``tune.decisions`` and
  ``tune.cost_source.<src>`` and lands in the flight-recorder ring
  (``("tune", "calibrate.<knob>", value)``) when armed.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ddl_tpu import envspec, wire
from ddl_tpu.cluster.topology import LinkCosts, probe_link_costs
from ddl_tpu.exceptions import ShutdownRequested
from ddl_tpu.obs.recorder import flight_note
from ddl_tpu.observability import Metrics, metrics as default_metrics

logger = logging.getLogger("ddl_tpu")

#: Provenance labels (the LinkCosts.source pattern, made universal).
COST_MEASURED = "measured"
COST_DECLARED = "declared"
COST_DEFAULT = "default"

#: Wire-stat sample geometry: small enough to measure in milliseconds,
#: token-valued floats like the bench's shard shape.
_SAMPLE_SHAPE = (256, 1024)


def _numeric(value: Any) -> float:
    """A float for the flight ring: wire dtypes map through their
    stable on-the-wire codes, other strings to 0.0."""
    if isinstance(value, str):
        return float(wire.WIRE_CODES.get(value, 0.0))
    try:
        return float(value)
    except (TypeError, ValueError):
        return 0.0


@dataclasses.dataclass(frozen=True)
class Decision:
    """One audited knob decision: what changed, on what evidence."""

    knob: str
    old: Any
    new: Any
    #: measured | declared | default (module doc).
    cost_source: str
    #: Human-readable trigger ("break-even 38.2 MiB/s > link 12.0").
    reason: str
    #: The signal values that drove it (empty for default decisions).
    signals: Dict[str, float] = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "knob": self.knob,
            "old": self.old,
            "new": self.new,
            "cost_source": self.cost_source,
            "reason": self.reason,
            "signals": dict(self.signals),
        }


@dataclasses.dataclass
class TunedConfig:
    """The Calibrator's output: a provenance-stamped config overlay.

    ``overlay`` holds ``LoaderConfig`` field values (:meth:`apply`
    produces the overlaid config); ``env`` holds registry knobs with no
    config field (:meth:`export` publishes them for envspec readers and
    spawned workers).  ``decisions`` records EVERY knob the pass judged
    — including ones left at their defaults — so absence of evidence is
    itself auditable.
    """

    decisions: List[Decision] = dataclasses.field(default_factory=list)
    overlay: Dict[str, Any] = dataclasses.field(default_factory=dict)
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    budget_s: float = 0.0
    elapsed_s: float = 0.0
    #: True when any probe was skipped for budget (its knob went
    #: ``default``) — the artifact's "calibration was partial" flag.
    deadline_hit: bool = False

    def apply(self, config: Any) -> Any:
        """``config`` with the overlay fields replaced (a new dataclass
        instance; the input is not mutated)."""
        fields = {
            k: v for k, v in self.overlay.items()
            if hasattr(config, k)
        }
        return dataclasses.replace(config, **fields)

    def export(self) -> None:
        """Publish the non-config knobs into the environment (the
        envspec seam loader construction and worker spawn read)."""
        import os

        for var, value in self.env.items():
            os.environ[var] = str(value)

    def cost_sources(self) -> Dict[str, int]:
        out = {COST_MEASURED: 0, COST_DECLARED: 0, COST_DEFAULT: 0}
        for d in self.decisions:
            out[d.cost_source] = out.get(d.cost_source, 0) + 1
        return out

    def as_report(self) -> dict:
        """The bench/artifact block body."""
        return {
            "decisions": [d.as_dict() for d in self.decisions],
            "overlay": dict(self.overlay),
            "env": dict(self.env),
            "cost_sources": self.cost_sources(),
            "budget_s": round(self.budget_s, 3),
            "elapsed_s": round(self.elapsed_s, 4),
            "deadline_hit": self.deadline_hit,
        }


class Calibrator:
    """Boot-time knob calibration under a deadline budget (module doc).

    ``link_costs`` supplies DECLARED link speeds (no probe runs for
    them); ``hosts`` + ``transfer`` instead requests a MEASURED
    ``probe_link_costs`` pass (``transfer`` pluggable exactly as the
    placement probe's — a real deployment wires a DCN send/recv pair).
    ``sample`` overrides the wire microbenchmark's input (e.g. a real
    shard slice); ``distribute_probe`` is a zero-arg callable returning
    ``{"ici": bytes_per_s, "xla": bytes_per_s}`` measured on the actual
    mesh (``tools/probe_ici.py``-style) — absent, the distribution knob
    stays at its shipped default.
    """

    def __init__(
        self,
        deadline_s: Optional[float] = None,
        link_costs: Optional[LinkCosts] = None,
        hosts: Optional[List[int]] = None,
        transfer: Optional[Callable[[int, int, np.ndarray], None]] = None,
        sample: Optional[np.ndarray] = None,
        distribute_probe: Optional[Callable[[], Dict[str, float]]] = None,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.deadline_s = (
            envspec.get("DDL_TPU_TUNE_DEADLINE_S")
            if deadline_s is None
            else float(deadline_s)
        )
        self.link_costs = link_costs
        self.hosts = list(hosts) if hosts else []
        self.transfer = transfer
        self.sample = sample
        self.distribute_probe = distribute_probe
        self.metrics = metrics or default_metrics()
        self._clock = clock

    # -- decision plumbing -------------------------------------------------

    def _decide(
        self,
        tuned: TunedConfig,
        knob: str,
        old: Any,
        new: Any,
        cost_source: str,
        reason: str,
        signals: Optional[Dict[str, float]] = None,
    ) -> None:
        d = Decision(
            knob=knob, old=old, new=new, cost_source=cost_source,
            reason=reason, signals=signals or {},
        )
        tuned.decisions.append(d)
        self.metrics.incr("tune.decisions")
        self.metrics.incr(f"tune.cost_source.{cost_source}")
        flight_note("tune", f"calibrate.{knob}", _numeric(new))
        logger.info(
            "tune: calibrate %s %r -> %r (%s: %s)",
            knob, old, new, cost_source, reason,
        )

    # -- the pass ----------------------------------------------------------

    def calibrate(self, config: Any = None) -> TunedConfig:
        """Run every probe the budget allows; return the overlay.

        ``config`` (a ``LoaderConfig`` or None) supplies the OLD values
        decisions are recorded against; the returned overlay is applied
        with :meth:`TunedConfig.apply` / :meth:`TunedConfig.export` by
        the caller — calibration computes, the caller commits.
        """
        t0 = self._clock()
        deadline = t0 + max(0.0, self.deadline_s)
        tuned = TunedConfig(budget_s=self.deadline_s)

        costs, link_source = self._link_costs(deadline, tuned)
        self._calibrate_wire(config, tuned, deadline, costs, link_source)
        self._calibrate_distribute(tuned, deadline)
        self._calibrate_depths(config, tuned)

        tuned.elapsed_s = self._clock() - t0
        return tuned

    def _remaining(self, deadline: float) -> float:
        return deadline - self._clock()

    def _link_costs(
        self, deadline: float, tuned: TunedConfig
    ) -> tuple:
        """(LinkCosts, provenance): declared wins, then a measured
        probe inside the remaining budget, then defaults."""
        if self.link_costs is not None:
            return self.link_costs, COST_DECLARED
        remaining = self._remaining(deadline)
        if self.hosts and len(self.hosts) > 1 and remaining > 0:
            costs = probe_link_costs(
                self.hosts, self.transfer, timeout_s=remaining
            )
            if costs.n_links:
                return costs, COST_MEASURED
        else:
            tuned.deadline_hit = tuned.deadline_hit or remaining <= 0
        return LinkCosts({}, source="default"), COST_DEFAULT

    def _link_bottleneck(self, costs: LinkCosts) -> float:
        """The slowest known hop — the link every wire byte must be
        priced against (unknown fabrics price at the default floor)."""
        hosts = costs.hosts()
        if len(hosts) < 2:
            return costs.default_bytes_per_s
        return min(
            costs.bytes_per_s(a, b)
            for i, a in enumerate(hosts)
            for b in hosts[i + 1:]
        )

    def _calibrate_wire(
        self,
        config: Any,
        tuned: TunedConfig,
        deadline: float,
        costs: LinkCosts,
        link_source: str,
    ) -> None:
        old = getattr(config, "wire_dtype", "") or "raw"
        if self._remaining(deadline) <= 0:
            tuned.deadline_hit = True
            self._decide(
                tuned, "wire_dtype", old, old, COST_DEFAULT,
                "calibration budget exhausted before the wire probe",
            )
            return
        sample = self.sample
        if sample is None:
            rng = np.random.default_rng(0)
            sample = rng.integers(0, 32, _SAMPLE_SHAPE).astype(np.float32)
        stats = wire.measure_wire_stats(
            np.asarray(sample), deadline=deadline
        )
        if not stats:
            tuned.deadline_hit = True
            self._decide(
                tuned, "wire_dtype", old, old, COST_DEFAULT,
                "wire microbenchmark skipped (budget/dtype)",
            )
            return
        link = self._link_bottleneck(costs)
        pick = wire.pick_wire_format(stats, link)
        if pick not in wire.WIRE_DTYPES:
            # A codec won the economics; the wire_dtype knob only
            # carries the lossy tier — leave it raw and let the codec
            # knob (operator-set) cover the lossless tier.
            pick = "raw"
        # The decision's evidence is the break-even table vs the link.
        be = wire.break_even_table(stats)
        signals = {"link_bytes_per_s": round(link, 1)}
        signals.update(
            {f"break_even.{f}": round(v, 1) for f, v in be.items()}
        )
        src = COST_MEASURED if link_source == COST_MEASURED else link_source
        self._decide(
            tuned, "wire_dtype", old, pick, src,
            f"pick_wire_format at link {link:.3e} B/s "
            f"({link_source} link, measured wire stats)",
            signals,
        )
        if pick != old:
            tuned.overlay["wire_dtype"] = pick

    def _calibrate_distribute(
        self, tuned: TunedConfig, deadline: float
    ) -> None:
        old = envspec.get("DDL_TPU_DISTRIBUTE")
        if self.distribute_probe is None:
            self._decide(
                tuned, "distribute", old, old, COST_DEFAULT,
                "no distribution probe supplied (auto resolves per "
                "platform at ingest)",
            )
            return
        if self._remaining(deadline) <= 0:
            tuned.deadline_hit = True
            self._decide(
                tuned, "distribute", old, old, COST_DEFAULT,
                "calibration budget exhausted before the "
                "distribution probe",
            )
            return
        try:
            rates = dict(self.distribute_probe())
        except (ShutdownRequested, KeyboardInterrupt):
            raise
        except Exception as e:  # noqa: BLE001 - a dead probe keeps defaults
            logger.warning("tune: distribution probe failed: %s", e)
            self._decide(
                tuned, "distribute", old, old, COST_DEFAULT,
                f"distribution probe failed ({type(e).__name__})",
            )
            return
        if not rates:
            self._decide(
                tuned, "distribute", old, old, COST_DEFAULT,
                "distribution probe returned no rates",
            )
            return
        pick = max(sorted(rates), key=lambda k: rates[k])
        self._decide(
            tuned, "distribute", old, pick, COST_MEASURED,
            "fastest measured distribution tier",
            {f"bytes_per_s.{k}": round(v, 1) for k, v in rates.items()},
        )
        if pick != old:
            tuned.env["DDL_TPU_DISTRIBUTE"] = pick

    def _calibrate_depths(self, config: Any, tuned: TunedConfig) -> None:
        """Floor starved pipeline depths at their shipped defaults.

        Boot offers no compute profile to price depth against — the
        steady-state controller owns refinement — but a depth BELOW the
        shipped default is a known-starved configuration (no overlap at
        depth 1), so calibration restores the floor with ``default``
        provenance and leaves operator increases alone.
        """
        for knob, var, current in (
            ("prefetch_depth", "DDL_TPU_PREFETCH_DEPTH",
             getattr(config, "prefetch_depth", None)),
            ("staging_queue", "DDL_TPU_STAGING_QUEUE", None),
        ):
            spec = envspec.require(var)
            if current is None:
                current = envspec.get(var)
            floor = int(spec.default)
            if int(current) < floor:
                self._decide(
                    tuned, knob, int(current), floor, COST_DEFAULT,
                    f"depth {current} below the shipped default "
                    f"{floor}: no-overlap starvation at boot",
                )
                if knob == "prefetch_depth":
                    tuned.overlay["prefetch_depth"] = floor
                else:
                    tuned.env[var] = str(floor)
            else:
                self._decide(
                    tuned, knob, int(current), int(current), COST_DEFAULT,
                    "at/above the shipped default; steady-state "
                    "controller owns refinement",
                )
