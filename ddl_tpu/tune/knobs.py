"""Tunable-knob bindings: one uniform get/set seam per live knob.

A :class:`TunableKnob` binds a knob NAME to the object that owns it at
runtime — the PrefetchIterator's depth, the TransferExecutor's queue
bound, the StagingPool's per-geometry cap, a shuffler's per-round
``wire_dtype`` — with bounds the controller may never step outside and
a ``live`` flag separating knobs that retune mid-run from handshake-
time ones the Calibrator may only set before boot.  The controller
manipulates knobs ONLY through this seam (ddl-lint DDL027 enforces the
inverse: tuned call sites may not hardcode these constants).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Optional

from ddl_tpu import envspec


@dataclasses.dataclass
class TunableKnob:
    """One live tuning point: name + bound get/set + legal range."""

    name: str
    getter: Callable[[], Any]
    setter: Callable[[Any], None]
    #: Inclusive numeric bounds (None = unbounded on that side);
    #: ignored for non-numeric knobs like wire_dtype.
    lo: Optional[float] = None
    hi: Optional[float] = None
    #: False = boot-time only (slot-layout/handshake knobs): the
    #: steady-state controller must refuse to touch it mid-run.
    live: bool = True

    def read(self) -> Any:
        return self.getter()

    def clamp(self, value: Any) -> Any:
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            if self.lo is not None and value < self.lo:
                value = type(value)(self.lo)
            if self.hi is not None and value > self.hi:
                value = type(value)(self.hi)
        return value

    def write(self, value: Any) -> Any:
        """Clamp to bounds, apply, and return what was actually set."""
        value = self.clamp(value)
        self.setter(value)
        return value


def prefetch_knob(prefetch_iter: Any, lo: int = 1, hi: int = 16) -> TunableKnob:
    """Bind a :class:`~ddl_tpu.ingest.PrefetchIterator`'s depth."""
    return TunableKnob(
        name="prefetch_depth",
        getter=lambda: prefetch_iter._depth,
        setter=lambda v: prefetch_iter.set_depth(int(v)),
        lo=lo, hi=hi,
    )


def staging_queue_knob(executor: Any, lo: int = 1, hi: int = 32) -> TunableKnob:
    """Bind a :class:`~ddl_tpu.staging.TransferExecutor`'s queue bound."""
    return TunableKnob(
        name="staging_queue",
        getter=lambda: executor._max_queue,
        setter=lambda v: executor.set_max_queue(int(v)),
        lo=lo, hi=hi,
    )


def staging_pool_knob(pool: Any, lo: int = 1, hi: int = 64) -> TunableKnob:
    """Bind a :class:`~ddl_tpu.staging.StagingPool`'s per-geometry cap."""
    return TunableKnob(
        name="staging_pool_cap",
        getter=lambda: pool.max_per_key,
        setter=lambda v: pool.set_max_per_key(int(v)),
        lo=lo, hi=hi,
    )


def wire_dtype_knob(shuffler: Any) -> TunableKnob:
    """Bind an exchange shuffler's per-round ``wire_dtype``.

    Live for :class:`~ddl_tpu.shuffle.ThreadExchangeShuffler` (the
    attribute is consulted per exchange round); slot-transport wire
    dtypes are handshake-time and must NOT be bound here.
    """
    return TunableKnob(
        name="wire_dtype",
        getter=lambda: getattr(shuffler, "wire_dtype", "raw") or "raw",
        setter=lambda v: setattr(shuffler, "wire_dtype", v),
    )


def env_knob(
    var: str,
    lo: Optional[float] = None,
    hi: Optional[float] = None,
    live: bool = False,
) -> TunableKnob:
    """Bind a registered ``DDL_TPU_*`` env knob (the envspec seam).

    Boot-time by default: env writes only reach call sites that read
    the registry lazily (loader construction, worker spawn) — a
    :class:`~ddl_tpu.tune.calibrate.TunedConfig` export, not a mid-run
    retune.  The var must exist in the envspec registry (typo guard).
    """
    envspec.require(var)

    def _set(value: Any) -> None:
        os.environ[var] = str(value)

    return TunableKnob(
        name=var,
        getter=lambda: envspec.get(var),
        setter=_set,
        lo=lo, hi=hi, live=live,
    )
