"""Self-driving data plane: boot-time calibration + closed-loop tuning.

The loader exposes a dozen performance-critical knobs (wire_dtype and
codec, prefetch depth, staging queue/pool, ici-vs-xla distribution,
placement, autoscaler setpoints) and the PR-15 tracing layer built the
histograms and per-stage spans to judge them — but until this package a
human set every one, and a mis-set knob on an unfamiliar geometry
silently cost the throughput the stack was built to win (ROADMAP item
4).  Cost-model-driven reconfiguration is the established shape here:
arXiv:2105.14088 picks placement from measured link costs and
arXiv:2112.01075 prices redistribution legs before choosing them — this
package does the same for the ingest plane's own knobs, automatically:

- :class:`~ddl_tpu.tune.calibrate.Calibrator` — boot-time: runs the
  probe_wire break-even table against *measured* link speeds (the
  pluggable ``probe_link_costs``) plus a distribution microbenchmark,
  and emits a :class:`~ddl_tpu.tune.calibrate.TunedConfig` overlay onto
  ``LoaderConfig``/envspec.  Every decision carries ``cost_source``
  provenance (measured / declared / default — the placement engine's
  pattern) and the whole pass runs under a deadline budget
  (``DDL_TPU_TUNE_DEADLINE_S``) so calibration can never stall
  training start.
- :class:`~ddl_tpu.tune.controller.KnobController` — steady-state: a
  DDL018-compliant deadline loop watching ``window_latency_p99``, the
  windowed stall fraction, and ``stage_breakdown``, retuning prefetch
  depth and staging capacity under hysteresis (the Autoscaler
  precedent), re-running ``plan_placement`` on measured-cost drift,
  and flipping lossy wire off when ``loss_parity`` headroom shrinks.
  Every decision is flight-recorded (knob, old→new, triggering signal
  values) and guarded never-worse: a knob whose post-change window
  regresses is reverted.

Audit trail: ``tune.decisions`` / ``tune.reverts`` /
``tune.cost_source.*`` counters surface in ``north_star_report`` as
``tune_decisions`` / ``tune_reverts`` / ``tune_cost_source``, and each
decision lands in the flight-recorder ring (docs/TUNING.md walks a
post-mortem).  ``DDL_BENCH_MODE=autotune`` is the proof: self-tuned vs
shipped-defaults from a deliberately mis-matched cold start, gated
never-slower by bench_smoke.
"""

from ddl_tpu.tune.calibrate import (  # noqa: F401
    COST_DECLARED,
    COST_DEFAULT,
    COST_MEASURED,
    Calibrator,
    Decision,
    TunedConfig,
)
from ddl_tpu.tune.controller import (  # noqa: F401
    ControllerPolicy,
    KnobController,
)
from ddl_tpu.tune.knobs import (  # noqa: F401
    TunableKnob,
    env_knob,
    prefetch_knob,
    staging_pool_knob,
    staging_queue_knob,
    wire_dtype_knob,
)

__all__ = [
    "COST_DECLARED",
    "COST_DEFAULT",
    "COST_MEASURED",
    "Calibrator",
    "ControllerPolicy",
    "Decision",
    "KnobController",
    "TunableKnob",
    "TunedConfig",
    "env_knob",
    "prefetch_knob",
    "staging_pool_knob",
    "staging_queue_knob",
    "wire_dtype_knob",
]
