"""Steady-state knob control: hysteresis, never-worse, full audit.

The :class:`KnobController` is the Autoscaler's policy discipline
pointed at the data plane's own knobs.  Same loop shape (a DDL018
deadline loop on a stop event's timed wait), same hysteresis mechanics
(a signal must hold beyond its band for ``sustain_s`` before any
action; a dead band between the thresholds stops flapping; a
``cooldown_s`` spaces consecutive actions) — but where the Autoscaler
resizes the loader fleet, this retunes the knobs bound through
:mod:`ddl_tpu.tune.knobs`: prefetch depth and staging capacity on
sustained stall, the exchange wire on parity-headroom shrink, the
placement plan on measured-cost drift.

Two guarantees the Autoscaler does not need:

- **Never-worse.**  Every knob change opens an observation window (one
  cooldown long).  If the post-change window's throughput (windowed
  ``consumer.samples`` rate by default) regresses more than
  ``revert_tol`` below the pre-change window, the change is REVERTED,
  ``tune.reverts`` increments, and the revert itself is flight-recorded
  — a wrong guess costs one window, never a run.
- **Safety outranks pacing.**  The lossy-wire parity guard (flip to
  raw when measured drift eats into the ``loss_parity`` tolerance)
  ignores the cooldown and is one-way: the controller never re-enables
  a lossy wire it flipped off (re-arming is a human decision through
  calibration).

Every decision lands in the flight-recorder ring (``("tune", <knob>,
<new value>)``) and in ``tune.decisions`` / ``tune.cost_source.*`` —
``north_star_report`` surfaces the counters, docs/TUNING.md walks the
audit trail.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ddl_tpu import envspec
from ddl_tpu.cluster.placement import Placement, replan_on_drift
from ddl_tpu.exceptions import DDLError, ShutdownRequested
from ddl_tpu.faults import fault_point
from ddl_tpu.obs.recorder import flight_note
from ddl_tpu.observability import Metrics, metrics as default_metrics
from ddl_tpu.tune.calibrate import COST_MEASURED, Decision, _numeric
from ddl_tpu.tune.knobs import TunableKnob

logger = logging.getLogger("ddl_tpu")


@dataclasses.dataclass(frozen=True)
class ControllerPolicy:
    """Hysteresis + pacing + guard knobs for the tuning loop."""

    #: Grow pipeline depth when the windowed stall fraction holds above.
    up_stall_fraction: float = 0.25
    #: Shrink back toward baseline when it holds below (hysteresis
    #: floor; the gap to ``up_stall_fraction`` is the dead band).
    down_stall_fraction: float = 0.05
    #: Optional second up-signal: window-latency p99 (seconds) at/above
    #: this also counts as demand (0 disables it).
    up_latency_p99_s: float = 0.0
    #: How long a signal must hold beyond its band before acting.
    sustain_s: float = 2.0
    #: Minimum spacing between knob changes — ALSO the never-worse
    #: observation window a change is judged over.
    cooldown_s: float = 5.0
    #: Revert a change whose post-window throughput drops more than
    #: this fraction below the pre-window.
    revert_tol: float = 0.05
    #: Flip lossy wire to raw when measured drift exceeds this fraction
    #: of the parity tolerance.
    parity_headroom: float = 0.5
    #: Replan placement when any link's measured cost drifts beyond
    #: this relative tolerance.
    drift_rel_tol: float = 0.25

    def __post_init__(self) -> None:
        if not (0.0 <= self.down_stall_fraction < self.up_stall_fraction):
            raise DDLError(
                "hysteresis band requires 0 <= down_stall_fraction < "
                f"up_stall_fraction, got [{self.down_stall_fraction}, "
                f"{self.up_stall_fraction}]"
            )
        if self.sustain_s < 0 or self.cooldown_s < 0:
            raise DDLError("sustain_s/cooldown_s must be >= 0")
        if not (0.0 <= self.revert_tol < 1.0):
            raise DDLError("revert_tol must be in [0, 1)")
        if not (0.0 < self.parity_headroom <= 1.0):
            raise DDLError("parity_headroom must be in (0, 1]")

    @classmethod
    def from_env(cls) -> "ControllerPolicy":
        """Policy from the ``DDL_TPU_TUNE_*`` registry knobs."""
        return cls(
            sustain_s=envspec.get("DDL_TPU_TUNE_SUSTAIN_S"),
            cooldown_s=envspec.get("DDL_TPU_TUNE_COOLDOWN_S"),
            revert_tol=envspec.get("DDL_TPU_TUNE_REVERT_TOL"),
            parity_headroom=envspec.get("DDL_TPU_TUNE_PARITY_HEADROOM"),
        )


@dataclasses.dataclass
class _PendingChange:
    """One knob change under never-worse observation."""

    knob: TunableKnob
    old: Any
    new: Any
    opened_t: float
    work0: float
    pre_rate: float


class KnobController:
    """The closed loop binding PR-15 telemetry to live knob writes.

    ``knobs`` are the :class:`~ddl_tpu.tune.knobs.TunableKnob` bindings
    this controller may touch, in DEMAND PRIORITY order: on sustained
    stall the first growable depth knob grows (doubling, bounded);
    on sustained idleness the LAST grown knob shrinks back one step.
    Only ``live`` knobs are ever written.

    ``signal`` overrides the telemetry read — a zero-arg callable
    returning ``{"stall_fraction", "window_latency_p99"}``.  The
    default computes the WINDOWED stall fraction exactly as the
    Autoscaler does (deltas of ``consumer.wait`` minus admission waits
    over wall clock, per consumer) plus the shared histograms' p99.
    ``work`` overrides the never-worse guard's progress counter — a
    zero-arg callable returning cumulative work (default: the
    ``consumer.samples`` counter); throughput is its windowed rate.

    ``parity`` (optional) returns the current lossy-wire
    ``max_rel_drift`` (e.g. from a held-out
    :func:`~ddl_tpu.parallel.optimizer.loss_parity` probe) or None;
    ``wire_knob`` is the binding the parity guard flips.  ``view`` +
    ``costs_probe`` (zero-arg → ``LinkCosts``) arm the placement-drift
    leg against ``base_costs``.
    """

    def __init__(
        self,
        knobs: List[TunableKnob],
        policy: Optional[ControllerPolicy] = None,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
        signal: Optional[Callable[[], Dict[str, float]]] = None,
        work: Optional[Callable[[], float]] = None,
        parity: Optional[Callable[[], Optional[float]]] = None,
        parity_tol: Optional[float] = None,
        wire_knob: Optional[TunableKnob] = None,
        view: Any = None,
        costs_probe: Optional[Callable[[], Any]] = None,
        base_costs: Any = None,
        n_consumers: int = 1,
        poll_interval_s: Optional[float] = None,
    ):
        self.knobs = [k for k in knobs if k.live]
        self.policy = policy or ControllerPolicy.from_env()
        self.metrics = metrics or default_metrics()
        self._clock = clock
        self._signal = signal or self._windowed_signal
        self._work = work or (
            lambda: float(self.metrics.counter("consumer.samples"))
        )
        self._parity = parity
        if parity_tol is None:
            from ddl_tpu.parallel.optimizer import PARITY_REL_TOL

            parity_tol = PARITY_REL_TOL
        self.parity_tol = float(parity_tol)
        self.wire_knob = wire_knob
        self.view = view
        self._costs_probe = costs_probe
        self._costs = base_costs
        self.last_placement: Optional[Placement] = None
        self.n_consumers = max(1, int(n_consumers))
        self.poll_interval_s = (
            envspec.get("DDL_TPU_TUNE_INTERVAL_S")
            if poll_interval_s is None
            else poll_interval_s
        )
        #: Audit trail (Decision records, calibration's shape).
        self.decisions: List[Decision] = []
        #: Baseline values knobs shrink back toward.
        self._baseline = {k.name: k.read() for k in self.knobs}
        #: Knobs grown above baseline, newest last (shrink order).
        self._grown: List[TunableKnob] = []
        self._pending: Optional[_PendingChange] = None
        self._wire_flipped = False
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._last_action_t = -float("inf")
        self._last_wait_s = (
            self.metrics.timer("consumer.wait").total_s
            - self.metrics.timer("serve.admission_wait").total_s
        )
        self._last_wall = self._clock()
        self._last_work = self._work()
        self._rate_wall = self._last_wall
        self._last_rate = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signals -----------------------------------------------------------

    def _windowed_signal(self) -> Dict[str, float]:
        """Stall fraction over the span since the previous reading
        (the Autoscaler's windowed read: a cumulative fraction would
        dilute a fresh stall under a long quiet history), plus the
        shared window-latency p99."""
        now = self._clock()
        wait = (
            self.metrics.timer("consumer.wait").total_s
            - self.metrics.timer("serve.admission_wait").total_s
        )
        dt = max(now - self._last_wall, 1e-9)
        stall = (wait - self._last_wait_s) / dt / self.n_consumers
        self._last_wait_s, self._last_wall = wait, now
        return {
            "stall_fraction": max(0.0, stall),
            "window_latency_p99": self.metrics.quantile(
                "consumer.window_latency", 0.99
            ),
        }

    def _rate(self, now: float) -> float:
        """Windowed throughput (work units/s) since the last reading."""
        work = self._work()
        dt = max(now - self._rate_wall, 1e-9)
        rate = (work - self._last_work) / dt
        self._last_work = work
        self._rate_wall = now
        return max(0.0, rate)

    # -- decision plumbing -------------------------------------------------

    def _record(
        self,
        knob: str,
        old: Any,
        new: Any,
        reason: str,
        signals: Dict[str, float],
        revert: bool = False,
    ) -> None:
        d = Decision(
            knob=knob, old=old, new=new, cost_source=COST_MEASURED,
            reason=reason, signals=dict(signals),
        )
        self.decisions.append(d)
        self.metrics.incr("tune.decisions")
        self.metrics.incr(f"tune.cost_source.{COST_MEASURED}")
        if revert:
            self.metrics.incr("tune.reverts")
        flight_note(
            "tune", f"{'revert' if revert else 'retune'}.{knob}",
            _numeric(new),
        )
        logger.warning(
            "tune: %s %s %r -> %r (%s)",
            "REVERT" if revert else "retune", knob, old, new, reason,
        )

    # -- one policy evaluation ---------------------------------------------

    def step(self, now: Optional[float] = None) -> Optional[str]:
        """Evaluate the loop once; returns the action taken
        (``"grow"`` / ``"shrink"`` / ``"revert"`` / ``"wire_raw"`` /
        ``"replan"`` / ``None``).  Driven by :meth:`start`'s loop or
        called directly (tests, the bench's fast-forward clock)."""
        fault_point("tune.step")
        now = self._clock() if now is None else now
        sig = self._signal()
        rate = self._rate(now)

        # 1. Safety first: the parity guard ignores pacing entirely.
        acted = self._parity_guard(sig)
        if acted:
            self._last_rate = rate
            return acted

        # 2. Judge the open never-worse window before anything else —
        # a pending change must be accepted or reverted before the
        # controller may act again (the cooldown enforces the order).
        if self._pending is not None:
            acted = self._judge_pending(now, sig)
            if acted:
                self._last_rate = rate
                return acted

        # 3. Placement drift (no knob write; pacing still applies so a
        # noisy probe cannot replan every tick).
        if now - self._last_action_t >= self.policy.cooldown_s:
            acted = self._drift_replan(now, sig)
            if acted:
                self._last_rate = rate
                return acted

        # 4. Hysteresis over the stall band (the Autoscaler mechanics).
        action = self._hysteresis(now, sig, rate)
        self._last_rate = rate
        return action

    def _parity_guard(self, sig: Dict[str, float]) -> Optional[str]:
        if (
            self._parity is None
            or self.wire_knob is None
            or self._wire_flipped
        ):
            return None
        drift = self._parity()
        if drift is None:
            return None
        current = self.wire_knob.read()
        if current == "raw":
            return None
        budget = self.parity_headroom_budget()
        if drift <= budget:
            return None
        self.wire_knob.write("raw")
        self._wire_flipped = True
        self._record(
            self.wire_knob.name, current, "raw",
            f"parity headroom shrank: drift {drift:.3e} > "
            f"{self.policy.parity_headroom:.2f} x tol {self.parity_tol:.3e}",
            {**sig, "max_rel_drift": drift},
        )
        return "wire_raw"

    def parity_headroom_budget(self) -> float:
        """The drift level at which the lossy wire is no longer safe."""
        return self.policy.parity_headroom * self.parity_tol

    def _judge_pending(
        self, now: float, sig: Dict[str, float]
    ) -> Optional[str]:
        p = self._pending
        assert p is not None
        if now - p.opened_t < self.policy.cooldown_s:
            return None  # the observation window is still open
        dt = max(now - p.opened_t, 1e-9)
        post_rate = max(0.0, (self._work() - p.work0) / dt)
        floor = p.pre_rate * (1.0 - self.policy.revert_tol)
        self._pending = None
        if p.pre_rate > 0 and post_rate < floor:
            p.knob.write(p.old)
            if self._grown and self._grown[-1] is p.knob:
                self._grown.pop()
            self._record(
                p.knob.name, p.new, p.old,
                f"never-worse: post-change {post_rate:.1f}/s < "
                f"{floor:.1f}/s ({(1 - self.policy.revert_tol):.2f} x "
                f"pre-change {p.pre_rate:.1f}/s)",
                {**sig, "post_rate": post_rate, "pre_rate": p.pre_rate},
                revert=True,
            )
            # A reverted knob starts a fresh cooldown: the system needs
            # a clean window before the next experiment.
            self._last_action_t = now
            return "revert"
        return None  # accepted: the change stands

    def _drift_replan(
        self, now: float, sig: Dict[str, float]
    ) -> Optional[str]:
        if (
            self._costs_probe is None
            or self.view is None
            or self._costs is None
        ):
            return None
        try:
            fresh = self._costs_probe()
        except (ShutdownRequested, KeyboardInterrupt):
            raise
        except Exception:  # noqa: BLE001 - a dead probe never kills the loop
            logger.exception("tune: cost probe raised; continuing")
            return None
        plan = replan_on_drift(
            self.view, self._costs, fresh, self.policy.drift_rel_tol
        )
        if plan is None:
            return None
        self.last_placement = plan
        self._costs = fresh
        self._last_action_t = now
        self.metrics.incr("tune.replans")
        self._record(
            "placement", None, list(plan.assignment),
            f"measured link costs drifted beyond "
            f"{self.policy.drift_rel_tol:.2f}",
            sig,
        )
        return "replan"

    def _hysteresis(
        self, now: float, sig: Dict[str, float], rate: float
    ) -> Optional[str]:
        pol = self.policy
        stall = float(sig.get("stall_fraction", 0.0))
        p99 = float(sig.get("window_latency_p99", 0.0) or 0.0)
        demand = stall >= pol.up_stall_fraction or (
            pol.up_latency_p99_s > 0 and p99 >= pol.up_latency_p99_s
        )
        idle = stall <= pol.down_stall_fraction and not demand
        if demand:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
        elif idle:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
        else:  # the dead band: hold state, run no timers (no flapping)
            self._above_since = None
            self._below_since = None
        if now - self._last_action_t < pol.cooldown_s:
            return None
        if (
            self._above_since is not None
            and now - self._above_since >= pol.sustain_s
        ):
            return self._grow(now, sig, rate)
        if (
            self._below_since is not None
            and now - self._below_since >= pol.sustain_s
        ):
            return self._shrink(now, sig, rate)
        return None

    def _open_pending(
        self, knob: TunableKnob, old: Any, new: Any, now: float,
        rate: float,
    ) -> None:
        self._pending = _PendingChange(
            knob=knob, old=old, new=new, opened_t=now,
            work0=self._work(), pre_rate=rate or self._last_rate,
        )
        self._last_action_t = now
        self._above_since = None
        self._below_since = None

    def _grow(
        self, now: float, sig: Dict[str, float], rate: float
    ) -> Optional[str]:
        """Double the first depth knob with headroom (priority order)."""
        for knob in self.knobs:
            old = knob.read()
            if not isinstance(old, (int, float)) or isinstance(old, bool):
                continue
            new = knob.clamp(type(old)(old * 2))
            if new == old:
                continue  # at its ceiling; try the next knob
            knob.write(new)
            if knob not in self._grown:
                self._grown.append(knob)
            self._open_pending(knob, old, new, now, rate)
            self._record(
                knob.name, old, new,
                f"sustained stall {sig.get('stall_fraction', 0.0):.3f} "
                f">= {self.policy.up_stall_fraction:.3f} for "
                f"{self.policy.sustain_s:.1f}s",
                sig,
            )
            return "grow"
        return None  # every knob at its ceiling: demand without supply

    def _shrink(
        self, now: float, sig: Dict[str, float], rate: float
    ) -> Optional[str]:
        """Step the most recently grown knob back toward baseline."""
        while self._grown:
            knob = self._grown[-1]
            old = knob.read()
            base = self._baseline.get(knob.name, old)
            if not isinstance(old, (int, float)) or old <= base:
                self._grown.pop()
                continue
            halved = old // 2 if isinstance(old, int) else old / 2
            new = knob.clamp(type(old)(max(base, halved)))
            if new == old:
                self._grown.pop()
                continue
            knob.write(new)
            if new <= base:
                self._grown.pop()
            self._open_pending(knob, old, new, now, rate)
            self._record(
                knob.name, old, new,
                f"sustained idle {sig.get('stall_fraction', 0.0):.3f} "
                f"<= {self.policy.down_stall_fraction:.3f} for "
                f"{self.policy.sustain_s:.1f}s: reclaiming headroom",
                sig,
            )
            return "shrink"
        return None  # nothing above baseline: idleness costs nothing

    def retune(self, policy: ControllerPolicy) -> None:
        """Swap the policy live (the Autoscaler.retune contract: sustain
        timers reset, the cooldown clock is kept)."""
        self.policy = policy
        self._above_since = None
        self._below_since = None

    def report(self) -> dict:
        """The bench/artifact block body (calibration's shape)."""
        return {
            "decisions": [d.as_dict() for d in self.decisions],
            "reverts": int(self.metrics.counter("tune.reverts")),
            "replans": int(self.metrics.counter("tune.replans")),
            "wire_flipped": self._wire_flipped,
        }

    # -- the background loop (DDL018: timed stop-event wait) ---------------

    def start(self) -> "KnobController":
        self._thread = threading.Thread(
            target=self._run, name="ddl-tune", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.poll_interval_s * 2 + 1)

    def __enter__(self) -> "KnobController":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    def _run(self) -> None:
        # DDL018/DDL019: bounded by the stop event's timed wait; step()
        # does bounded work (one signal read, at most one knob write).
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.step()
            except (ShutdownRequested, KeyboardInterrupt):
                return  # teardown reached the policy loop: stop cleanly
            except Exception:
                # A crashing step must never silently disable tuning
                # (the Autoscaler._run contract).
                logger.exception("tune: controller step raised; continuing")
                continue
