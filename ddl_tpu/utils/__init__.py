"""Cross-cutting utilities: callback dispatch and call tracing.

Parity: reference ``ddl/utils.py`` — ``execute_callbacks`` (:9),
``with_logging`` (:25), ``for_all_methods`` (:45).  The dispatcher here fixes
SURVEY Q1: the reference returned from inside the loop after the *first*
callback (its default-lambda fallback always matched), so registered
callbacks beyond index 0 — including the global shuffler — never ran
(reference ``ddl/utils.py:11-22``).  This implementation runs every callback
that actually implements the hook and returns the last non-None result.
"""

from __future__ import annotations

import functools
import logging
import os
import time
from typing import Any, Callable, Iterable, Optional, Sequence

from ddl_tpu.protocols import CALLBACK_POSITIONS

logger = logging.getLogger("ddl_tpu")


def env_flag(name: str, override: Optional[bool] = None) -> bool:
    """The repo's one boolean env-gate parser (``DDL_TPU_INTEGRITY``,
    ``DDL_TPU_STAGED``, ``DDL_TPU_TFRECORD_CRC``, ...): an explicit
    ``override`` wins; otherwise the variable is truthy unless set to
    ``0``/``off``/``false`` (case-insensitive).  Delegates to the
    :mod:`ddl_tpu.envspec` registry, which owns the default — an
    unregistered name raises ``UnknownKnobError`` (the VP003 contract,
    enforced at runtime too)."""
    # Lazy: utils is imported everywhere, envspec pulls in config.
    from ddl_tpu import envspec

    return envspec.flag(name, override)


def execute_callbacks(
    callbacks: Sequence[Any], position: str, **kwargs: Any
) -> Any:
    """Dispatch hook ``position`` on every callback that implements it.

    Unlike the reference (``ddl/utils.py:9-22``), this iterates ALL
    callbacks: a hook is invoked only when the callback defines it (no
    silent default swallowing the chain), and the last non-None return wins
    (hooks that produce a value, like ``on_init``, are conventionally
    implemented by exactly one callback).
    """
    if position not in CALLBACK_POSITIONS:
        raise ValueError(
            f"unknown callback position {position!r}; valid: {CALLBACK_POSITIONS}"
        )
    result: Any = None
    for callback in callbacks:
        fn = getattr(callback, position, None)
        if fn is None or not callable(fn):
            continue
        ret = fn(**kwargs)
        if ret is not None:
            result = ret
    return result


def with_logging(
    fn: Callable[..., Any] | None = None, *, tag: str = ""
) -> Callable[..., Any]:
    """Debug-trace a callable: rank/worker-tagged entry/exit + duration.

    Parity: reference ``ddl/utils.py:25-42`` logged entry/exit with args at
    DEBUG.  Here the line also carries a monotonic duration so the traces
    double as a poor-man's profiler; at non-DEBUG levels the wrapper is a
    near-zero-cost passthrough.
    """

    def deco(f: Callable[..., Any]) -> Callable[..., Any]:
        qual = f"{tag}{f.__qualname__}"

        @functools.wraps(f)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            if not logger.isEnabledFor(logging.DEBUG):
                return f(*args, **kwargs)
            t0 = time.perf_counter()
            logger.debug("-> %s args=%r kwargs=%r", qual, args[1:], kwargs)
            try:
                ret = f(*args, **kwargs)
            except BaseException as e:
                logger.debug(
                    "!! %s raised %r after %.3fms",
                    qual, e, (time.perf_counter() - t0) * 1e3,
                )
                raise
            logger.debug(
                "<- %s = %r (%.3fms)", qual, ret, (time.perf_counter() - t0) * 1e3
            )
            return ret

        return wrapper

    if fn is not None:
        return deco(fn)
    return deco


def for_all_methods(
    decorator: Callable[..., Any], exclude: Iterable[str] = ()
) -> Callable[[type], type]:
    """Class decorator applying ``decorator`` to every public method.

    Parity: reference ``ddl/utils.py:45-57``.  Dunders are always skipped —
    which keeps ``__getitem__`` (the consumer hot path) quiet, as the
    reference did explicitly (``ddl/mpi_dataloader.py:104-106``).
    """
    exclude = set(exclude)

    def deco(cls: type) -> type:
        for name, attr in list(vars(cls).items()):
            if name in exclude or name.startswith("__"):
                continue
            if callable(attr):
                setattr(cls, name, decorator(attr))
        return cls

    return deco


def value_ready(value: Any, default: bool) -> bool:
    """Non-blocking completion probe on a device value (a jax array or
    pytree of them) — the ONE implementation behind the loader's
    transfer-gated release sweep and the fused step's overlap /
    slots-in-flight accounting, which must observe progress without
    ever waiting for it.

    ``default`` is the answer for leaves without ``is_ready`` (older
    jax, or duck-typed futures missing the probe), and the polarity is
    the caller's SAFETY direction: the release sweep passes ``False``
    (report not-ready — the forced blocking flush still frees the slot
    correctly, the fast path just never triggers), while the
    observability probes pass ``True`` (gauges degrade to zero rather
    than the probe becoming a sync).
    """
    try:
        import jax

        return all(
            bool(leaf.is_ready()) for leaf in jax.tree.leaves(value)
        )
    except AttributeError:
        return default
