"""Producer-function library: ready-made readers for common data layouts.

The reference shipped only the abstract skeleton — every user wrote their
own producer (reference ``ddl/datasetwrapper.py``).  These cover the
driver's scale-out configs (BASELINE.json): in-memory arrays (the
``TensorDataset`` analog, configs[0]), sharded files on disk
(ImageNet/WebDataset-style shard-per-producer, configs[1-2]), and token
streams for LLM pretraining (C4/Llama feed, configs[3-4]).  All shard
deterministically by ``(instance_idx, producer_idx)`` the way the
reference example sliced per instance (reference ``tests/run_ddl.py:84-87``).

The file-based readers fetch shard bytes through the pluggable storage
backends in :mod:`ddl_tpu.cache` and keep decoded shards in the
multi-tier shard cache when it is enabled (``DDL_TPU_CACHE=1`` or an
explicit ``cache=`` store) — epoch ≥ 2 then skips fetch *and* decode,
and a background warmer prefetches upcoming shards in epoch order.  See
:class:`_ShardCacheMixin` and docs/CACHING.md.
"""

from __future__ import annotations

import glob as glob_mod
from typing import Any, BinaryIO, Callable, Optional, Sequence

import numpy as np

from ddl_tpu.datasetwrapper import DataProducerOnInitReturn, ProducerFunctionSkeleton
from ddl_tpu.exceptions import IntegrityError


def _my_shard(n_items: int, producer_idx: int, n_producers: int,
              instance_idx: int, n_instances: int) -> np.ndarray:
    """Deterministic strided shard of [0, n_items) for this worker."""
    worker = instance_idx * n_producers + (producer_idx - 1)
    total = n_instances * n_producers
    return np.arange(worker % total, n_items, total)


def _glob_my_shards(pattern: str, producer_idx: int, n_producers: int,
                    instance_idx: int, n_instances: int) -> list:
    """Glob + strided per-worker shard assignment (shared by every
    file-shard producer), validating at least one shard per worker."""
    paths = sorted(glob_mod.glob(pattern))
    if not paths:
        raise FileNotFoundError(f"no shards match {pattern!r}")
    mine = _my_shard(len(paths), producer_idx, n_producers,
                     instance_idx, n_instances)
    if len(mine) == 0:
        raise ValueError(
            f"{len(paths)} shard(s) matching {pattern!r} is fewer than "
            f"one per worker ({n_instances * n_producers} workers)"
        )
    return [paths[i] for i in mine]


class _ShardCacheMixin:
    """Cache/backend plumbing shared by the shard-file producers.

    Every shard byte these producers touch goes through a pluggable
    :class:`~ddl_tpu.cache.StorageBackend` (``backend=`` constructor
    kwarg; default the local filesystem) with bounded retry/backoff, and
    — when the cache is enabled — decoded shards are kept in a
    :class:`~ddl_tpu.cache.CacheStore` keyed by content-addressed
    ``(source fingerprint, shard, reader class + params, transform
    version)`` keys, so epoch ≥ 2 skips both the fetch and the decode.
    A background :class:`~ddl_tpu.cache.CacheWarmer` prefetches this
    worker's shard list in epoch order; the ``on_push_end`` hook (run in
    ``DataPusher.push_data``'s ``finally``) closes it with a bounded
    join, so no run leaks a warmer thread.

    Cache resolution (worker-side, in ``on_init``): an explicit
    ``cache=`` store wins (THREAD mode / tests — a ``CacheStore`` does
    not pickle across the PROCESS spawn boundary); otherwise the
    ``DDL_TPU_CACHE`` gate selects the process-default store built from
    the environment, which PROCESS workers inherit.

    Subclasses set ``transform_version`` (bump when decode output
    changes) and override ``_reader_params`` with every constructor
    parameter that changes decoded bytes.
    """

    #: Decode-logic version tag: part of the cache key, so bumping it
    #: orphans (never aliases) entries decoded by older logic.
    transform_version = 1

    def _cache_init(self) -> None:
        """Resolve backend/store/retry policy (call early in ``on_init``).

        ``cache`` semantics: a store instance uses exactly that store;
        ``None`` defers to the ``DDL_TPU_CACHE`` env gate; ``False``
        forces the cache OFF regardless of the environment (the bench's
        uncached control arm and A/B baselines need a value that cannot
        be flipped by an exported gate).
        """
        from ddl_tpu import cache as cache_mod

        self._backend = getattr(self, "backend", None) or cache_mod.LocalBackend()
        explicit = getattr(self, "cache", None)
        if explicit is False:
            self._cache = None
        elif explicit is not None:
            self._cache = explicit
        elif cache_mod.cache_enabled():
            self._cache = cache_mod.default_store()
        else:
            self._cache = None
        self._retry = cache_mod.retry_settings_from_env()
        if not hasattr(self, "_warmer"):
            self._warmer = None

    def _reader_params(self) -> str:
        """Constructor params that change decoded bytes (key material)."""
        return ""

    def _shard_key(self, path: str):
        from ddl_tpu import cache as cache_mod

        return cache_mod.CacheKey(
            source=self._backend.fingerprint(path),
            shard=path,
            reader=f"{type(self).__qualname__}({self._reader_params()})",
            transform=str(self.transform_version),
        )

    def _open_shard(self, path: str, should_abort=None) -> BinaryIO:
        """Backend open with the one bounded retry/backoff policy."""
        from ddl_tpu import cache as cache_mod

        m = self._cache.metrics if self._cache is not None else None
        return cache_mod.open_with_retry(
            self._backend, path, metrics=m, should_abort=should_abort,
            **self._retry,
        )

    def _cached_shard(
        self, path: str, decode: Callable[[str, BinaryIO], np.ndarray]
    ) -> np.ndarray:
        """Whole-shard get-or-decode (``decode(path, open_file)``).

        On a miss — including a corrupt disk entry the store just
        quarantined — the shard is refetched from source and
        re-inserted, so corruption degrades to one extra fetch, never to
        wrong data.  The returned array is read-only when it came from
        the cache: treat it as shared.
        """
        if self._cache is None:
            with self._open_shard(path) as f:
                return decode(path, f)
        key = self._shard_key(path)
        arr = self._cache.get(key)
        if arr is None:
            with self._open_shard(path) as f:
                arr = self._cache.put(key, decode(path, f))
        return arr

    def _start_warmer(
        self,
        paths: Sequence[str],
        decode: Callable[[str, BinaryIO], np.ndarray],
    ) -> None:
        """Kick off epoch-order prefetch of ``paths`` (idempotent; no-op
        without a cache or with warming disabled)."""
        from ddl_tpu import cache as cache_mod

        if (
            self._cache is None
            or self._warmer is not None
            or not cache_mod.warm_enabled(getattr(self, "warm", None))
        ):
            return

        def job(path):
            def load(should_abort):
                with self._open_shard(path, should_abort=should_abort) as f:
                    return decode(path, f)

            # Key as a thunk: fingerprinting is a per-shard backend
            # round trip, paid on the WARMER thread, not serially here
            # on the producer's init path.
            return (lambda p=path: self._shard_key(p), load)

        self._warmer = cache_mod.CacheWarmer(
            self._cache,
            [job(p) for p in paths],
            name=f"ddl-cache-warmer-{type(self).__name__}",
        )

    def on_push_end(self, **kw: Any) -> None:
        """Producer teardown hook: stop the warmer (bounded join)."""
        w = getattr(self, "_warmer", None)
        if w is not None:
            w.close()
            self._warmer = None


class ArrayProducer(ProducerFunctionSkeleton):
    """Serve a host-resident (N, F) array — the ``TensorDataset`` analog.

    Each worker owns a strided shard; every window is a fresh sample of
    ``window_size`` rows from the shard (with reshuffle per refill).
    """

    def __init__(self, data: np.ndarray, window_size: int,
                 splits: Optional[Sequence[int]] = None, seed: int = 0):
        self.data = np.ascontiguousarray(data)
        self.window_size = window_size
        self.splits = tuple(splits) if splits else (data.shape[1],)
        self.seed = seed

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        idx = _my_shard(len(self.data), producer_idx, n_producers,
                        instance_idx, n_instances)
        self._shard = self.data[idx]
        if len(self._shard) < self.window_size:
            reps = -(-self.window_size // max(len(self._shard), 1))
            self._shard = np.tile(self._shard, (reps, 1))
        self._rng = np.random.default_rng(
            [self.seed, instance_idx, producer_idx]
        )
        return DataProducerOnInitReturn(
            nData=self.window_size,
            nValues=self.data.shape[1],
            shape=(self.window_size, self.data.shape[1]),
            splits=self.splits,
            dtype=self.data.dtype,
        )

    #: Every fill fully rewrites the window — safe to hand a live ring
    #: slot (write-once producer discipline; see DataPusher).
    supports_inplace_fill = True

    def _fill(self, my_ary: np.ndarray) -> None:
        pick = self._rng.choice(len(self._shard), self.window_size,
                                replace=False)
        # Gather straight into the (possibly ring-slot) window: one host
        # write instead of materialize-then-copy.  mode="clip" (indices
        # are in-range by construction) because mode="raise" forces
        # numpy to buffer the output — re-adding the very copy pass the
        # out= gather exists to delete.
        self._shard.take(pick, axis=0, out=my_ary, mode="clip")

    def post_init(self, my_ary, **kw):
        self._fill(my_ary)

    def execute_function(self, my_ary, **kw):
        self._fill(my_ary)


class FileShardProducer(_ShardCacheMixin, ProducerFunctionSkeleton):
    """Stream ``.npy`` shard files matching a glob, shard-per-worker.

    The layout of WebDataset/ImageNet-style shard collections: many
    same-shaped record files; each worker round-robins its own subset,
    loading one shard per window refill (IO overlaps training via the
    ring's double buffering).  Shard reads go through the storage
    backend + shard cache (:class:`_ShardCacheMixin`): with
    ``DDL_TPU_CACHE=1`` (or an explicit ``cache=`` store), every epoch
    after the first serves decoded shards from the warm tier.  The
    per-refill reshuffle draws a permutation from the worker's seeded
    RNG, so the served stream is identical whether a shard came from
    source or from cache.
    """

    #: Each refill is one full permutation-gather into the window, so
    #: PROCESS-mode pushers may hand this reader a live shm-slot view
    #: (write-once: the commit memcpy disappears).
    supports_inplace_fill = True

    def __init__(self, pattern: str, splits: Optional[Sequence[int]] = None,
                 seed: int = 0, backend: Any = None, cache: Any = None,
                 warm: Optional[bool] = None):
        self.pattern = pattern
        self.splits = tuple(splits) if splits else None
        self.seed = seed
        self.backend = backend
        self.cache = cache
        self.warm = warm

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        self._paths = _glob_my_shards(
            self.pattern, producer_idx, n_producers, instance_idx,
            n_instances,
        )
        self._cursor = 0
        self._rng = np.random.default_rng([self.seed, producer_idx])
        self._cache_init()
        first = self._cached_shard(self._paths[0], self._decode)
        self._shape = first.shape
        self._dtype = first.dtype
        self._start_warmer(self._paths, self._decode)
        return DataProducerOnInitReturn(
            nData=first.shape[0],
            nValues=int(np.prod(first.shape[1:])),
            shape=(first.shape[0], int(np.prod(first.shape[1:]))),
            splits=self.splits or (int(np.prod(first.shape[1:])),),
            dtype=first.dtype,
        )

    @staticmethod
    def _decode(path: str, f: BinaryIO) -> np.ndarray:
        return np.load(f)

    def _load_next(self, my_ary: np.ndarray) -> None:
        path = self._paths[self._cursor % len(self._paths)]
        self._cursor += 1
        # Cached arrays are shared and read-only, so the reshuffle is a
        # permutation GATHER into the window, never an in-place shuffle
        # of the source (which would corrupt every later epoch's hit) —
        # and it gathers STRAIGHT into the window view (``out=``): the
        # warm path then writes decoded bytes exactly once, into the shm
        # slot itself on the inplace-fill path.
        arr = self._cached_shard(path, self._decode).reshape(my_ary.shape)
        perm = self._rng.permutation(len(arr))
        # mode="clip": a permutation is in-range by construction, and
        # mode="raise" would buffer the output (an extra copy pass).
        arr.take(perm, axis=0, out=my_ary, mode="clip")

    def post_init(self, my_ary, **kw):
        self._load_next(my_ary)

    def execute_function(self, my_ary, **kw):
        self._load_next(my_ary)


class TokenStreamProducer(ProducerFunctionSkeleton):
    """Serve fixed-length token sequences from a flat token array on disk.

    The C4/pretrain feed shape (BASELINE configs[3-4]): a memory-mapped
    1-D token file; each window is ``windows_rows`` sequences of
    ``seq_len`` tokens drawn from this worker's strided region.  Output
    splits are ``(seq_len,)`` — the consumer reshapes into (B, T) int
    batches for the LM loss.
    """

    #: Row-wise full rewrite per refill (and PackedTokenProducer's
    #: segment pass reads only what the same call wrote) — live-slot safe.
    supports_inplace_fill = True

    def __init__(self, token_file: str, seq_len: int, window_rows: int,
                 dtype: Any = np.int32, seed: int = 0):
        self.token_file = token_file
        self.seq_len = seq_len
        self.window_rows = window_rows
        self.dtype = np.dtype(dtype)
        self.seed = seed

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        self._tokens = np.memmap(self.token_file, dtype=self.dtype, mode="r")
        n_seqs = len(self._tokens) // self.seq_len
        mine = _my_shard(n_seqs, producer_idx, n_producers,
                         instance_idx, n_instances)
        if len(mine) == 0:
            raise ValueError("token file smaller than one sequence per worker")
        self._mine = mine
        self._rng = np.random.default_rng([self.seed, instance_idx, producer_idx])
        return DataProducerOnInitReturn(
            nData=self.window_rows,
            nValues=self.seq_len,
            shape=(self.window_rows, self.seq_len),
            splits=(self.seq_len,),
            dtype=self.dtype,
        )

    def _fill(self, my_ary: np.ndarray) -> None:
        pick = self._rng.choice(
            self._mine, self.window_rows, replace=len(self._mine) < self.window_rows
        )
        for row, seq_idx in enumerate(pick):
            start = int(seq_idx) * self.seq_len
            my_ary[row] = self._tokens[start : start + self.seq_len]

    def post_init(self, my_ary, **kw):
        self._fill(my_ary)

    def execute_function(self, my_ary, **kw):
        self._fill(my_ary)


class PackedTokenProducer(TokenStreamProducer):
    """Token stream with PACKED-DOCUMENT segment ids.

    Streaming packing, the standard LM-pretraining layout: each row is
    ``seq_len`` consecutive tokens spanning document boundaries, and a
    second column block carries row-local segment ids that increment
    after every ``delimiter`` token (EOS).  Feed the columns to a
    segment-aware loss so attention resets at document boundaries:

        loss = lambda p, b: llama.next_token_loss(
            p, b[0], cfg, segment_ids=b[1])

    Window layout: (window_rows, 2*seq_len), splits (seq_len, seq_len) —
    column 0 tokens, column 1 segment ids.
    """

    def __init__(self, token_file: str, seq_len: int, window_rows: int,
                 delimiter: int = 0, dtype: Any = np.int32, seed: int = 0):
        super().__init__(token_file, seq_len, window_rows, dtype, seed)
        self.delimiter = int(delimiter)

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        base = super().on_init(
            producer_idx=producer_idx, n_producers=n_producers,
            instance_idx=instance_idx, n_instances=n_instances, **kw,
        )
        return DataProducerOnInitReturn(
            nData=base.nData,
            nValues=2 * self.seq_len,
            shape=(self.window_rows, 2 * self.seq_len),
            splits=(self.seq_len, self.seq_len),
            dtype=self.dtype,
        )

    def _fill(self, my_ary: np.ndarray) -> None:
        tokens = my_ary[:, : self.seq_len]
        super()._fill(tokens)
        # Row-local segment ids: a token belongs to the document OPENED
        # by the most recent delimiter strictly before it (the delimiter
        # itself closes its document).
        ends = tokens == self.delimiter
        seg = np.zeros_like(tokens)
        seg[:, 1:] = np.cumsum(ends[:, :-1], axis=1)
        my_ary[:, self.seq_len :] = seg


class WebDatasetProducer(_ShardCacheMixin, ProducerFunctionSkeleton):
    """WebDataset-style tar-shard image reader (BASELINE configs[1-2]).

    Each shard is a ``.tar`` whose members pair by basename, the standard
    WebDataset/ImageNet layout: ``<key>.jpg`` / ``.jpeg`` / ``.png`` (the
    image) and ``<key>.cls`` (ascii integer label).  Images decode via
    PIL, resize to ``(image_size, image_size)`` RGB, scale to [0, 1]
    float32, and flatten; each window row is ``[pixels..., label]``
    (splits ``(H*W*3, 1)``).  Shards are assigned to workers by the usual
    strided rule and read as tar *streams*, sample by sample (only the
    current sample's files are in memory — a multi-hundred-MB ImageNet
    shard is never materialised whole), cycling shards forever.

    With the shard cache enabled the DECODED rows of each shard land in
    the warm tier as one ``(n_samples, H*W*3+1)`` float32 array — image
    decode is this reader's dominant cost, so epoch ≥ 2 skips the tar
    read *and* every PIL decode.  The cold path still streams (rows are
    served as they decode; the shard array is only assembled for the
    cache insert), and serves byte-identical rows either way.
    """

    _IMG_EXT = (".jpg", ".jpeg", ".png")

    #: Rows are written once each, covering the whole window every fill
    #: — decode lands in the shm slot itself on the inplace-fill path.
    supports_inplace_fill = True

    def __init__(self, pattern: str, image_size: int = 32,
                 window_rows: int = 64, backend: Any = None,
                 cache: Any = None, warm: Optional[bool] = None):
        self.pattern = pattern
        self.image_size = image_size
        self.window_rows = window_rows
        self.backend = backend
        self.cache = cache
        self.warm = warm

    def _reader_params(self) -> str:
        return f"image_size={self.image_size}"

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        try:
            from PIL import Image  # noqa: F401
        except ImportError as e:  # pragma: no cover - PIL ships in image
            raise RuntimeError(
                "WebDatasetProducer needs Pillow for image decoding"
            ) from e
        self._shards = _glob_my_shards(
            self.pattern, producer_idx, n_producers, instance_idx,
            n_instances,
        )
        self._cache_init()
        self._n_px = self.image_size * self.image_size * 3
        self._iter = self._stream_rows()
        self._start_warmer(self._shards, self._decode_shard)
        return DataProducerOnInitReturn(
            nData=self.window_rows,
            nValues=self._n_px + 1,
            shape=(self.window_rows, self._n_px + 1),
            splits=(self._n_px, 1),
        )

    # -- tar streaming -----------------------------------------------------

    def _stream_pairs(self, f):
        """Yield (image_bytes, label) from ONE open tar stream.

        WebDataset convention keeps a sample's files adjacent, but pairing
        is done by key so ordering within a key doesn't matter; ``pending``
        holds only keys whose pair is incomplete.
        """
        import tarfile

        with tarfile.open(fileobj=f, mode="r|*") as tf:  # streaming read
            pending: dict = {}
            done: set = set()  # keys already yielded this shard
            for m in tf:
                if not m.isfile():
                    continue
                stem, dot, ext = m.name.rpartition(".")
                ext = dot + ext.lower()
                # Only the pairing members buffer; .json/.txt/...
                # sidecars would otherwise leak (and once a key has
                # yielded, trailing members for it are dropped too).
                if ext not in self._IMG_EXT and ext != ".cls":
                    continue
                if stem in done:
                    continue
                d = pending.setdefault(stem, {})
                d[ext] = tf.extractfile(m).read()
                img = next(
                    (d[e] for e in self._IMG_EXT if e in d), None
                )
                if img is not None and ".cls" in d:
                    del pending[stem]
                    done.add(stem)
                    yield img, int(d[".cls"].decode().strip())

    def _row(self, img_bytes: bytes, label: int) -> np.ndarray:
        row = np.empty(self._n_px + 1, np.float32)
        row[:-1] = self._decode(img_bytes)
        row[-1] = float(label)
        return row

    def _decode_shard(self, path: str, f) -> np.ndarray:
        """Whole-shard decode → (n_samples, n_px+1) rows (warmer path)."""
        rows = [self._row(img, lab) for img, lab in self._stream_pairs(f)]
        if not rows:
            raise ValueError(f"shard {path} holds no (image, .cls) pairs")
        return np.stack(rows)

    def _stream_rows(self):
        """Yield decoded window rows, cycling shards forever.

        Warm shards come straight out of the cache (no tar open, no PIL
        decode); cold shards stream row-by-row and are inserted whole at
        shard end — an abandoned mid-shard stream caches nothing rather
        than something partial.
        """
        shard_i = 0
        while True:
            path = self._shards[shard_i % len(self._shards)]
            shard_i += 1
            cached = (
                self._cache.get(self._shard_key(path))
                if self._cache is not None else None
            )
            if cached is not None:
                if len(cached) == 0:
                    raise ValueError(
                        f"shard {path} holds no (image, .cls) pairs"
                    )
                yield from cached
                continue
            collect = [] if self._cache is not None else None
            collect_bytes = 0
            n = 0
            with self._open_shard(path) as f:
                for img, label in self._stream_pairs(f):
                    row = self._row(img, label)
                    n += 1
                    if collect is not None:
                        collect.append(row)
                        collect_bytes += row.nbytes
                        if collect_bytes > self._cache.ram_budget_bytes:
                            # Decoded shard exceeds what either tier
                            # would keep: stop buffering and preserve
                            # this reader's never-materialise-the-shard
                            # memory bound — the stream itself goes on.
                            collect = None
                    yield row
            if n == 0:
                raise ValueError(
                    f"shard {path} holds no (image, .cls) pairs"
                )
            if collect is not None:
                self._cache.put(self._shard_key(path), np.stack(collect))

    def _decode(self, img_bytes: bytes) -> np.ndarray:
        import io

        from PIL import Image

        im = Image.open(io.BytesIO(img_bytes)).convert("RGB")
        if im.size != (self.image_size, self.image_size):
            im = im.resize((self.image_size, self.image_size))
        return np.asarray(im, np.float32).reshape(-1) / 255.0

    def _fill(self, my_ary: np.ndarray) -> None:
        for row in range(self.window_rows):
            my_ary[row] = next(self._iter)

    def post_init(self, my_ary, **kw):
        self._fill(my_ary)

    def execute_function(self, my_ary, **kw):
        self._fill(my_ary)


# -- TFRecord / tf.Example (stdlib-only micro parsers) ------------------------

# CRC32C (Castagnoli) — the TFRecord framing checksum — implemented with
# numpy lookup tables, no tensorflow/crc32c dependency.  Verified against
# the spec's check vector (crc32c(b"123456789") == 0xE3069283,
# tests/test_faults.py).  Structure: slicing-by-K generalised to a WIDE
# stripe (K = 2048) so each Python-loop step checksums a whole stripe
# with one vectorised table gather + XOR reduction — a narrow
# slicing-by-8 loop costs ~1 MiB/s in numpy scalar indexing, which would
# throttle the producer fill path the moment validation defaults on.

_CRC32C_POLY = 0x82F63B78  # reversed Castagnoli polynomial
_CRC32C_STRIPE = 2048  # bytes per vectorised step (table: K*256*4 = 2 MiB)
_crc32c_byte_table: Optional[np.ndarray] = None
_crc32c_stripe_table: Optional[np.ndarray] = None


def _make_crc32c_tables() -> tuple:
    t0 = np.empty(256, np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (_CRC32C_POLY if c & 1 else 0)
        t0[i] = c
    # chain[m][b]: CRC contribution of byte b followed by m zero bytes;
    # stripe[j] is the table for position j within a K-byte stripe
    # (byte j is followed by K-1-j bytes).
    stripe = np.empty((_CRC32C_STRIPE, 256), np.uint32)
    prev = t0
    stripe[_CRC32C_STRIPE - 1] = t0
    for m in range(1, _CRC32C_STRIPE):
        prev = t0[prev & 0xFF] ^ (prev >> np.uint32(8))
        stripe[_CRC32C_STRIPE - 1 - m] = prev
    return t0, stripe


def _crc32c_update_bytes(crc: int, buf: np.ndarray, t0: np.ndarray) -> int:
    """Per-byte tail update (buf shorter than one stripe)."""
    for b in buf:
        crc = int(t0[(crc ^ int(b)) & 0xFF]) ^ (crc >> 8)
    return crc


def crc32c(data) -> int:
    """CRC32C of a bytes-like / uint8 array.

    Whole stripes of ``_CRC32C_STRIPE`` bytes are folded with one numpy
    gather + ``bitwise_xor.reduce`` each (the running CRC is XORed into
    the stripe's first 4 bytes, per slicing-by-N); the sub-stripe tail
    falls back to the per-byte table loop.  Measured ~2 orders of
    magnitude over a scalar-indexing loop — validation at ingest cadence
    without a native dependency.
    """
    global _crc32c_byte_table, _crc32c_stripe_table
    if _crc32c_byte_table is None:
        _crc32c_byte_table, _crc32c_stripe_table = _make_crc32c_tables()
    t0, stripe = _crc32c_byte_table, _crc32c_stripe_table
    buf = np.frombuffer(memoryview(data), np.uint8)
    crc = 0xFFFFFFFF
    K = _CRC32C_STRIPE
    nstripes = len(buf) // K
    if nstripes:
        # Flattened-table gather (one int add + 1-D take) measures 2x
        # the 2-D fancy index.
        flat = stripe.ravel()
        offs = np.arange(K, dtype=np.int64) * 256
        for s in range(nstripes):
            block = buf[s * K : (s + 1) * K]
            # Fold the running CRC into the stripe's first 4 bytes
            # (little-endian), per slicing-by-N.
            head = block[:4] ^ np.frombuffer(
                crc.to_bytes(4, "little"), np.uint8
            )
            crc = int(
                np.bitwise_xor.reduce(flat[offs[:4] + head])
                ^ np.bitwise_xor.reduce(flat[offs[4:] + block[4:]])
            )
    crc = _crc32c_update_bytes(crc, buf[nstripes * K :], t0)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data) -> int:
    """The TFRecord 'masked' CRC: rotate right 15 and add a constant —
    guards against CRCs of CRCs looking valid (TFRecord spec)."""
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def tfrecord_crc_enabled(override: Optional[bool] = None) -> bool:
    """The ``DDL_TPU_TFRECORD_CRC`` gate (default ON).  The opt-out is
    for trusted local data where the decode throughput matters more than
    detecting at-rest corruption."""
    from ddl_tpu.utils import env_flag

    return env_flag("DDL_TPU_TFRECORD_CRC", override)


def iter_tfrecords(
    path: str,
    verify_crc: Optional[bool] = None,
    fileobj: Optional[BinaryIO] = None,
):
    """Yield raw record payloads from a TFRecord file.

    Framing (TFRecord spec): u64le length, u32 masked length-crc,
    payload, u32 masked payload-crc.  Both CRCs are validated (pure
    numpy CRC32C — no tensorflow dependency) and a mismatch raises
    :class:`~ddl_tpu.exceptions.IntegrityError` with file/offset
    context; ``verify_crc=False`` (or ``DDL_TPU_TFRECORD_CRC=0``) skips
    validation for trusted local data.  A TRUNCATED final record
    (anywhere short of its full ``length + trailer`` framing) is treated
    as end-of-stream in BOTH modes — the validation knob must never
    change which records a file serves, only whether they are checked.

    ``fileobj`` reads an already-open stream instead of opening ``path``
    (the storage-backend seam: producers pass a backend-opened handle;
    ``path`` then only labels error messages).  The caller owns and
    closes a passed ``fileobj``.
    """
    if fileobj is not None:
        yield from _iter_tfrecord_stream(path, fileobj, verify_crc)
        return
    with open(path, "rb") as f:
        yield from _iter_tfrecord_stream(path, f, verify_crc)


def _iter_tfrecord_stream(
    path: str, f: BinaryIO, verify_crc: Optional[bool]
):
    import struct

    verify = tfrecord_crc_enabled(verify_crc)
    offset = 0
    while True:
        head = f.read(12)
        if len(head) < 12:
            return
        (length,) = struct.unpack("<Q", head[:8])
        if verify:
            (got_len_crc,) = struct.unpack("<I", head[8:12])
            want_len_crc = masked_crc32c(head[:8])
            if got_len_crc != want_len_crc:
                raise IntegrityError(
                    f"{path}: corrupt TFRecord length-crc at offset "
                    f"{offset} (0x{got_len_crc:08x} != "
                    f"0x{want_len_crc:08x})"
                )
        payload = f.read(length)
        if len(payload) < length:
            return
        tail = f.read(4)
        if len(tail) < 4:
            return  # truncated trailer: end-of-stream (both modes)
        if verify:
            (got_crc,) = struct.unpack("<I", tail)
            want_crc = masked_crc32c(payload)
            if got_crc != want_crc:
                raise IntegrityError(
                    f"{path}: corrupt TFRecord payload at offset "
                    f"{offset} ({length} bytes; crc 0x{got_crc:08x} "
                    f"!= 0x{want_crc:08x})"
                )
        offset += 12 + length + 4
        yield payload


def _read_varint(buf: bytes, pos: int):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def example_int64_feature(payload: bytes, key: str) -> Optional[np.ndarray]:
    """Extract an int64-list feature from a serialized tf.Example.

    A micro-decoder for the three nested messages actually involved
    (Example.features → Features.feature map → Feature.int64_list),
    stdlib-only — the C4 feed (BASELINE configs[3]) parses without a
    tensorflow import.  Returns None when ``key`` is absent.
    """

    def fields(buf):
        pos = 0
        while pos < len(buf):
            tag, pos = _read_varint(buf, pos)
            field, wire = tag >> 3, tag & 7
            if wire == 2:  # length-delimited
                n, pos = _read_varint(buf, pos)
                yield field, buf[pos : pos + n]
                pos += n
            elif wire == 0:  # varint
                v, pos = _read_varint(buf, pos)
                yield field, v
            elif wire == 5:  # 32-bit
                pos += 4
            elif wire == 1:  # 64-bit
                pos += 8
            else:  # pragma: no cover - malformed input
                raise ValueError(f"unsupported wire type {wire}")

    for f_ex, features in fields(payload):
        if f_ex != 1:  # Example.features
            continue
        for f_map, entry in fields(features):
            if f_map != 1:  # Features.feature (map entry)
                continue
            k = v = None
            for f_e, val in fields(entry):
                if f_e == 1:
                    k = val.decode()
                elif f_e == 2:
                    v = val
            if k != key or v is None:
                continue
            for f_feat, lst in fields(v):
                if f_feat != 3:  # Feature.int64_list
                    continue
                values = []
                for f_l, packed in fields(lst):
                    if f_l != 1:
                        continue
                    if isinstance(packed, int):  # unpacked varint
                        values.append(packed)
                    else:  # packed repeated varints
                        pos = 0
                        while pos < len(packed):
                            v_, pos = _read_varint(packed, pos)
                            values.append(v_)
                return np.array(values, np.int64)
    return None


class TFRecordTokenProducer(_ShardCacheMixin, ProducerFunctionSkeleton):
    """C4-style tokenized TFRecord stream (BASELINE configs[3]).

    Shard files matching ``pattern`` are assigned per worker; records
    parse with the stdlib-only framing/Example readers above.  With
    ``feature_key`` set (default ``"input_ids"``) each record is a
    tf.Example whose int64-list feature supplies tokens; with
    ``feature_key=None`` record payloads are raw little-endian int32
    tokens.  Token streams concatenate and cut into ``seq_len`` rows.

    With the shard cache enabled, a shard's parsed tokens land in the
    warm tier as ONE concatenated int32 array — epoch ≥ 2 skips the
    framing walk, both CRC passes, and the protobuf micro-decode.  The
    stream is byte-identical either way (the consumer concatenates
    chunks regardless of their cut points).
    """

    #: ``_fill`` streams token chunks straight into the flat window view
    #: (no concatenate temp), fully rewriting it — live-slot safe.
    supports_inplace_fill = True

    def __init__(self, pattern: str, seq_len: int, window_rows: int,
                 feature_key: Optional[str] = "input_ids",
                 verify_crc: Optional[bool] = None, backend: Any = None,
                 cache: Any = None, warm: Optional[bool] = None):
        self.pattern = pattern
        self.seq_len = seq_len
        self.window_rows = window_rows
        self.feature_key = feature_key
        #: None defers to the ``DDL_TPU_TFRECORD_CRC`` gate (default on);
        #: False is the trusted-local-data opt-out.
        self.verify_crc = verify_crc
        self.backend = backend
        self.cache = cache
        self.warm = warm

    def _reader_params(self) -> str:
        return f"feature_key={self.feature_key}"

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        self._shards = _glob_my_shards(
            self.pattern, producer_idx, n_producers, instance_idx,
            n_instances,
        )
        self._cache_init()
        self._records = self._stream_records()
        self._buf = np.zeros((0,), np.int32)
        self._start_warmer(self._shards, self._decode_shard)
        return DataProducerOnInitReturn(
            nData=self.window_rows,
            nValues=self.seq_len,
            shape=(self.window_rows, self.seq_len),
            splits=(self.seq_len,),
            dtype=np.int32,
        )

    def _decode_shard(self, path: str, f) -> np.ndarray:
        """Whole-shard parse → one concatenated token array (warmer path).

        An all-empty shard caches as a zero-length array: warm epochs
        then skip it without refetching, and the dry-shard accounting in
        ``_stream_records`` still sees it contribute no tokens.
        """
        chunks = [
            self._tokens_from(p)
            for p in iter_tfrecords(
                path, verify_crc=self.verify_crc, fileobj=f
            )
        ]
        chunks = [c for c in chunks if len(c)]
        return np.concatenate(chunks) if chunks else np.zeros(0, np.int32)

    def _stream_records(self):
        """Yield token chunks record-by-record, cycling shards forever —
        memory stays bounded by one record, not one shard (cold path;
        the cache insert assembles the shard's tokens once at shard
        end), and the first batch is served as soon as enough records
        have parsed.  Warm shards yield their whole token array as one
        chunk — same concatenated stream, zero parse work."""
        shard_i = 0
        while True:
            path = self._shards[shard_i % len(self._shards)]
            shard_i += 1
            grew = False
            cached = (
                self._cache.get(self._shard_key(path))
                if self._cache is not None else None
            )
            if cached is not None:
                if len(cached):
                    grew = True
                    yield cached
            else:
                collect = [] if self._cache is not None else None
                collect_bytes = 0
                with self._open_shard(path) as f:
                    for payload in iter_tfrecords(
                        path, verify_crc=self.verify_crc, fileobj=f
                    ):
                        toks = self._tokens_from(payload)
                        if len(toks):
                            grew = True
                            if collect is not None:
                                collect.append(toks)
                                collect_bytes += toks.nbytes
                                if (
                                    collect_bytes
                                    > self._cache.ram_budget_bytes
                                ):
                                    # Shard too big for either tier:
                                    # keep streaming record-bounded,
                                    # don't buffer the uncacheable.
                                    collect = None
                            yield toks
                if collect is not None:
                    self._cache.put(
                        self._shard_key(path),
                        np.concatenate(collect)
                        if collect else np.zeros(0, np.int32),
                    )
            if not grew:
                # Track consecutive dry shards (records with zero tokens
                # or none at all) so an all-empty shard set raises instead
                # of cycling forever.
                self._dry_shards = getattr(self, "_dry_shards", 0) + 1
                if self._dry_shards >= len(self._shards):
                    raise ValueError(
                        f"no tokens in any of {len(self._shards)} TFRecord "
                        f"shard(s) (last: {path})"
                    )
            else:
                self._dry_shards = 0

    def _tokens_from(self, payload: bytes) -> np.ndarray:
        if self.feature_key is None:
            return np.frombuffer(payload, "<i4").astype(np.int32)
        toks = example_int64_feature(payload, self.feature_key)
        if toks is None:
            raise ValueError(
                f"record lacks int64 feature {self.feature_key!r}"
            )
        return toks.astype(np.int32)

    def _fill(self, my_ary: np.ndarray) -> None:
        # Write-once: token chunks land straight in the flat window view
        # (a ring-slot view on the inplace path) as they arrive — the
        # old concatenate-then-copy built a whole-window temp per fill.
        # Chunk order and cut points are unchanged, so the served stream
        # is byte-identical to the copying implementation.
        flat = my_ary.reshape(-1)
        need = flat.size
        take = min(len(self._buf), need)
        if take:
            flat[:take] = self._buf[:take]
        rest = self._buf[take:]
        pos = take
        while pos < need:
            toks = next(self._records)
            take = min(len(toks), need - pos)
            flat[pos : pos + take] = toks[:take]
            rest = toks[take:]
            pos += take
        self._buf = rest

    def post_init(self, my_ary, **kw):
        self._fill(my_ary)

    def execute_function(self, my_ary, **kw):
        self._fill(my_ary)
