"""Producer-function library: ready-made readers for common data layouts.

The reference shipped only the abstract skeleton — every user wrote their
own producer (reference ``ddl/datasetwrapper.py``).  These cover the
driver's scale-out configs (BASELINE.json): in-memory arrays (the
``TensorDataset`` analog, configs[0]), sharded files on disk
(ImageNet/WebDataset-style shard-per-producer, configs[1-2]), and token
streams for LLM pretraining (C4/Llama feed, configs[3-4]).  All shard
deterministically by ``(instance_idx, producer_idx)`` the way the
reference example sliced per instance (reference ``tests/run_ddl.py:84-87``).
"""

from __future__ import annotations

import glob as glob_mod
from typing import Any, Optional, Sequence

import numpy as np

from ddl_tpu.datasetwrapper import DataProducerOnInitReturn, ProducerFunctionSkeleton


def _my_shard(n_items: int, producer_idx: int, n_producers: int,
              instance_idx: int, n_instances: int) -> np.ndarray:
    """Deterministic strided shard of [0, n_items) for this worker."""
    worker = instance_idx * n_producers + (producer_idx - 1)
    total = n_instances * n_producers
    return np.arange(worker % total, n_items, total)


class ArrayProducer(ProducerFunctionSkeleton):
    """Serve a host-resident (N, F) array — the ``TensorDataset`` analog.

    Each worker owns a strided shard; every window is a fresh sample of
    ``window_size`` rows from the shard (with reshuffle per refill).
    """

    def __init__(self, data: np.ndarray, window_size: int,
                 splits: Optional[Sequence[int]] = None, seed: int = 0):
        self.data = np.ascontiguousarray(data)
        self.window_size = window_size
        self.splits = tuple(splits) if splits else (data.shape[1],)
        self.seed = seed

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        idx = _my_shard(len(self.data), producer_idx, n_producers,
                        instance_idx, n_instances)
        self._shard = self.data[idx]
        if len(self._shard) < self.window_size:
            reps = -(-self.window_size // max(len(self._shard), 1))
            self._shard = np.tile(self._shard, (reps, 1))
        self._rng = np.random.default_rng(
            [self.seed, instance_idx, producer_idx]
        )
        return DataProducerOnInitReturn(
            nData=self.window_size,
            nValues=self.data.shape[1],
            shape=(self.window_size, self.data.shape[1]),
            splits=self.splits,
            dtype=self.data.dtype,
        )

    def _fill(self, my_ary: np.ndarray) -> None:
        pick = self._rng.choice(len(self._shard), self.window_size,
                                replace=False)
        np.copyto(my_ary, self._shard[pick])

    def post_init(self, my_ary, **kw):
        self._fill(my_ary)

    def execute_function(self, my_ary, **kw):
        self._fill(my_ary)


class FileShardProducer(ProducerFunctionSkeleton):
    """Stream ``.npy`` shard files matching a glob, shard-per-worker.

    The layout of WebDataset/ImageNet-style shard collections: many
    same-shaped record files; each worker round-robins its own subset,
    loading one shard per window refill (IO overlaps training via the
    ring's double buffering).
    """

    def __init__(self, pattern: str, splits: Optional[Sequence[int]] = None,
                 seed: int = 0):
        self.pattern = pattern
        self.splits = tuple(splits) if splits else None
        self.seed = seed

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        paths = sorted(glob_mod.glob(self.pattern))
        if not paths:
            raise FileNotFoundError(f"no shards match {self.pattern!r}")
        mine = _my_shard(len(paths), producer_idx, n_producers,
                         instance_idx, n_instances)
        if len(mine) == 0:
            raise ValueError(
                f"{len(paths)} shards < {n_instances * n_producers} workers"
            )
        self._paths = [paths[i] for i in mine]
        self._cursor = 0
        self._rng = np.random.default_rng([self.seed, producer_idx])
        first = np.load(self._paths[0])
        self._shape = first.shape
        self._dtype = first.dtype
        return DataProducerOnInitReturn(
            nData=first.shape[0],
            nValues=int(np.prod(first.shape[1:])),
            shape=(first.shape[0], int(np.prod(first.shape[1:]))),
            splits=self.splits or (int(np.prod(first.shape[1:])),),
            dtype=first.dtype,
        )

    def _load_next(self, my_ary: np.ndarray) -> None:
        path = self._paths[self._cursor % len(self._paths)]
        self._cursor += 1
        arr = np.load(path).reshape(my_ary.shape)
        self._rng.shuffle(arr)
        np.copyto(my_ary, arr)

    def post_init(self, my_ary, **kw):
        self._load_next(my_ary)

    def execute_function(self, my_ary, **kw):
        self._load_next(my_ary)


class TokenStreamProducer(ProducerFunctionSkeleton):
    """Serve fixed-length token sequences from a flat token array on disk.

    The C4/pretrain feed shape (BASELINE configs[3-4]): a memory-mapped
    1-D token file; each window is ``windows_rows`` sequences of
    ``seq_len`` tokens drawn from this worker's strided region.  Output
    splits are ``(seq_len,)`` — the consumer reshapes into (B, T) int
    batches for the LM loss.
    """

    def __init__(self, token_file: str, seq_len: int, window_rows: int,
                 dtype: Any = np.int32, seed: int = 0):
        self.token_file = token_file
        self.seq_len = seq_len
        self.window_rows = window_rows
        self.dtype = np.dtype(dtype)
        self.seed = seed

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        self._tokens = np.memmap(self.token_file, dtype=self.dtype, mode="r")
        n_seqs = len(self._tokens) // self.seq_len
        mine = _my_shard(n_seqs, producer_idx, n_producers,
                         instance_idx, n_instances)
        if len(mine) == 0:
            raise ValueError("token file smaller than one sequence per worker")
        self._mine = mine
        self._rng = np.random.default_rng([self.seed, instance_idx, producer_idx])
        return DataProducerOnInitReturn(
            nData=self.window_rows,
            nValues=self.seq_len,
            shape=(self.window_rows, self.seq_len),
            splits=(self.seq_len,),
            dtype=self.dtype,
        )

    def _fill(self, my_ary: np.ndarray) -> None:
        pick = self._rng.choice(
            self._mine, self.window_rows, replace=len(self._mine) < self.window_rows
        )
        for row, seq_idx in enumerate(pick):
            start = int(seq_idx) * self.seq_len
            my_ary[row] = self._tokens[start : start + self.seq_len]

    def post_init(self, my_ary, **kw):
        self._fill(my_ary)

    def execute_function(self, my_ary, **kw):
        self._fill(my_ary)
