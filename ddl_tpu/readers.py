"""Producer-function library: ready-made readers for common data layouts.

The reference shipped only the abstract skeleton — every user wrote their
own producer (reference ``ddl/datasetwrapper.py``).  These cover the
driver's scale-out configs (BASELINE.json): in-memory arrays (the
``TensorDataset`` analog, configs[0]), sharded files on disk
(ImageNet/WebDataset-style shard-per-producer, configs[1-2]), and token
streams for LLM pretraining (C4/Llama feed, configs[3-4]).  All shard
deterministically by ``(instance_idx, producer_idx)`` the way the
reference example sliced per instance (reference ``tests/run_ddl.py:84-87``).
"""

from __future__ import annotations

import glob as glob_mod
from typing import Any, Optional, Sequence

import numpy as np

from ddl_tpu.datasetwrapper import DataProducerOnInitReturn, ProducerFunctionSkeleton
from ddl_tpu.exceptions import IntegrityError


def _my_shard(n_items: int, producer_idx: int, n_producers: int,
              instance_idx: int, n_instances: int) -> np.ndarray:
    """Deterministic strided shard of [0, n_items) for this worker."""
    worker = instance_idx * n_producers + (producer_idx - 1)
    total = n_instances * n_producers
    return np.arange(worker % total, n_items, total)


def _glob_my_shards(pattern: str, producer_idx: int, n_producers: int,
                    instance_idx: int, n_instances: int) -> list:
    """Glob + strided per-worker shard assignment (shared by every
    file-shard producer), validating at least one shard per worker."""
    paths = sorted(glob_mod.glob(pattern))
    if not paths:
        raise FileNotFoundError(f"no shards match {pattern!r}")
    mine = _my_shard(len(paths), producer_idx, n_producers,
                     instance_idx, n_instances)
    if len(mine) == 0:
        raise ValueError(
            f"{len(paths)} shard(s) matching {pattern!r} is fewer than "
            f"one per worker ({n_instances * n_producers} workers)"
        )
    return [paths[i] for i in mine]


class ArrayProducer(ProducerFunctionSkeleton):
    """Serve a host-resident (N, F) array — the ``TensorDataset`` analog.

    Each worker owns a strided shard; every window is a fresh sample of
    ``window_size`` rows from the shard (with reshuffle per refill).
    """

    def __init__(self, data: np.ndarray, window_size: int,
                 splits: Optional[Sequence[int]] = None, seed: int = 0):
        self.data = np.ascontiguousarray(data)
        self.window_size = window_size
        self.splits = tuple(splits) if splits else (data.shape[1],)
        self.seed = seed

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        idx = _my_shard(len(self.data), producer_idx, n_producers,
                        instance_idx, n_instances)
        self._shard = self.data[idx]
        if len(self._shard) < self.window_size:
            reps = -(-self.window_size // max(len(self._shard), 1))
            self._shard = np.tile(self._shard, (reps, 1))
        self._rng = np.random.default_rng(
            [self.seed, instance_idx, producer_idx]
        )
        return DataProducerOnInitReturn(
            nData=self.window_size,
            nValues=self.data.shape[1],
            shape=(self.window_size, self.data.shape[1]),
            splits=self.splits,
            dtype=self.data.dtype,
        )

    def _fill(self, my_ary: np.ndarray) -> None:
        pick = self._rng.choice(len(self._shard), self.window_size,
                                replace=False)
        np.copyto(my_ary, self._shard[pick])

    def post_init(self, my_ary, **kw):
        self._fill(my_ary)

    def execute_function(self, my_ary, **kw):
        self._fill(my_ary)


class FileShardProducer(ProducerFunctionSkeleton):
    """Stream ``.npy`` shard files matching a glob, shard-per-worker.

    The layout of WebDataset/ImageNet-style shard collections: many
    same-shaped record files; each worker round-robins its own subset,
    loading one shard per window refill (IO overlaps training via the
    ring's double buffering).
    """

    def __init__(self, pattern: str, splits: Optional[Sequence[int]] = None,
                 seed: int = 0):
        self.pattern = pattern
        self.splits = tuple(splits) if splits else None
        self.seed = seed

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        self._paths = _glob_my_shards(
            self.pattern, producer_idx, n_producers, instance_idx,
            n_instances,
        )
        self._cursor = 0
        self._rng = np.random.default_rng([self.seed, producer_idx])
        first = np.load(self._paths[0])
        self._shape = first.shape
        self._dtype = first.dtype
        return DataProducerOnInitReturn(
            nData=first.shape[0],
            nValues=int(np.prod(first.shape[1:])),
            shape=(first.shape[0], int(np.prod(first.shape[1:]))),
            splits=self.splits or (int(np.prod(first.shape[1:])),),
            dtype=first.dtype,
        )

    def _load_next(self, my_ary: np.ndarray) -> None:
        path = self._paths[self._cursor % len(self._paths)]
        self._cursor += 1
        arr = np.load(path).reshape(my_ary.shape)
        self._rng.shuffle(arr)
        np.copyto(my_ary, arr)

    def post_init(self, my_ary, **kw):
        self._load_next(my_ary)

    def execute_function(self, my_ary, **kw):
        self._load_next(my_ary)


class TokenStreamProducer(ProducerFunctionSkeleton):
    """Serve fixed-length token sequences from a flat token array on disk.

    The C4/pretrain feed shape (BASELINE configs[3-4]): a memory-mapped
    1-D token file; each window is ``windows_rows`` sequences of
    ``seq_len`` tokens drawn from this worker's strided region.  Output
    splits are ``(seq_len,)`` — the consumer reshapes into (B, T) int
    batches for the LM loss.
    """

    def __init__(self, token_file: str, seq_len: int, window_rows: int,
                 dtype: Any = np.int32, seed: int = 0):
        self.token_file = token_file
        self.seq_len = seq_len
        self.window_rows = window_rows
        self.dtype = np.dtype(dtype)
        self.seed = seed

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        self._tokens = np.memmap(self.token_file, dtype=self.dtype, mode="r")
        n_seqs = len(self._tokens) // self.seq_len
        mine = _my_shard(n_seqs, producer_idx, n_producers,
                         instance_idx, n_instances)
        if len(mine) == 0:
            raise ValueError("token file smaller than one sequence per worker")
        self._mine = mine
        self._rng = np.random.default_rng([self.seed, instance_idx, producer_idx])
        return DataProducerOnInitReturn(
            nData=self.window_rows,
            nValues=self.seq_len,
            shape=(self.window_rows, self.seq_len),
            splits=(self.seq_len,),
            dtype=self.dtype,
        )

    def _fill(self, my_ary: np.ndarray) -> None:
        pick = self._rng.choice(
            self._mine, self.window_rows, replace=len(self._mine) < self.window_rows
        )
        for row, seq_idx in enumerate(pick):
            start = int(seq_idx) * self.seq_len
            my_ary[row] = self._tokens[start : start + self.seq_len]

    def post_init(self, my_ary, **kw):
        self._fill(my_ary)

    def execute_function(self, my_ary, **kw):
        self._fill(my_ary)


class PackedTokenProducer(TokenStreamProducer):
    """Token stream with PACKED-DOCUMENT segment ids.

    Streaming packing, the standard LM-pretraining layout: each row is
    ``seq_len`` consecutive tokens spanning document boundaries, and a
    second column block carries row-local segment ids that increment
    after every ``delimiter`` token (EOS).  Feed the columns to a
    segment-aware loss so attention resets at document boundaries:

        loss = lambda p, b: llama.next_token_loss(
            p, b[0], cfg, segment_ids=b[1])

    Window layout: (window_rows, 2*seq_len), splits (seq_len, seq_len) —
    column 0 tokens, column 1 segment ids.
    """

    def __init__(self, token_file: str, seq_len: int, window_rows: int,
                 delimiter: int = 0, dtype: Any = np.int32, seed: int = 0):
        super().__init__(token_file, seq_len, window_rows, dtype, seed)
        self.delimiter = int(delimiter)

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        base = super().on_init(
            producer_idx=producer_idx, n_producers=n_producers,
            instance_idx=instance_idx, n_instances=n_instances, **kw,
        )
        return DataProducerOnInitReturn(
            nData=base.nData,
            nValues=2 * self.seq_len,
            shape=(self.window_rows, 2 * self.seq_len),
            splits=(self.seq_len, self.seq_len),
            dtype=self.dtype,
        )

    def _fill(self, my_ary: np.ndarray) -> None:
        tokens = my_ary[:, : self.seq_len]
        super()._fill(tokens)
        # Row-local segment ids: a token belongs to the document OPENED
        # by the most recent delimiter strictly before it (the delimiter
        # itself closes its document).
        ends = tokens == self.delimiter
        seg = np.zeros_like(tokens)
        seg[:, 1:] = np.cumsum(ends[:, :-1], axis=1)
        my_ary[:, self.seq_len :] = seg


class WebDatasetProducer(ProducerFunctionSkeleton):
    """WebDataset-style tar-shard image reader (BASELINE configs[1-2]).

    Each shard is a ``.tar`` whose members pair by basename, the standard
    WebDataset/ImageNet layout: ``<key>.jpg`` / ``.jpeg`` / ``.png`` (the
    image) and ``<key>.cls`` (ascii integer label).  Images decode via
    PIL, resize to ``(image_size, image_size)`` RGB, scale to [0, 1]
    float32, and flatten; each window row is ``[pixels..., label]``
    (splits ``(H*W*3, 1)``).  Shards are assigned to workers by the usual
    strided rule and read as tar *streams*, sample by sample (only the
    current sample's files are in memory — a multi-hundred-MB ImageNet
    shard is never materialised whole), cycling shards forever.
    """

    _IMG_EXT = (".jpg", ".jpeg", ".png")

    def __init__(self, pattern: str, image_size: int = 32,
                 window_rows: int = 64):
        self.pattern = pattern
        self.image_size = image_size
        self.window_rows = window_rows

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        try:
            from PIL import Image  # noqa: F401
        except ImportError as e:  # pragma: no cover - PIL ships in image
            raise RuntimeError(
                "WebDatasetProducer needs Pillow for image decoding"
            ) from e
        self._shards = _glob_my_shards(
            self.pattern, producer_idx, n_producers, instance_idx,
            n_instances,
        )
        self._iter = self._stream_samples()
        n_px = self.image_size * self.image_size * 3
        return DataProducerOnInitReturn(
            nData=self.window_rows,
            nValues=n_px + 1,
            shape=(self.window_rows, n_px + 1),
            splits=(n_px, 1),
        )

    # -- tar streaming -----------------------------------------------------

    def _stream_samples(self):
        """Yield (image_bytes, label), streaming tars and cycling forever.

        WebDataset convention keeps a sample's files adjacent, but pairing
        is done by key so ordering within a key doesn't matter; ``pending``
        holds only keys whose pair is incomplete.
        """
        import tarfile

        shard_i = 0
        while True:
            path = self._shards[shard_i % len(self._shards)]
            shard_i += 1
            yielded = 0
            with tarfile.open(path, mode="r|*") as tf:  # streaming read
                pending: dict = {}
                done: set = set()  # keys already yielded this shard
                for m in tf:
                    if not m.isfile():
                        continue
                    stem, dot, ext = m.name.rpartition(".")
                    ext = dot + ext.lower()
                    # Only the pairing members buffer; .json/.txt/...
                    # sidecars would otherwise leak (and once a key has
                    # yielded, trailing members for it are dropped too).
                    if ext not in self._IMG_EXT and ext != ".cls":
                        continue
                    if stem in done:
                        continue
                    d = pending.setdefault(stem, {})
                    d[ext] = tf.extractfile(m).read()
                    img = next(
                        (d[e] for e in self._IMG_EXT if e in d), None
                    )
                    if img is not None and ".cls" in d:
                        del pending[stem]
                        done.add(stem)
                        yielded += 1
                        yield img, int(d[".cls"].decode().strip())
            if yielded == 0:
                raise ValueError(
                    f"shard {path} holds no (image, .cls) pairs"
                )

    def _decode(self, img_bytes: bytes) -> np.ndarray:
        import io

        from PIL import Image

        im = Image.open(io.BytesIO(img_bytes)).convert("RGB")
        if im.size != (self.image_size, self.image_size):
            im = im.resize((self.image_size, self.image_size))
        return np.asarray(im, np.float32).reshape(-1) / 255.0

    def _fill(self, my_ary: np.ndarray) -> None:
        for row in range(self.window_rows):
            img, label = next(self._iter)
            my_ary[row, :-1] = self._decode(img)
            my_ary[row, -1] = float(label)

    def post_init(self, my_ary, **kw):
        self._fill(my_ary)

    def execute_function(self, my_ary, **kw):
        self._fill(my_ary)


# -- TFRecord / tf.Example (stdlib-only micro parsers) ------------------------

# CRC32C (Castagnoli) — the TFRecord framing checksum — implemented with
# numpy lookup tables, no tensorflow/crc32c dependency.  Verified against
# the spec's check vector (crc32c(b"123456789") == 0xE3069283,
# tests/test_faults.py).  Structure: slicing-by-K generalised to a WIDE
# stripe (K = 2048) so each Python-loop step checksums a whole stripe
# with one vectorised table gather + XOR reduction — a narrow
# slicing-by-8 loop costs ~1 MiB/s in numpy scalar indexing, which would
# throttle the producer fill path the moment validation defaults on.

_CRC32C_POLY = 0x82F63B78  # reversed Castagnoli polynomial
_CRC32C_STRIPE = 2048  # bytes per vectorised step (table: K*256*4 = 2 MiB)
_crc32c_byte_table: Optional[np.ndarray] = None
_crc32c_stripe_table: Optional[np.ndarray] = None


def _make_crc32c_tables() -> tuple:
    t0 = np.empty(256, np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (_CRC32C_POLY if c & 1 else 0)
        t0[i] = c
    # chain[m][b]: CRC contribution of byte b followed by m zero bytes;
    # stripe[j] is the table for position j within a K-byte stripe
    # (byte j is followed by K-1-j bytes).
    stripe = np.empty((_CRC32C_STRIPE, 256), np.uint32)
    prev = t0
    stripe[_CRC32C_STRIPE - 1] = t0
    for m in range(1, _CRC32C_STRIPE):
        prev = t0[prev & 0xFF] ^ (prev >> np.uint32(8))
        stripe[_CRC32C_STRIPE - 1 - m] = prev
    return t0, stripe


def _crc32c_update_bytes(crc: int, buf: np.ndarray, t0: np.ndarray) -> int:
    """Per-byte tail update (buf shorter than one stripe)."""
    for b in buf:
        crc = int(t0[(crc ^ int(b)) & 0xFF]) ^ (crc >> 8)
    return crc


def crc32c(data) -> int:
    """CRC32C of a bytes-like / uint8 array.

    Whole stripes of ``_CRC32C_STRIPE`` bytes are folded with one numpy
    gather + ``bitwise_xor.reduce`` each (the running CRC is XORed into
    the stripe's first 4 bytes, per slicing-by-N); the sub-stripe tail
    falls back to the per-byte table loop.  Measured ~2 orders of
    magnitude over a scalar-indexing loop — validation at ingest cadence
    without a native dependency.
    """
    global _crc32c_byte_table, _crc32c_stripe_table
    if _crc32c_byte_table is None:
        _crc32c_byte_table, _crc32c_stripe_table = _make_crc32c_tables()
    t0, stripe = _crc32c_byte_table, _crc32c_stripe_table
    buf = np.frombuffer(memoryview(data), np.uint8)
    crc = 0xFFFFFFFF
    K = _CRC32C_STRIPE
    nstripes = len(buf) // K
    if nstripes:
        # Flattened-table gather (one int add + 1-D take) measures 2x
        # the 2-D fancy index.
        flat = stripe.ravel()
        offs = np.arange(K, dtype=np.int64) * 256
        for s in range(nstripes):
            block = buf[s * K : (s + 1) * K]
            # Fold the running CRC into the stripe's first 4 bytes
            # (little-endian), per slicing-by-N.
            head = block[:4] ^ np.frombuffer(
                crc.to_bytes(4, "little"), np.uint8
            )
            crc = int(
                np.bitwise_xor.reduce(flat[offs[:4] + head])
                ^ np.bitwise_xor.reduce(flat[offs[4:] + block[4:]])
            )
    crc = _crc32c_update_bytes(crc, buf[nstripes * K :], t0)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data) -> int:
    """The TFRecord 'masked' CRC: rotate right 15 and add a constant —
    guards against CRCs of CRCs looking valid (TFRecord spec)."""
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def tfrecord_crc_enabled(override: Optional[bool] = None) -> bool:
    """The ``DDL_TPU_TFRECORD_CRC`` gate (default ON).  The opt-out is
    for trusted local data where the decode throughput matters more than
    detecting at-rest corruption."""
    from ddl_tpu.utils import env_flag

    return env_flag("DDL_TPU_TFRECORD_CRC", override)


def iter_tfrecords(path: str, verify_crc: Optional[bool] = None):
    """Yield raw record payloads from a TFRecord file.

    Framing (TFRecord spec): u64le length, u32 masked length-crc,
    payload, u32 masked payload-crc.  Both CRCs are validated (pure
    numpy CRC32C — no tensorflow dependency) and a mismatch raises
    :class:`~ddl_tpu.exceptions.IntegrityError` with file/offset
    context; ``verify_crc=False`` (or ``DDL_TPU_TFRECORD_CRC=0``) skips
    validation for trusted local data.  A TRUNCATED final record
    (anywhere short of its full ``length + trailer`` framing) is treated
    as end-of-stream in BOTH modes — the validation knob must never
    change which records a file serves, only whether they are checked.
    """
    import struct

    verify = tfrecord_crc_enabled(verify_crc)
    with open(path, "rb") as f:
        offset = 0
        while True:
            head = f.read(12)
            if len(head) < 12:
                return
            (length,) = struct.unpack("<Q", head[:8])
            if verify:
                (got_len_crc,) = struct.unpack("<I", head[8:12])
                want_len_crc = masked_crc32c(head[:8])
                if got_len_crc != want_len_crc:
                    raise IntegrityError(
                        f"{path}: corrupt TFRecord length-crc at offset "
                        f"{offset} (0x{got_len_crc:08x} != "
                        f"0x{want_len_crc:08x})"
                    )
            payload = f.read(length)
            if len(payload) < length:
                return
            tail = f.read(4)
            if len(tail) < 4:
                return  # truncated trailer: end-of-stream (both modes)
            if verify:
                (got_crc,) = struct.unpack("<I", tail)
                want_crc = masked_crc32c(payload)
                if got_crc != want_crc:
                    raise IntegrityError(
                        f"{path}: corrupt TFRecord payload at offset "
                        f"{offset} ({length} bytes; crc 0x{got_crc:08x} "
                        f"!= 0x{want_crc:08x})"
                    )
            offset += 12 + length + 4
            yield payload


def _read_varint(buf: bytes, pos: int):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def example_int64_feature(payload: bytes, key: str) -> Optional[np.ndarray]:
    """Extract an int64-list feature from a serialized tf.Example.

    A micro-decoder for the three nested messages actually involved
    (Example.features → Features.feature map → Feature.int64_list),
    stdlib-only — the C4 feed (BASELINE configs[3]) parses without a
    tensorflow import.  Returns None when ``key`` is absent.
    """

    def fields(buf):
        pos = 0
        while pos < len(buf):
            tag, pos = _read_varint(buf, pos)
            field, wire = tag >> 3, tag & 7
            if wire == 2:  # length-delimited
                n, pos = _read_varint(buf, pos)
                yield field, buf[pos : pos + n]
                pos += n
            elif wire == 0:  # varint
                v, pos = _read_varint(buf, pos)
                yield field, v
            elif wire == 5:  # 32-bit
                pos += 4
            elif wire == 1:  # 64-bit
                pos += 8
            else:  # pragma: no cover - malformed input
                raise ValueError(f"unsupported wire type {wire}")

    for f_ex, features in fields(payload):
        if f_ex != 1:  # Example.features
            continue
        for f_map, entry in fields(features):
            if f_map != 1:  # Features.feature (map entry)
                continue
            k = v = None
            for f_e, val in fields(entry):
                if f_e == 1:
                    k = val.decode()
                elif f_e == 2:
                    v = val
            if k != key or v is None:
                continue
            for f_feat, lst in fields(v):
                if f_feat != 3:  # Feature.int64_list
                    continue
                values = []
                for f_l, packed in fields(lst):
                    if f_l != 1:
                        continue
                    if isinstance(packed, int):  # unpacked varint
                        values.append(packed)
                    else:  # packed repeated varints
                        pos = 0
                        while pos < len(packed):
                            v_, pos = _read_varint(packed, pos)
                            values.append(v_)
                return np.array(values, np.int64)
    return None


class TFRecordTokenProducer(ProducerFunctionSkeleton):
    """C4-style tokenized TFRecord stream (BASELINE configs[3]).

    Shard files matching ``pattern`` are assigned per worker; records
    parse with the stdlib-only framing/Example readers above.  With
    ``feature_key`` set (default ``"input_ids"``) each record is a
    tf.Example whose int64-list feature supplies tokens; with
    ``feature_key=None`` record payloads are raw little-endian int32
    tokens.  Token streams concatenate and cut into ``seq_len`` rows.
    """

    def __init__(self, pattern: str, seq_len: int, window_rows: int,
                 feature_key: Optional[str] = "input_ids",
                 verify_crc: Optional[bool] = None):
        self.pattern = pattern
        self.seq_len = seq_len
        self.window_rows = window_rows
        self.feature_key = feature_key
        #: None defers to the ``DDL_TPU_TFRECORD_CRC`` gate (default on);
        #: False is the trusted-local-data opt-out.
        self.verify_crc = verify_crc

    def on_init(self, producer_idx=0, n_producers=1, instance_idx=0,
                n_instances=1, **kw) -> DataProducerOnInitReturn:
        self._shards = _glob_my_shards(
            self.pattern, producer_idx, n_producers, instance_idx,
            n_instances,
        )
        self._records = self._stream_records()
        self._buf = np.zeros((0,), np.int32)
        return DataProducerOnInitReturn(
            nData=self.window_rows,
            nValues=self.seq_len,
            shape=(self.window_rows, self.seq_len),
            splits=(self.seq_len,),
            dtype=np.int32,
        )

    def _stream_records(self):
        """Yield token chunks record-by-record, cycling shards forever —
        memory stays bounded by one record, not one shard, and the first
        batch is served as soon as enough records have parsed."""
        shard_i = 0
        while True:
            path = self._shards[shard_i % len(self._shards)]
            shard_i += 1
            grew = False
            for payload in iter_tfrecords(path, verify_crc=self.verify_crc):
                toks = self._tokens_from(payload)
                if len(toks):
                    grew = True
                    yield toks
            if not grew:
                # Track consecutive dry shards (records with zero tokens
                # or none at all) so an all-empty shard set raises instead
                # of cycling forever.
                self._dry_shards = getattr(self, "_dry_shards", 0) + 1
                if self._dry_shards >= len(self._shards):
                    raise ValueError(
                        f"no tokens in any of {len(self._shards)} TFRecord "
                        f"shard(s) (last: {path})"
                    )
            else:
                self._dry_shards = 0

    def _tokens_from(self, payload: bytes) -> np.ndarray:
        if self.feature_key is None:
            return np.frombuffer(payload, "<i4").astype(np.int32)
        toks = example_int64_feature(payload, self.feature_key)
        if toks is None:
            raise ValueError(
                f"record lacks int64 feature {self.feature_key!r}"
            )
        return toks.astype(np.int32)

    def _fill(self, my_ary: np.ndarray) -> None:
        need = self.window_rows * self.seq_len
        chunks = [self._buf]
        have = len(self._buf)
        while have < need:
            toks = next(self._records)
            chunks.append(toks)
            have += len(toks)
        self._buf = np.concatenate(chunks) if len(chunks) > 1 else self._buf
        my_ary[:] = self._buf[:need].reshape(self.window_rows, self.seq_len)
        self._buf = self._buf[need:]

    def post_init(self, my_ary, **kw):
        self._fill(my_ary)

    def execute_function(self, my_ary, **kw):
        self._fill(my_ary)
