"""ddl_tpu.resilience — preemption-tolerant training (ISSUE 14).

Trainer-side fault tolerance closing the loop from preemption notice
to byte-identical resume:

- :class:`AsyncCheckpointer` — background-thread generation
  checkpoints (atomic temp+rename, integrity-trailer stamped,
  step-derived seq, keep-K retention, loader cursor fenced into the
  same blob) whose hot-path stall is the D2H snapshot alone.
- :class:`PreemptionGuard` — SIGTERM / ``DDL_TPU_PREEMPT_NOTICE`` /
  chaos-site notices turned into a deadline-bounded graceful drain
  (forced checkpoint → tenant-window revocation → graceful host drain
  → clean producer shutdown).
- The restore ladder — :func:`latest_verified_generation` /
  :func:`restore_latest`: unverifiable generations quarantined
  (``.quarantined``) and skipped, fallback to the previous verified
  generation, cold start (loud counter) at exhaustion.

docs/ROBUSTNESS.md has the failure model; docs/DEPLOY.md the
"surviving TPU preemption" recipe.
"""

from ddl_tpu.resilience.ckpt import (
    AsyncCheckpointer,
    RestoredRun,
    latest_verified_generation,
    list_generations,
    restore_latest,
    serialize_generation,
    verify_generation,
)
from ddl_tpu.resilience.guard import (
    DEADLINE_ENV,
    DEFAULT_DEADLINE_S,
    NOTICE_ENV,
    PreemptionGuard,
)

__all__ = [
    "AsyncCheckpointer",
    "DEADLINE_ENV",
    "DEFAULT_DEADLINE_S",
    "NOTICE_ENV",
    "PreemptionGuard",
    "RestoredRun",
    "latest_verified_generation",
    "list_generations",
    "restore_latest",
    "serialize_generation",
    "verify_generation",
]
