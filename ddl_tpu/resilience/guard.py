"""Preemption notice → deadline-bounded graceful drain (ISSUE 14).

TPU preemption is the dominant production failure mode on
spot/preemptible pods: the platform delivers a SIGTERM (or an agent
sets an env knob) and the job has a fixed grace budget — typically
30-60 s — before the hard kill.  :class:`PreemptionGuard` turns that
notice into an ordered drain the trainer runs at its next window
boundary:

1. **Forced final checkpoint** — the async checkpointer's
   :meth:`~ddl_tpu.resilience.ckpt.AsyncCheckpointer.checkpoint_now`
   (train state + fenced loader cursor, durably written), so the
   restarted job loses ZERO steps instead of up to one interval.
   Always attempted first: the checkpoint is the rung that bounds lost
   work; everything after it is cluster hygiene.
2. **In-flight tenant-window revocation** — ``revoke_inflight`` through
   the admission seam (:mod:`ddl_tpu.serve`): active tenants' granted
   and waiting window acquisitions on this host are revoked under a
   per-tenant SLO (size it from the p99 window latency the tenancy
   bench measures) instead of waiting for idleness — the ROADMAP 1(c)
   rung.  Revoked waiters raise the typed
   :class:`~ddl_tpu.exceptions.WindowsRevoked`.
3. **Graceful host drain** — ``ElasticCluster.drain_host`` for the
   departing host: the epoch-fenced view change re-partitions its
   shards onto survivors and parks its producers as warm standby.
4. **Clean producer shutdown** — the loader's shutdown, so rings close
   and the watchdog records zero failures (a drain is not a fault).

Every rung is bounded by the remaining grace budget; a rung whose turn
comes after the deadline is SKIPPED with a loud counter (the
checkpoint, first in line, is the one that practically never is).

Notice sources (any of): a SIGTERM handler (:meth:`install` — the
production path), the ``DDL_TPU_PREEMPT_NOTICE`` env knob (operator /
agent; optionally carrying the grace seconds as its value), a
programmatic :meth:`notify`, or the ``resilience.notice`` chaos site
(``PREEMPT_NOTICE`` raises the real
:class:`~ddl_tpu.exceptions.PreemptionNotice`, which :meth:`poll`
absorbs — deterministic preemption for the chaos matrix).
"""

from __future__ import annotations

import logging
import os
import signal
import threading

from ddl_tpu import envspec
from ddl_tpu.concurrency import named_rlock
import time
from typing import Any, Callable, Optional

from ddl_tpu.exceptions import (
    CheckpointError,
    DDLError,
    PreemptionNotice,
    ShutdownRequested,
)
from ddl_tpu.faults import fault_point
from ddl_tpu.observability import Metrics, metrics as default_metrics

logger = logging.getLogger("ddl_tpu")

#: Env knob: any truthy value is a standing preemption notice; a float
#: value overrides the grace budget (seconds).
NOTICE_ENV = "DDL_TPU_PREEMPT_NOTICE"
#: Env knob: default grace budget when the notice carries none.
DEADLINE_ENV = "DDL_TPU_PREEMPT_DEADLINE_S"
#: Fallback grace budget (the common TPU spot notice is 30 s).
DEFAULT_DEADLINE_S = 30.0


class PreemptionGuard:
    """One training run's preemption handler.

    Construct it with whatever drain rungs exist in the deployment —
    a bench that only wants checkpoint-on-SIGTERM attaches nothing;
    the full serving stack attaches the admission controller and the
    elastic cluster::

        guard = PreemptionGuard(cluster=elastic, host_id=my_host,
                                admission=controller)
        trainer = Trainer(..., preemption_guard=guard)
        with guard:                       # installs the SIGTERM handler
            res = trainer.fit(...)
        if res.preempted:
            ...                           # exit; restart resumes

    Thread-safe: the signal handler / a watcher thread may
    :meth:`notify` while the trainer polls at window boundaries.
    """

    def __init__(
        self,
        deadline_s: Optional[float] = None,
        cluster: Any = None,
        host_id: Optional[int] = None,
        admission: Any = None,
        revoke_slo_s: float = 1.0,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if deadline_s is None:
            deadline_s = envspec.get(DEADLINE_ENV)
        if deadline_s <= 0:
            raise DDLError(
                f"preemption deadline must be > 0, got {deadline_s}"
            )
        self.deadline_s = float(deadline_s)
        self.cluster = cluster
        self.host_id = host_id
        self.admission = admission
        self.revoke_slo_s = float(revoke_slo_s)
        self.metrics = metrics or default_metrics()
        self._clock = clock
        # REENTRANT: the SIGTERM handler runs on the MAIN thread between
        # bytecodes — with a plain Lock, a signal landing while that
        # same thread holds it (remaining() is called from every drain
        # rung) would deadlock notify() against its own frame.
        self._lock = named_rlock("resilience.guard")
        self._notice_t: Optional[float] = None
        self._reason = ""
        self._drained = False
        # Flight-record dump pending flag: the dump itself is DEFERRED
        # to poll()/drain() on the main thread (see _flight_dump_once).
        self._flight_dumped = False
        self._prev_handler: Any = None
        self._installed = False

    # -- notice sources ----------------------------------------------------

    def install(self) -> "PreemptionGuard":
        """Install the SIGTERM handler (main thread only — elsewhere
        the signal module refuses; the env/programmatic sources still
        work, logged)."""
        try:
            self._prev_handler = signal.signal(
                signal.SIGTERM, self._on_sigterm
            )
            self._installed = True
        except ValueError:
            logger.warning(
                "resilience: SIGTERM handler not installed (not the "
                "main thread) — env/programmatic notice still observed"
            )
        return self

    def uninstall(self) -> None:
        if self._installed:
            signal.signal(signal.SIGTERM, self._prev_handler)
            self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc: Any) -> None:
        self.uninstall()

    def _on_sigterm(self, signum: int, frame: Any) -> None:
        # Async-signal-safe enough: set the flag; the trainer drains at
        # its next window boundary.
        self.notify("SIGTERM")

    def notify(
        self, reason: str = "", deadline_s: Optional[float] = None
    ) -> None:
        """Record a preemption notice (first one wins; duplicates are
        absorbed).  ``deadline_s`` overrides the grace budget when the
        notice carries its own."""
        with self._lock:
            if self._notice_t is not None:
                return
            self._notice_t = self._clock()
            self._reason = reason or "notice"
            if deadline_s is not None and deadline_s > 0:
                self.deadline_s = float(deadline_s)
        self.metrics.incr("resilience.notices")
        logger.warning(
            "resilience: preemption notice (%s) — graceful drain within "
            "%.1fs at the next window boundary",
            self._reason, self.deadline_s,
        )

    def _flight_dump_once(self) -> None:
        """Post-mortem artifact (ddl_tpu.obs): capture the pipeline's
        state AT the notice, so a drain that later overruns its grace
        budget has a before picture (no-op when no recorder is armed).
        Deferred OUT of :meth:`notify` on purpose: notify runs inside
        the SIGTERM handler, and a dump there (metrics snapshot under
        the registry lock, recorder lock, file IO) could re-enter a
        lock the interrupted main thread already holds and deadlock
        the drain — the exact hazard class PR 14 fixed for the guard's
        own lock.  This runs on the main thread only (poll / drain, at
        window boundaries)."""
        if self._flight_dumped or self._notice_t is None:
            return
        self._flight_dumped = True
        from ddl_tpu.obs.recorder import flight_dump

        flight_dump(
            "resilience.preemption_notice",
            metrics=self.metrics,
            extra={"reason": self._reason, "grace_s": self.deadline_s},
        )

    def poll(self) -> bool:
        """The trainer's once-per-window-boundary check: True once a
        notice is pending (signal, env knob, chaos site, or a prior
        :meth:`notify`)."""
        if self._notice_t is not None:
            self._flight_dump_once()
            return True
        try:
            # Chaos site: PREEMPT_NOTICE raises the real type below.
            fault_point("resilience.notice")
        except PreemptionNotice as n:
            self.notify("injected", deadline_s=n.deadline_s or None)
            self._flight_dump_once()
            return True
        env = envspec.raw(NOTICE_ENV) or ""
        if env and env.lower() not in ("0", "off", "false"):
            try:
                deadline = float(env)
            except ValueError:
                deadline = None
            self.notify(f"{NOTICE_ENV}={env}", deadline_s=deadline)
            self._flight_dump_once()
            return True
        return False

    @property
    def pending(self) -> bool:
        return self._notice_t is not None

    @property
    def drained(self) -> bool:
        return self._drained

    def remaining(self) -> float:
        """Grace budget left (seconds); the full budget before notice."""
        with self._lock:
            if self._notice_t is None:
                return self.deadline_s
            return max(
                0.0, self.deadline_s - (self._clock() - self._notice_t)
            )

    # -- the drain ladder --------------------------------------------------

    def drain(
        self,
        final_checkpoint: Optional[Callable[[], None]] = None,
        shutdown: Optional[Callable[[], None]] = None,
    ) -> bool:
        """Run the drain ladder under the remaining grace budget.

        ``final_checkpoint`` (the trainer's forced-checkpoint thunk)
        runs FIRST and is the only rung attempted even at a blown
        deadline — it bounds lost work; the cluster rungs are hygiene a
        restart can survive skipping.  Returns True when every
        applicable rung completed inside the budget.
        """
        t0 = self._clock()
        # Catch-all for drains entered without a poll (programmatic
        # notify + direct drain): still safe — drain runs on the main
        # thread, never in the signal handler.
        self._flight_dump_once()
        self.metrics.incr("resilience.drains")
        within = True
        if final_checkpoint is not None:
            try:
                final_checkpoint()
            except CheckpointError:
                logger.exception(
                    "resilience: forced final checkpoint FAILED — the "
                    "restart resumes from the previous generation"
                )
                self.metrics.incr("resilience.final_ckpt_failures")
        within &= self._rung(
            "revoke_inflight",
            self._revoke_rung if self.admission is not None else None,
        )
        within &= self._rung(
            "drain_host",
            self._drain_host_rung
            if self.cluster is not None and self.host_id is not None
            else None,
        )
        within &= self._rung("shutdown", shutdown)
        dt = self._clock() - t0
        self.metrics.add_time("resilience.drain", dt)
        within = within and self.remaining() > 0
        self.metrics.set_gauge(
            "resilience.drain_within_deadline", 1.0 if within else 0.0
        )
        self._drained = True
        logger.warning(
            "resilience: drain complete in %.2fs (%s the %.1fs budget)",
            dt, "within" if within else "OVER", self.deadline_s,
        )
        return within

    def _rung(
        self, name: str, action: Optional[Callable[[], None]]
    ) -> bool:
        if action is None:
            return True
        if self.remaining() <= 0:
            self.metrics.incr("resilience.drain_rungs_skipped")
            logger.error(
                "resilience: drain rung %r SKIPPED — grace budget "
                "exhausted", name,
            )
            return False
        try:
            action()
        except (ShutdownRequested, KeyboardInterrupt):
            raise
        except Exception:
            # ANY failed hygiene rung must never abort the drain: the
            # checkpoint already landed and the restart recovers — an
            # AttributeError out of a half-torn-down loader is exactly
            # as survivable as a typed DDLError here.
            logger.exception("resilience: drain rung %r failed", name)
            self.metrics.incr("resilience.drain_rung_failures")
        return True

    def _revoke_rung(self) -> None:
        slo = min(self.revoke_slo_s, max(0.0, self.remaining()))
        self.admission.revoke_inflight(slo)
        self.metrics.incr("resilience.revocations")

    def _drain_host_rung(self) -> None:
        self.cluster.drain_host(self.host_id)
