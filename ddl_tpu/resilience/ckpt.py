"""Async integrity-checked train-state checkpoints (ISSUE 14 tentpole).

The legacy path (``ddl_tpu.checkpoint.save_train_state``) is synchronous:
the step loop stalls for the whole serialize + fsync while the Orbax
writer runs.  This module moves everything but the device→host snapshot
off the hot path:

- :class:`AsyncCheckpointer` snapshots the :class:`~ddl_tpu.parallel.
  train.TrainState` into pooled host staging buffers at a step-future
  boundary (``jax.device_get`` blocks only on the step that produced the
  state — the donation-safe point: once the copy lands in OUR buffers,
  the next scan is free to donate the device buffers) and hands the
  snapshot to a background writer thread.  The caller's measured stall
  is the D2H copy alone (``resilience.ckpt_submit``); serialization,
  fsync and rename hide behind training (``resilience.ckpt_write``).
- Every generation is ONE file — ``gen_<step>.ckpt`` — written through
  :func:`ddl_tpu.checkpoint.atomic_file_write` (temp+rename; DDL022)
  and stamped with the ring-slot integrity trailer
  (:mod:`ddl_tpu.integrity`): crc32 over the whole blob plus a
  STEP-DERIVED sequence, so a torn tail fails the CRC and a
  renamed/aliased generation fails the seq check even with an intact
  payload.
- The loader's logical clock (:class:`~ddl_tpu.checkpoint.
  LoaderCheckpoint`) captured at the same window boundary travels
  INSIDE the generation blob — trainer step and loader cursor are
  fenced together, so a crash between two files can never desync the
  resumed data stream from the restored params.  (``loader.json`` is
  still mirrored next to the generations for back-compat tooling; the
  embedded copy is authoritative on restore.)
- Restore walks generations newest→oldest, quarantines unverifiable
  ones (``.quarantined``, the cache-store pattern) and falls back to
  the previous verified generation; exhaustion returns None — a COLD
  START with the ``resilience.ckpt_cold_starts`` counter left loud.

Retention is keep-K: the writer unlinks generations beyond ``keep``
after each successful write (quarantined files are retired on the same
sweep once they age past the window — forensics, not a disk leak).

Chaos: the ``resilience.ckpt_write`` fault site fires on the fully
stamped blob immediately before the atomic write — ``CKPT_CORRUPTION``
flips bytes AFTER the CRC was committed, so the written generation
verifies false on read and the quarantine/fallback ladder is what the
injection exercises.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import threading

from ddl_tpu.concurrency import named_condition
import time
from typing import Any, List, Optional, Tuple

import numpy as np

from ddl_tpu import integrity
from ddl_tpu.checkpoint import (
    LoaderCheckpoint,
    atomic_file_write,
    quarantine_path,
)
from ddl_tpu.exceptions import CheckpointError, ShutdownRequested
from ddl_tpu.faults import fault_point
from ddl_tpu.observability import Metrics, metrics as default_metrics
from ddl_tpu.parallel.train import TrainState

logger = logging.getLogger("ddl_tpu")

#: Generation-file magic (8 bytes), ahead of the u32 header length.
_MAGIC = b"DDLRES1\0"
_GEN_RE = re.compile(r"^gen_(\d{10})\.ckpt$")

#: Trailer identity for checkpoint blobs (the ring headers carry the
#: 1-based producer index there; 0 is unused by any producer).
_CKPT_PRODUCER = 0


def _gen_name(step: int) -> str:
    return f"gen_{int(step):010d}.ckpt"


def list_generations(directory: str) -> List[Tuple[int, str]]:
    """``[(step, path)]`` of every generation file, oldest first."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _GEN_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def verify_generation(path: str, expect_step: int) -> Optional[str]:
    """Full read-side check of one generation file.  Returns a failure
    description, or None when the blob is intact AND is the generation
    its filename claims (trailer seq == step — a renamed file fails
    here even with an intact payload)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        return f"unreadable: {e}"
    min_size = len(_MAGIC) + 4 + integrity.HEADER_BYTES
    if len(raw) < min_size:
        return f"truncated: {len(raw)} bytes < minimum {min_size}"
    view = np.frombuffer(raw, dtype=np.uint8)
    payload_bytes = len(raw) - integrity.HEADER_BYTES
    err = integrity.verify_window(
        view, payload_bytes,
        expect_seq=int(expect_step), expect_producer=_CKPT_PRODUCER,
    )
    if err is not None:
        return err
    if raw[: len(_MAGIC)] != _MAGIC:
        return f"bad file magic {raw[:8]!r}"
    return None


def latest_verified_generation(
    directory: str, quarantine: bool = True,
    metrics: Optional[Metrics] = None,
) -> Optional[Tuple[int, str]]:
    """The newest ``(step, path)`` whose integrity trailer verifies.

    Unverifiable generations are quarantined and skipped — the restore
    falls back to the previous verified generation.  Returns None at
    exhaustion (cold start; the caller makes that loud)."""
    m = metrics or default_metrics()
    for step, path in reversed(list_generations(directory)):
        err = verify_generation(path, step)
        if err is None:
            return step, path
        logger.error(
            "resilience: checkpoint generation %s failed verification "
            "(%s)", path, err,
        )
        if quarantine:
            quarantine_path(path, metrics=m)
        else:
            m.incr("resilience.ckpt_quarantined")
    return None


@dataclasses.dataclass
class RestoredRun:
    """One verified restore: the train state, the loader cursor that
    was fenced to it (None for state-only generations), and the step."""

    state: TrainState
    loader: Optional[LoaderCheckpoint]
    step: int


def _leaves(state: TrainState) -> List[Any]:
    import jax

    return jax.tree_util.tree_leaves(
        {"params": state.params, "opt_state": state.opt_state}
    )


def _leaf_array(leaf: Any) -> np.ndarray:
    """Materialize one state leaf on the host.  The caller copies the
    result into its own staging buffer, so a zero-copy device_get view
    (the CPU client) is fine here — independence from the device
    buffer is established by THAT copy, not this function."""
    import jax

    if isinstance(leaf, (int, float)):
        return np.asarray(leaf)
    return np.asarray(jax.device_get(leaf))


def serialize_generation(
    step: int,
    leaves: List[np.ndarray],
    loader_dict: Optional[dict],
) -> np.ndarray:
    """Build the stamped generation blob: magic | u32 header-len |
    header JSON | leaf payload | 32-byte integrity trailer (crc over
    everything before it, seq = step)."""
    header = json.dumps({
        "step": int(step),
        "loader": loader_dict,
        "leaves": [
            {"shape": list(a.shape), "dtype": str(a.dtype)}
            for a in leaves
        ],
    }).encode()
    payload_bytes = (
        len(_MAGIC) + 4 + len(header) + sum(a.nbytes for a in leaves)
    )
    blob = np.empty(payload_bytes + integrity.HEADER_BYTES, dtype=np.uint8)
    off = len(_MAGIC)
    blob[:off] = np.frombuffer(_MAGIC, dtype=np.uint8)
    blob[off : off + 4] = np.frombuffer(
        np.uint32(len(header)).tobytes(), dtype=np.uint8
    )
    off += 4
    blob[off : off + len(header)] = np.frombuffer(header, dtype=np.uint8)
    off += len(header)
    for a in leaves:
        flat = np.ascontiguousarray(a).view(np.uint8).reshape(-1)
        blob[off : off + flat.nbytes] = flat
        off += flat.nbytes
    crc = integrity.window_crc(blob[:payload_bytes])
    integrity.write_header(
        blob, payload_bytes, seq=int(step), producer_idx=_CKPT_PRODUCER,
        crc=crc,
    )
    return blob


def _parse_generation(path: str) -> Tuple[dict, np.ndarray]:
    """(header dict, payload byte view) of a VERIFIED generation."""
    with open(path, "rb") as f:
        raw = f.read()
    off = len(_MAGIC)
    (hlen,) = np.frombuffer(raw[off : off + 4], dtype=np.uint32)
    off += 4
    header = json.loads(raw[off : off + int(hlen)].decode())
    off += int(hlen)
    payload = np.frombuffer(
        raw, dtype=np.uint8,
        count=len(raw) - integrity.HEADER_BYTES - off, offset=off,
    )
    return header, payload


def restore_latest(
    directory: str,
    like: TrainState,
    metrics: Optional[Metrics] = None,
    found: Optional[Tuple[int, str]] = None,
) -> Optional[RestoredRun]:
    """Restore the newest verified generation onto ``like``'s structure
    and shardings.  Returns None when no verified generation exists
    (cold start — counted ``resilience.ckpt_cold_starts`` ONLY when
    unverifiable generations were present and exhausted, i.e. data was
    lost; an empty directory is a first run, not an incident).

    ``found`` short-circuits the verification scan with a ``(step,
    path)`` the caller already verified via
    :func:`latest_verified_generation` — restart I/O matters exactly
    in the preemption-recovery window, and re-CRC'ing every multi-GB
    blob a second time would double it."""
    import jax

    m = metrics or default_metrics()
    had_any = bool(list_generations(directory))
    if found is None:
        found = latest_verified_generation(directory, metrics=m)
    if found is None:
        if had_any:
            m.incr("resilience.ckpt_cold_starts")
            logger.error(
                "resilience: EVERY checkpoint generation under %s "
                "failed verification — COLD START (all quarantined)",
                directory,
            )
        return None
    step, path = found
    header, payload = _parse_generation(path)
    like_leaves = _leaves(like)
    meta = header["leaves"]
    if len(meta) != len(like_leaves):
        raise CheckpointError(
            f"generation {path} holds {len(meta)} leaves; the current "
            f"model/optimizer has {len(like_leaves)} — geometry changed"
        )
    out, off = [], 0
    for want, leaf in zip(meta, like_leaves):
        arr = np.asarray(leaf) if isinstance(leaf, (int, float)) else leaf
        dtype = np.dtype(arr.dtype)
        shape = tuple(want["shape"])
        if shape != tuple(arr.shape) or want["dtype"] != str(dtype):
            raise CheckpointError(
                f"generation {path} leaf {len(out)}: saved "
                f"{want['dtype']}{shape} vs current "
                f"{dtype}{tuple(arr.shape)} — geometry changed"
            )
        nbytes = int(dtype.itemsize * int(np.prod(shape, dtype=np.int64)))
        host = (
            payload[off : off + nbytes].copy().view(dtype).reshape(shape)
        )
        off += nbytes
        if isinstance(leaf, (int, float)):
            out.append(type(leaf)(host[()]))
        elif hasattr(leaf, "sharding"):
            out.append(jax.device_put(host, leaf.sharding))
        else:
            out.append(host)
    treedef = jax.tree_util.tree_structure(
        {"params": like.params, "opt_state": like.opt_state}
    )
    tree = jax.tree_util.tree_unflatten(treedef, out)
    loader_ck = None
    if header.get("loader"):
        loader_ck = LoaderCheckpoint(**header["loader"])
    m.incr("resilience.ckpt_restores")
    return RestoredRun(
        state=TrainState(
            params=tree["params"], opt_state=tree["opt_state"],
            step=int(header["step"]),
        ),
        loader=loader_ck,
        step=step,
    )


class AsyncCheckpointer:
    """Background-thread checkpoint writer with pooled host staging.

    ``submit`` is the hot-path call: it materializes the state into
    recycled host buffers (the D2H copy — the only stall the step loop
    pays, at the step-future boundary where ``device_get`` blocks just
    on the step that produced the state) and enqueues the write.  The
    writer thread serializes, stamps the integrity trailer, writes
    atomically, mirrors ``loader.json``, and trims retention — all
    under training.  Staging is double-buffered (two buffer sets max,
    the :class:`~ddl_tpu.staging.StagingPool` recycle pattern): a
    writer that falls behind backpressures ``submit`` into SKIPPING a
    periodic checkpoint (counted, the lost-work bound grows by one
    interval) rather than growing host memory without bound; the
    FORCED final checkpoint (:meth:`checkpoint_now`) waits instead.

    The writer thread starts on first use and parks itself (exits)
    after a few idle seconds, so trainers that checkpoint once do not
    pin a thread for their lifetime.
    """

    #: Idle seconds after which the parked writer thread exits.
    _IDLE_EXIT_S = 5.0

    def __init__(
        self,
        directory: str,
        keep: int = 3,
        metrics: Optional[Metrics] = None,
        submit_timeout_s: float = 120.0,
    ):
        if keep < 1:
            raise CheckpointError(f"keep must be >= 1, got {keep}")
        self.directory = os.path.abspath(directory)
        self.keep = int(keep)
        self.metrics = metrics or default_metrics()
        self.submit_timeout_s = float(submit_timeout_s)
        self._cond = named_condition("resilience.ckpt.cv")
        self._queue: List[Tuple[int, List[np.ndarray], Optional[dict]]] = []
        self._free: List[List[np.ndarray]] = []
        self._n_sets = 0
        self._busy = False
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._last_error: Optional[BaseException] = None

    # -- staging (double-buffered host snapshot) ---------------------------

    def _acquire_buffers(
        self, leaves: List[Any], block: bool,
        timeout_s: Optional[float] = None,
    ) -> Optional[List[np.ndarray]]:
        wait_s = self.submit_timeout_s if timeout_s is None else timeout_s
        with self._cond:
            deadline = time.monotonic() + wait_s
            while not self._free and self._n_sets >= 2:
                if not block:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise CheckpointError(
                        "checkpoint writer wedged: no staging buffer "
                        f"freed within {wait_s}s"
                    )
                self._cond.wait(min(0.2, remaining))
            if self._free:
                bufs = self._free.pop()
                if len(bufs) == len(leaves) and all(
                    b.shape == np.shape(l) and b.dtype == getattr(
                        l, "dtype", np.asarray(l).dtype
                    )
                    for b, l in zip(bufs, leaves)
                ):
                    return bufs
                # Geometry changed (new model on one checkpointer):
                # drop the stale set and allocate fresh below.
                self._n_sets -= 1
            self._n_sets += 1
        return [
            np.empty(np.shape(l), dtype=getattr(
                l, "dtype", np.asarray(l).dtype
            ))
            for l in leaves
        ]

    def _release_buffers(self, bufs: List[np.ndarray]) -> None:
        with self._cond:
            self._free.append(bufs)
            self._cond.notify_all()

    # -- the hot-path call -------------------------------------------------

    def submit(
        self,
        state: TrainState,
        loader_ckpt: Optional[LoaderCheckpoint] = None,
        block: bool = False,
        timeout_s: Optional[float] = None,
    ) -> bool:
        """Snapshot ``state`` (+ the fenced loader cursor) and enqueue
        the write.  Returns False when the writer is backed up and the
        checkpoint was SKIPPED (periodic checkpoints only —
        ``block=True``, the forced path, waits for a buffer instead,
        up to ``timeout_s`` when given).
        """
        if self._closed:
            raise CheckpointError("checkpointer is closed")
        t0 = time.perf_counter()
        leaves = _leaves(state)
        bufs = self._acquire_buffers(leaves, block=block,
                                     timeout_s=timeout_s)
        if bufs is None:
            self.metrics.incr("resilience.ckpt_skipped")
            logger.warning(
                "resilience: checkpoint writer backed up — skipping "
                "step-%d checkpoint (lost-work bound grows one interval)",
                int(state.step),
            )
            return False
        # The donation-safe boundary: device_get blocks only on the
        # step futures that produced the state; after the copy below
        # lands, the caller may donate the device buffers freely.
        for buf, leaf in zip(bufs, leaves):
            np.copyto(buf, _leaf_array(leaf), casting="no")
        loader_dict = (
            dataclasses.asdict(loader_ckpt)
            if loader_ckpt is not None
            else None
        )
        with self._cond:
            self._queue.append((int(state.step), bufs, loader_dict))
            self._ensure_writer()
            self._cond.notify_all()
        self.metrics.add_time(
            "resilience.ckpt_submit", time.perf_counter() - t0
        )
        return True

    def checkpoint_now(
        self,
        state: TrainState,
        loader_ckpt: Optional[LoaderCheckpoint] = None,
        timeout_s: float = 60.0,
    ) -> None:
        """The FORCED checkpoint (preemption drain): submit with
        backpressure-wait, then flush to disk; raises
        :class:`CheckpointError` if the generation is not durably
        written inside ``timeout_s`` — ONE budget covering both halves
        (a preemption deadline has no patience for the defaults).  A
        stale failure from an EARLIER periodic write is cleared first:
        this call reports on ITS OWN generation, not on history the
        retention loop already logged."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            self._last_error = None
        self.submit(state, loader_ckpt, block=True, timeout_s=timeout_s)
        self.flush(timeout_s=max(0.0, deadline - time.monotonic()))
        self.metrics.incr("resilience.final_ckpts")

    def flush(self, timeout_s: float = 60.0) -> None:
        """Bounded wait for every queued write to land (raises
        :class:`CheckpointError` on timeout or a writer failure).  A
        raised failure is CONSUMED: one failure episode surfaces once,
        and later flushes over subsequent successful writes are clean
        again (a transient ENOSPC hours ago must not poison the
        preemption drain's forced checkpoint)."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            while self._queue or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise CheckpointError(
                        f"checkpoint flush timed out after {timeout_s}s "
                        f"({len(self._queue)} generation(s) still queued)"
                    )
                self._cond.wait(min(0.2, remaining))
            err, self._last_error = self._last_error, None
        if err is not None:
            raise CheckpointError(
                f"checkpoint write failed: {type(err).__name__}: {err}"
            ) from err

    def close(self, timeout_s: float = 60.0) -> None:
        if self._closed:
            return
        try:
            self.flush(timeout_s=timeout_s)
        finally:
            self._closed = True
            with self._cond:
                t = self._thread
                self._cond.notify_all()
            if t is not None:
                t.join(timeout_s)

    # -- the writer thread -------------------------------------------------

    def _ensure_writer(self) -> None:
        # Caller holds self._cond.
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="ddl-ckpt-writer", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        idle_since = time.monotonic()
        while True:
            with self._cond:
                while not self._queue:
                    if self._closed or (
                        time.monotonic() - idle_since > self._IDLE_EXIT_S
                    ):
                        self._thread = None
                        self._cond.notify_all()
                        return
                    self._cond.wait(0.2)
                step, bufs, loader_dict = self._queue.pop(0)
                self._busy = True
            try:
                with self.metrics.timed("resilience.ckpt_write"):
                    self._write_generation(step, bufs, loader_dict)
                self.metrics.incr("resilience.ckpts")
            except (ShutdownRequested, KeyboardInterrupt):
                with self._cond:
                    self._busy = False
                    self._thread = None
                    self._cond.notify_all()
                raise
            except Exception as e:  # writer must survive one bad write
                self.metrics.incr("resilience.ckpt_write_failures")
                logger.exception(
                    "resilience: checkpoint write for step %d failed", step
                )
                with self._cond:
                    self._last_error = e
            finally:
                self._release_buffers(bufs)
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()
            idle_since = time.monotonic()

    def _write_generation(
        self, step: int, leaves: List[np.ndarray],
        loader_dict: Optional[dict],
    ) -> None:
        blob = serialize_generation(step, leaves, loader_dict)
        payload_bytes = blob.nbytes - integrity.HEADER_BYTES
        # Chaos site: fires on the STAMPED blob just before the atomic
        # write — CKPT_CORRUPTION flips committed bytes so read-time
        # verification (and the quarantine/fallback ladder) is what the
        # injection exercises.
        fault_point("resilience.ckpt_write", view=blob[:payload_bytes])
        path = os.path.join(self.directory, _gen_name(step))
        atomic_file_write(path, blob.tobytes())
        self.metrics.set_gauge("resilience.ckpt_bytes", float(blob.nbytes))
        if loader_dict is not None:
            # Back-compat mirror: legacy tooling reads loader.json; the
            # EMBEDDED copy above is authoritative on restore (fenced
            # in the same atomic write as the train state).
            atomic_file_write(
                os.path.join(self.directory, "loader.json"),
                json.dumps(loader_dict).encode(),
            )
        self._trim_retention()

    def _trim_retention(self) -> None:
        gens = list_generations(self.directory)
        for step, path in gens[: -self.keep] if len(gens) > self.keep else []:
            try:
                os.unlink(path)
                self.metrics.incr("resilience.ckpt_retired")
            except OSError:
                logger.warning(
                    "resilience: could not retire generation %s", path
                )
        # Quarantined blobs are forensics, not a disk leak: retire them
        # once their step ages past the retained window (recurring
        # corruption must not fill the checkpoint volume and then fail
        # the one forced checkpoint a real preemption depends on).
        if not gens[-self.keep :]:
            return
        oldest_kept = gens[-self.keep :][0][0]
        for name in os.listdir(self.directory):
            if ".ckpt.quarantined" not in name:
                continue
            m = re.match(r"^gen_(\d{10})\.ckpt\.quarantined", name)
            if m and int(m.group(1)) < oldest_kept:
                try:
                    os.unlink(os.path.join(self.directory, name))
                    self.metrics.incr("resilience.ckpt_retired")
                except OSError:
                    logger.warning(
                        "resilience: could not retire quarantined %s",
                        name,
                    )
