"""Profiling integration: jax.profiler traces around pipeline sections.

The reference's only introspection was the DEBUG call tracer
(``with_logging``, SURVEY §5.1), kept in ``ddl_tpu.utils``.  This adds the
TPU-native layer: ``jax.profiler`` device traces with named host
annotations, so ingest stalls and collective time show up on the TensorBoard
timeline next to the XLA ops.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace for the enclosed block."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named host span, visible on the profiler timeline.

    Usage::

        with annotate("ddl.window_drain"):
            batch = loader[i]
    """
    import jax

    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def maybe_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Trace only when a log dir is configured (no-op otherwise)."""
    if log_dir:
        with trace(log_dir):
            yield
    else:
        yield
