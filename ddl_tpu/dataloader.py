"""Consumer API: the distributed dataloader.

Parity with reference ``ddl/mpi_dataloader.py`` — ``DistributedDataLoader``
with ``__len__`` / ``__getitem__`` / ``mark`` (``mpi_dataloader.py:107-241``):

- ``__len__`` is ``batches_per_window`` — an "epoch" in the user loop is one
  window of the current producer (Q7 semantics preserved for API compat;
  dataset coverage comes from round-robin rotation across epochs).
- ``__getitem__`` returns a zero-copy tuple of column-split tensors from the
  current window (reference ``mpi_dataloader.py:179-198``).
- The user MUST call ``mark(Marker.END_OF_BATCH)`` after every step and
  ``mark(Marker.END_OF_EPOCH)`` after every epoch; rotation and shutdown
  are driven off the marks (reference ``mpi_dataloader.py:89-102``).

Fixes over the reference: unequal ``batches_per_window`` across producers is
SERVED (weighted rotation — each turn drains the whole current window, so
``len(loader)`` tracks the rotation) where the reference left mixed sizes
as an unfinished deadlocking ToDo (Q6, ``mpi_dataloader.py:223``);
single-process THREAD mode is first-class rather than a silent empty
loader (Q9, ``mpi_dataloader.py:173-174``); output can be numpy views,
torch tensors, or JAX device arrays (device ingest).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ddl_tpu import envspec
from ddl_tpu import integrity
from ddl_tpu.datasetwrapper import ProducerFunctionSkeleton
from ddl_tpu.exceptions import (
    DoesNotMatchError,
    IntegrityError,
    LoaderStateError,
    ShutdownRequested,
    StallTimeoutError,
)
from ddl_tpu.obs import spans as obs_spans
from ddl_tpu.obs.recorder import flight_dump
from ddl_tpu.observability import Metrics, metrics as default_metrics
from ddl_tpu.transport.connection import NOTHING, ConsumerConnection
from ddl_tpu.types import (
    ControlAck,
    Marker,
    MetaData_Consumer_To_Producer,
    ObsReport,
)
from ddl_tpu.utils import for_all_methods, with_logging

logger = logging.getLogger("ddl_tpu")


def _transfer_ready(dev: Any) -> bool:
    """Non-blocking transfer-completion probe on a device value (a jax
    array or tuple/pytree of them).  Leaves without ``is_ready`` (older
    jax) report not-ready — the caller's forced flush still blocks
    correctly, the fast path just never triggers."""
    from ddl_tpu.utils import value_ready

    return value_ready(dev, default=False)


class _CorruptAhead(Exception):
    """Internal: integrity verification failed on a LOOKAHEAD acquire.

    Held earlier slots make out-of-FIFO quarantine impossible, so the
    stream stops deepening instead; the corrupt window re-verifies (and
    enters quarantine-and-replay) when it reaches the head.  Never
    escapes the loader.
    """


class _TargetRevoked(Exception):
    """Internal: the target being acquired left the loader pool (a
    cluster view change dropped its host mid-acquire).  The acquire
    paths re-normalise onto the published pool and retry; never escapes
    the loader."""


# Rank-tagged DEBUG call tracing on every method, as the reference wrapped
# its three core classes (reference ``mpi_dataloader.py:106``); the hot
# per-batch path (``__getitem__`` via dunder skip, ``_host_cols``
# explicitly) stays quiet, mirroring the reference's ``__getitem__``
# exclusion (``mpi_dataloader.py:104-106``).
@for_all_methods(with_logging, exclude=("_host_cols", "_host_batch"))
class DistributedDataLoader:
    """Map-style loader over producer window rings.

    Construction performs the consumer half of the handshake
    (reference ``mpi_dataloader.py:127-172``): broadcast the pickled
    producer function + batch geometry, gather per-producer window specs,
    attach rings, and acquire the first window.
    """

    def __init__(
        self,
        data_producer_function: ProducerFunctionSkeleton,
        batch_size: int,
        connection: ConsumerConnection,
        n_epochs: int = 1,
        global_shuffle_fraction_exchange: float = 0.0,
        exchange_method: str = "sendrecv_replace",
        output: str = "torch",
        device: Any = None,
        sharding: Any = None,
        metrics: Optional[Metrics] = None,
        timeout_s: float = 300.0,
        staged: Optional[bool] = None,
        distribute: Optional[str] = None,
        cluster: Any = None,
    ):
        if output not in ("torch", "numpy", "jax"):
            raise ValueError(f"output must be torch|numpy|jax, got {output!r}")
        self.batch_size = batch_size
        self.n_epochs = n_epochs
        self.connection = connection
        self.output = output
        self.metrics = metrics or default_metrics()
        # The acked control seam's delivery counters (ctrl.*) land in
        # this loader's registry (ddl_tpu.transport.envelope).
        connection.control_metrics = self.metrics
        self.timeout_s = timeout_s
        self._epoch = 0
        self._batches_in_window = 0
        self._served_in_epoch = 0
        self._target = 0  # index into connection.rings, round-robin
        self._cur_slot: Optional[int] = None
        self._cur_array: Optional[np.ndarray] = None
        self._stream_token: Optional[object] = None  # active windows() stream
        self._finalized = False
        self._ingestor = None
        # Staged windows whose ring slots were released early (copy done)
        # but which no stream has yielded yet — an abandoned stream's
        # lookahead survives here, so the next stream serves it instead
        # of losing it (the break-resume contract, kept under staging).
        self._staged_orphans: "list" = []
        # Inline-stream windows already YIELDED whose ring slots are
        # still held pending transfer completion: [target, slot, dev]
        # in yield (== per-ring FIFO) order.  The old stream blocked the
        # host on every window's transfer before yielding it
        # (``jax.block_until_ready``), serializing window k+1's H2D
        # against window k's scanned optimizer steps (VERDICT r5 weak
        # #4); release is now gated on a non-blocking readiness probe,
        # with forced (blocking) flushes only where the ring actually
        # needs the slot back.
        self._release_backlog: "list" = []
        # Fused-step protocol seam: the most recently yielded stream
        # window's backlog entry, so ``gate_release_on`` can re-gate its
        # slot release on the CONSUMING step's done-future instead of
        # the bare transfer (ddl_tpu.trainer._fused_stream_loop).
        self._last_stream_entry: Any = None
        # Loader-pool decoupling seam (ddl_tpu.cluster): the APPLIED
        # LoaderPool this loader rotates over (members filtered to
        # local ring targets).  None = every ring (the static topology
        # the handshake reported).  Pool updates arrive asynchronously
        # (cluster supervisor thread) as _pending_pool and are APPLIED
        # on the consumer thread at window boundaries — rotation state
        # is single-threaded by construction.
        self._pool: Any = None
        self._pool_generation = -1
        self._pending_pool: Any = None
        self._cluster = cluster
        # Multi-tenant admission seam (ddl_tpu.serve): when bound, every
        # window acquisition passes the fair-share gate before touching
        # a ring, and charges its byte size after — see bind_admission.
        self._admission: Any = None
        # Per-job integrity namespace (ddl_tpu.serve.jobs): producers
        # stamp trailer seqs at seq_base + iteration and this consumer
        # expects exactly that slice, so a window leaking across jobs
        # fails seq verification.  Rides the producer function — the
        # wire_dtype handshake pattern — so both sides always agree.
        self._seq_base = int(
            getattr(data_producer_function, "seq_base", 0) or 0
        )
        # Cross-process observability (ddl_tpu.obs): PROCESS workers
        # ship ObsReports over the control channel; the merger fences
        # and folds them into this registry under producer.<idx>.*.
        # Built lazily on the first cross-process report poll.
        self._obs_merger: Any = None
        # Logical seq of the most recent successful head acquire — the
        # window-identity key the span/staging instrumentation stitches
        # on (consumer thread only, like the rotation state).
        self._last_acquired_seq: Optional[int] = None
        # Identity key of the most recently YIELDED stream window (the
        # trainer's consume spans read it — see last_window_key).
        self._last_window_key: Any = None
        if output == "jax":
            from ddl_tpu.ingest import DeviceIngestor

            # ``staged=None`` defers to the DDL_TPU_STAGED env gate;
            # ``distribute=None`` to DDL_TPU_DISTRIBUTE (default "auto":
            # the post-H2D hop rides the ICI fan-out tier on accelerator
            # meshes, the XLA scatter elsewhere — ddl_tpu/parallel/ici).
            self._ingestor = DeviceIngestor(
                device=device, sharding=sharding, metrics=self.metrics,
                staged=staged, distribute=distribute,
            )

        # -- handshake -----------------------------------------------------
        connection.send_metadata(
            MetaData_Consumer_To_Producer(
                data_producer_function=data_producer_function,
                batch_size=batch_size,
                n_epochs=n_epochs,
                global_shuffle_fraction_exchange=global_shuffle_fraction_exchange,
                exchange_method=exchange_method,
            )
        )
        replies = connection.recv_metadata_as_consumer()
        if not replies:
            raise DoesNotMatchError(0, "no producers connected")
        self.replies = replies
        # Per-producer epoch lengths: UNEQUAL batches_per_window is
        # served by weighted rotation — each producer's turn serves its
        # WHOLE window, so a bigger window simply makes a longer epoch
        # (len(self) tracks the current target).  The reference left
        # mixed sizes as an unfinished ToDo that deadlocked its token
        # protocol (Q6, reference mpi_dataloader.py:223); rotation has
        # no tokens to mismatch.
        self._lens = [r.batches_per_window for r in replies]
        # End-to-end integrity (ddl_tpu.integrity): every producer that
        # advertised header stamping gets drain-time verification; the
        # quarantine-and-replay budget bounds how often one logical
        # window may be re-requested before the corruption is declared
        # unrecoverable.  Replay rewinds the producer function, which is
        # only sound without cross-instance exchange (peer-contributed
        # rows are not locally regenerable, whichever transport carried
        # them — host rendezvous or the device tier's ICI exchange) —
        # with shuffle active a corrupt slot escalates straight to
        # IntegrityError.
        self._integrity = all(getattr(r, "integrity", False) for r in replies)
        # Wire format per producer (ddl_tpu.wire): slots from a
        # wire-encoded producer carry the bf16/int8 payload + trailer
        # scales; the consumer edge decodes them back to the logical
        # shape/dtype the handshake reported (``_slot_array``).
        self._wire_dtypes = [
            getattr(r, "wire_dtype", "raw") or "raw" for r in replies
        ]
        self._shuffle_fraction = global_shuffle_fraction_exchange
        self._max_replays = envspec.get("DDL_TPU_MAX_REPLAYS")
        # Per-target count of DISCARDED ring commits (quarantined slots +
        # stale in-flight successors dropped while waiting for a replay):
        # logical window seq = ring.released + held - skew.
        self._seq_skew = [0] * len(replies)
        # Geometry is per-producer: heterogeneous column layouts are served
        # correctly rather than silently mis-split with producer 0's spec.
        self.splits_per_producer = [tuple(r.splits) for r in replies]
        self.shapes = [tuple(r.shape) for r in replies]
        self.dtypes = [np.dtype(r.dtype) for r in replies]
        connection.attach_rings()
        # Cluster decoupling seam: consume from whatever loader pool the
        # view publishes.  ``cluster`` may be the full recovery ladder
        # (ElasticCluster — attach_loader wires pool-following + rung-2
        # actions) or a bare ClusterSupervisor (pool-following only).
        if cluster is not None:
            if hasattr(cluster, "attach_loader"):
                cluster.attach_loader(self)
            else:
                cluster.add_listener(
                    lambda _old, new, _dead: self.apply_pool(
                        new.loader_pool()
                    )
                )
                self.apply_pool(cluster.view.loader_pool())
        # First window is acquired lazily on first __getitem__: acquiring
        # here (as the reference did, mpi_dataloader.py:172) would also make
        # the FINAL mark of a run block on a whole extra window that
        # shutdown immediately discards.

    # -- iteration protocol ------------------------------------------------

    @property
    def n_producers(self) -> int:
        return self.connection.n_producers

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def batches_per_window(self) -> int:
        """Epoch length of the CURRENT target producer (Q7: one epoch ==
        one window).  With mixed window sizes this changes as the
        rotation advances — read it per epoch, as ``Trainer.fit`` does
        for its per-geometry scan cache."""
        return self._lens[self._target]

    def __len__(self) -> int:
        return self._lens[self._target]

    def _host_batch(self, idx: int) -> np.ndarray:
        """Zero-copy view of batch ``idx`` in the current window."""
        if not isinstance(idx, (int, np.integer)):
            raise ValueError(f"index must be int, got {type(idx)}")
        if (
            self._cur_array is None
            and self._batches_in_window == 0
            and self._served_in_epoch
        ):
            # This epoch's window has been fully served and released
            # (marks rotated the target); the next window belongs to the
            # NEXT epoch (Q7: one epoch == one window).  Ending
            # iteration here is what bounds a `for` loop when the NEXT
            # producer's window is longer than the one just served —
            # with equal windows the idx bound below fired at the same
            # point, with mixed windows it would keep indexing into the
            # rotated-to window mid-epoch.
            raise IndexError(idx)
        if idx < 0 or idx >= self._lens[self._target]:
            raise IndexError(idx)
        if self._finalized:
            raise LoaderStateError("loader is finalized")
        if self._cur_array is None:
            self._acquire_current()
        assert self._cur_array is not None
        start = self.batch_size * idx
        batch = self._cur_array[start : start + self.batch_size]
        self.metrics.incr("consumer.samples", self.batch_size)
        self._served_in_epoch += 1
        return batch

    def _host_cols(self, idx: int) -> Tuple[np.ndarray, ...]:
        """Zero-copy column views of batch ``idx`` in the current window."""
        return _split_columns(
            self._host_batch(idx), self.splits_per_producer[self._target]
        )

    def __getitem__(self, idx: int) -> Tuple[Any, ...]:
        # IndexError terminates Python's implicit iteration protocol in the
        # user's `for` loop (reference mpi_dataloader.py:180-183).
        if self.output == "jax":
            # One transfer per batch, column split ON device (narrow
            # columns otherwise pay the link's fixed per-transfer cost).
            assert self._ingestor is not None
            return self._ingestor.put_batch(
                self._host_batch(idx), self.splits_per_producer[self._target]
            )
        cols = self._host_cols(idx)
        if self.output == "numpy":
            return cols
        # torch.from_numpy is zero-copy over the ring slot, exactly as
        # the reference's view over the MPI shared window
        # (mpi_dataloader.py:192-193).
        import torch

        return tuple(torch.from_numpy(c) for c in cols)

    def prefetch(self, depth: Optional[int] = None):
        """Iterate one epoch's device batches with ``depth`` transfers in
        flight (``output="jax"`` only) — while step k computes, batch k+1
        is already crossing into HBM (the standard TPU input recipe;
        VERDICT r2 item 5 wired this into the training path).
        ``depth=None`` reads ``DDL_TPU_PREFETCH_DEPTH`` (the
        config-mirrored seam the boot-time Calibrator retunes).

        Reads ahead *within the current window*: all ``len(self)`` batches
        of an epoch live in one window, and each batch is copied out of
        the slot before the window is released — at enqueue time on the
        inline path, and no later than the slot-release barrier
        (``TransferExecutor.flush_copies`` in ``_release_current``) on
        the staged path — so lookahead never outlives the slot.
        ``mark()`` stays the caller's job, exactly as with plain
        iteration.
        """
        if self._ingestor is None:
            raise LoaderStateError("prefetch requires output='jax'")
        from ddl_tpu.ingest import PrefetchIterator

        splits = self.splits_per_producer[self._target]

        def host_iter():
            for idx in range(self._lens[self._target]):
                yield self._host_batch(idx)

        # Staged ingestors enqueue slot views to the background executor
        # (copy + dispatch off-thread) and pop ready device tuples; the
        # put fn serves inline ingestors AND the staged adaptive direct
        # mode (pooled, dispatch now) on hosts where the worker starves.
        # PrefetchIterator itself gates `transfer` on ingestor.staged.
        return PrefetchIterator(
            host_iter(), self._ingestor, depth,
            put=lambda b: self._ingestor.put_batch(b, splits),
            transfer=self._ingestor.batch_transfer_fn(splits),
        )

    def windows(self, lookahead: int = 1):
        """Stream whole windows into HBM, one per epoch (``output="jax"``).

        Two ingest disciplines, selected by the ``DDL_TPU_STAGED`` gate
        and the target platform (``DeviceIngestor.stream_staged``):

        - **Staged** (default on accelerators; forced by
          ``staged=True``): the background executor copies each window
          slot→pooled-staging-buffer and dispatches its transfer
          off-thread; the SLOT is released back to the producer as soon
          as the staging copy completes — one host memcpy of hold time
          instead of the whole H2D transfer, so producers refill sooner
          and the same ``nslots`` sustains a deeper in-flight pipeline.
        - **Inline** (``DDL_TPU_STAGED=0``, and the default on the CPU
          client): each window's transfer sources the ring slot directly
          (no host memcpy anywhere between producer fill and HBM).  The
          slot is still owned until the transfer completes, but the
          HOST never blocks on that: windows yield as async device
          values and slot release is gated on a transfer-completion
          probe (forced only when the ring runs out of slots), so
          window k+1's H2D overlaps window k's compute instead of
          serializing behind a per-window ``block_until_ready``.  (On
          the CPU client ``put_window`` detaches the source with its
          alias-guard copy, so slots release at yield.)

        Either way the next window's transfer streams while the caller's
        compute on the current one runs.  This is the TPU analog of the
        reference's zero-copy shared-window reads
        (reference ``mpi_dataloader.py:192-193``) extended across the
        host→device boundary.

        ``lookahead`` (default 1) double-buffers the stream: before window
        k is yielded, window k+1 is acquired — holding a second slot —
        and its transfer started, so H2D overlaps the caller's compute BY
        CONSTRUCTION rather than by async-dispatch timing.  The reference
        double-buffered the host-side analog only as a ToDo sketch
        (reference ``mpi_dataloader.py:21-28``); here it spans the
        host→device boundary.  The lookahead acquire is a NON-BLOCKING
        try: when the producer has not committed window k+1 yet, window k
        yields immediately and the wait happens where it always did — the
        stream never lets producer slowness delay compute it could not
        have hidden anyway.  Needs ``nslots >= 2`` (or >= 2 producers) to
        take effect; ``lookahead=0`` restores strict alternation.

        Yields device arrays of shape ``(batches_per_window, batch_size,
        *features)``.  The caller still calls ``mark(Marker.END_OF_EPOCH)``
        after each window (Q7: one epoch == one window); batch-level
        ``__getitem__``/``END_OF_BATCH`` iteration must not be mixed with
        ``windows()`` inside the same epoch.  Pair with producer functions
        that set ``inplace_fill`` for a fully copy-free pipeline.
        """
        if self._ingestor is None:
            raise LoaderStateError("windows() requires output='jax'")
        import collections

        import jax

        from ddl_tpu.profiling import annotate
        from ddl_tpu.staging import StagedTransfer

        # Staged engine: the window is copied slot→pooled-staging-buffer
        # by the background executor, and the SLOT is released as soon as
        # that copy completes — the producer refills while the H2D
        # transfer (sourcing the staging buffer, not the slot) is still
        # in flight.  Inline (DDL_TPU_STAGED=0, and the default on the
        # CPU client, where the stream is zero-copy — see
        # DeviceIngestor.stream_staged): the transfer sources the slot
        # directly and the slot is held until the bytes are on device.
        engine = (
            self._ingestor.engine() if self._ingestor.stream_staged else None
        )

        held: collections.Counter = collections.Counter()
        # A previous stream's yielded-but-unreleased windows still hold
        # ring slots; count them so this stream's drain-lookahead
        # accounting (acquire_drain_ahead(held)) skips past them, and
        # sweep them out as their transfers complete.
        for _entry in self._release_backlog:
            held[_entry[0]] += 1
        # FIFO of [slot, target, payload, samples, slot_released] with
        # transfers in flight; at most 1 + lookahead entries.  payload is
        # a device array (inline) or a StagedTransfer handle (staged).
        pending: collections.deque = collections.deque()
        # GENERATOR-LOCAL rotation cursor.  ``self._target`` stays the
        # authoritative next-UNSERVED pointer and only advances when a
        # window is actually yielded (see finish) — so abandoning this
        # generator needs no state rollback, and a stale generator
        # finalized by GC long after a new stream started cannot corrupt
        # the live rotation.  Acquired-but-unyielded windows need no ring
        # cleanup either: acquisition has no ring side effect (only
        # release() moves the counter), so a later stream re-acquires
        # exactly the same windows.  In-flight transfers on abandonment
        # are harmless — the producer cannot overwrite an unreleased
        # slot, and slot mappings outlive close().
        cursor = self._target
        # ONE live stream at a time: two concurrently-iterated streams
        # would acquire the same slot (cursor and held counts are
        # per-generator) and double-release it, silently corrupting the
        # ring counters.  Starting a new stream therefore invalidates
        # the previous one — its next iteration raises instead.
        token = object()
        self._stream_token = token

        def check_live():
            if self._stream_token is not token:
                raise LoaderStateError(
                    "this windows() stream was superseded by a newer "
                    "windows() call on the same loader; iterate one "
                    "stream at a time"
                )

        def start_one(timeout_s: float):
            """Acquire the next window at the local cursor, start its
            transfer, advance the cursor.  With ``held[target] > 0`` the
            ring's drain-lookahead primitive acquires PAST the still-held
            slot (release order stays FIFO).  Acquisition is integrity-
            verified: a corrupt head window is quarantined and replayed
            before anything is submitted downstream.  A cluster view
            change revoking the target mid-acquire rotates onto the
            published pool and retries — the cross-host ladder's
            consumer-side edge."""
            nonlocal cursor
            self._apply_pending_pool()
            cursor = self._next_target(cursor, include=True)
            target = cursor
            with annotate("ddl.window_acquire"), self.metrics.timed(
                "consumer.wait"
            ):
                while True:
                    try:
                        slot = self._acquire_verified(
                            target, held[target], timeout_s
                        )
                        break
                    except _TargetRevoked:
                        self._apply_pending_pool()
                        cursor = self._next_target(cursor, include=True)
                        target = cursor
            ring = self.connection.rings[target]
            # Window identity (the integrity trailer's (producer_idx,
            # seq)) — the key every downstream span of THIS window
            # stitches on (staging copy/transfer, H2D, ICI fan-out,
            # trainer consume, slot release).
            wkey = (target + 1, self._last_acquired_seq)
            arr = self._slot_array(target, slot)
            # Ragged tail rows (nData not a batch multiple) are unserved,
            # exactly as in batch iteration.  bpw is per-TARGET: mixed
            # window sizes yield differently-shaped windows as the
            # rotation advances.
            bpw = self._lens[target]
            served = bpw * self.batch_size
            window = arr[:served].reshape(
                bpw, self.batch_size, *self.shapes[target][1:]
            )
            # Byte accounting is deferred to finish(): counting bytes at
            # yield keeps ingest.bytes and consumer.samples covering
            # identical windows over any measurement span (dispatch leads
            # the yield by the lookahead depth).  An engine that faulted
            # (staged transfers exhausted their retry budget) is skipped:
            # the degradation ladder routes every later window straight
            # down the sanctioned inline path.
            if engine is not None and not engine.faulted:
                ingestor = self._ingestor
                # Shm-backed staging (write-once pipeline): on clients
                # whose device_put genuinely copies, the staged transfer
                # sources the slot DIRECTLY — no slot→staging memcpy —
                # and copy_done (the release edge) fires at transfer
                # completion.  The slot is held for the DMA, so the
                # early-release torn-read hazard the staged CRC re-check
                # guards does not exist on this path.
                alias = (
                    ingestor.stream_alias
                    and not engine.executor.alias_unsafe
                )
                # Post-copy re-verify (ddl_tpu.integrity): when the
                # served rows span the whole payload, the committed CRC
                # also certifies the staging copy — the executor checks
                # it after its slot→buffer memcpy, catching a producer
                # overwriting a not-yet-copied slot.
                expected_crc = None
                if not alias and self._integrity and window.nbytes == int(
                    ring.slot_payload(slot)
                ):
                    expected_crc = integrity.read_header(
                        ring.slot_view(slot), ring.slot_payload(slot)
                    ).crc
                payload = engine.submit(
                    window,
                    lambda buf: (ingestor._transfer(buf),) * 2,
                    expected_crc=expected_crc,
                    alias_src=alias,
                    span_key=wkey,
                )
            else:
                # Identity context for the nested transfer/fan-out
                # spans (put_window, IciDistributor) — they run on this
                # thread and cannot see the window key otherwise.
                obs_spans.set_window(*wkey)
                try:
                    payload = self._ingestor.put_window(
                        window, defer_metrics=True
                    )
                finally:
                    obs_spans.clear_window()
            held[target] += 1
            cursor = self._next_target(cursor)
            return [slot, target, payload, served, False, wkey]

        def release_early():
            """Staged mode: hand back the slots of every pending window
            whose staging copy has completed — in pending (FIFO) order,
            stopping at the first incomplete copy so per-ring release
            order stays FIFO.  This is what shrinks slot-hold time from
            'whole H2D transfer' to 'one host memcpy': the producer can
            refill while the transfer is still crossing the link.

            A released-but-unyielded window's data lives only in its
            staging buffer, so it is recorded on the LOADER
            (``_staged_orphans``): if this stream is abandoned, the next
            stream inherits and serves it — the break-resume contract
            survives early release."""
            for entry in pending:
                slot, target, payload, _served, released = entry[:5]
                if released:
                    continue
                if not isinstance(payload, StagedTransfer):
                    # Inline-fallback window (engine faulted mid-stream):
                    # its transfer sources the slot directly, so the slot
                    # is held until finish() — and release order is FIFO,
                    # so nothing behind it may release early either.
                    break
                if not payload.copy_done.is_set():
                    break
                self.connection.rings[target].release(slot)
                obs_spans.mark("consumer.release", *entry[5])
                held[target] -= 1
                entry[4] = True
                self._staged_orphans.append(entry)

        def finish(entry):
            slot, target, payload, served, released, wkey = entry
            if isinstance(payload, StagedTransfer):
                # Wait only for the staging copy + dispatch (the slot's
                # last reader), not the whole transfer — the device value
                # is an async future exactly like the batch path's.
                # Work-stealing: an unstarted job runs inline here.  On
                # transfer-retry exhaustion the engine salvages the
                # verified staging copy down the sanctioned inline path
                # (degradation ladder rung 2 — no loss, no duplicate;
                # `engine.faulted` routes later windows inline up front).
                def inline_put(buf):
                    dev = self._ingestor.put_window(buf, defer_metrics=True)
                    jax.block_until_ready(dev)
                    return dev

                dev = engine.complete_or_salvage(
                    payload, inline_put, self.timeout_s
                )
            else:
                dev = payload
            self.metrics.incr("ingest.bytes", float(dev.nbytes))
            self.metrics.incr("ingest.windows")
            self.metrics.incr("consumer.windows")
            self.metrics.incr("consumer.samples", served)
            self._last_stream_entry = None
            if not released:
                if not isinstance(payload, StagedTransfer) and (
                    not self._ingestor.window_source_detached()
                ):
                    # Inline on an accelerator: the transfer sources the
                    # ring slot, so the slot must outlive the DMA — but
                    # the HOST need not wait for it.  The old
                    # ``block_until_ready`` here serialized window k+1's
                    # H2D against window k's scanned optimizer steps
                    # (VERDICT r5 weak #4); release is instead deferred
                    # onto the transfer-completion probe
                    # (``_sweep_release_backlog``), forced only when the
                    # ring runs out of slots.  The entry is remembered
                    # so a fused-step consumer can re-gate it on the
                    # consuming step's done-future (gate_release_on).
                    # (Named distinctly from the enclosing ``entry``
                    # parameter — the pending-queue 5-tuple — which the
                    # staged-orphan branch below still reads.)
                    backlog_entry = [target, slot, dev, wkey]
                    self._release_backlog.append(backlog_entry)
                    self._last_stream_entry = backlog_entry
                else:
                    # Staged payload (copy+dispatch already awaited) or
                    # inline with a DETACHED source (the CPU client's
                    # alias-guard copy in ``put_window``): nothing reads
                    # the slot anymore, hand it back now.
                    self.connection.rings[target].release(slot)
                    obs_spans.mark("consumer.release", *wkey)
                    held[target] -= 1
            elif self._staged_orphans and self._staged_orphans[0] is entry:
                # Yielded after its early release: no longer an orphan.
                self._staged_orphans.pop(0)
            # This window is now SERVED: commit the rotation.
            self._target = self._next_target(target)
            self._last_window_key = wkey
            obs_spans.mark("consumer.yield", *wkey)
            return dev

        # Inherit a superseded/abandoned stream's early-released windows:
        # their slots are gone from the ring (data lives in staging
        # buffers / in flight to HBM) and they are, by FIFO construction,
        # exactly the next unserved windows in rotation order — serve
        # them first, then continue acquiring after them.
        for entry in self._staged_orphans:
            pending.append(entry)
        if pending:
            cursor = self._next_target(pending[-1][1])

        # Yield-bounded up front: the generator serves exactly the
        # epochs left, so exhausting it eagerly (e.g. list()) before
        # the marks terminates rather than streaming past the run.
        remaining = self.n_epochs - self._epoch
        for i in range(remaining):
            check_live()
            if self._finalized:
                break
            # Cross-process observability: fold any pending worker
            # ObsReports in at the window boundary (no-op in THREAD).
            self._poll_obs()
            if self._release_backlog:
                # Free completed-transfer slots (non-blocking probe)
                # before acquiring or deepening.
                self._sweep_release_backlog(held)
            if not pending:
                if (
                    held[cursor]
                    >= self.connection.rings[cursor].nslots
                ):
                    # Every slot of the head ring is either in flight or
                    # awaiting its transfer-gated release: the blocking
                    # acquire below could never be satisfied (the
                    # producer has no free slot to commit into) — wait
                    # out the OLDEST deferred transfer on that ring.
                    self._flush_release_backlog(held, target=cursor)
                pending.append(start_one(self.timeout_s))
            if engine is not None:
                # Free completed-copy slots BEFORE deepening: an early
                # release lowers held[cursor], so the same nslots admits
                # a deeper in-flight pipeline.
                release_early()
            # Deepen the pipeline up to `lookahead` extra windows, each
            # a non-blocking try: the first not-yet-committed (or
            # capacity-exhausted) window ends the deepening round.
            while (
                len(pending) <= lookahead
                and i + len(pending) < remaining
                and not self._finalized
                and held[cursor]
                < self.connection.rings[cursor].nslots
                # A full executor queue would park start_one inside
                # submit's backpressure wait — deepening is lookahead,
                # never a place to block.  A faulted engine routes
                # inline, so its queue no longer gates deepening.
                and (
                    engine is None
                    or engine.faulted
                    or engine.executor.has_capacity()
                )
            ):
                # Cheap counter peek first: a not-yet-committed window
                # must not register a wait event in the stall accounting
                # (it is lookahead, not a stall).
                if not self.connection.rings[cursor].poll_drain_ready(
                    held[cursor]
                ):
                    break
                try:
                    pending.append(start_one(0.0))
                except StallTimeoutError:
                    break  # not committed yet; wait at next iter
                except _CorruptAhead:
                    # Corrupt window discovered during lookahead: held
                    # slots forbid out-of-FIFO quarantine, so stop
                    # deepening — it re-verifies (and replays) when it
                    # reaches the head at ahead == 0.
                    break
                except NotImplementedError:
                    # Ring without drain lookahead (a custom WindowRing
                    # on the base-class fallback): degrade to strict
                    # alternation instead of dying mid-stream.
                    lookahead = 0
                    break
            yield finish(pending.popleft())

    # -- cross-process observability drain (ddl_tpu.obs) -------------------

    def _poll_obs(self) -> None:
        """Drain pending producer ObsReports (non-blocking, once per
        window boundary) and merge them into this registry under
        ``producer.<idx>.*``.  THREAD-mode channels never carry reports
        (the worker registry IS this one), so the poll is a cheap
        per-window no-op there."""
        self._drain_obs_once()

    def _obs_reports_possible(self) -> bool:
        """Could this loader's producers ship ObsReports at all?
        Cross-process channels with shipping enabled — THREAD loaders
        (in-process queues, shared registry) never wait on teardown."""
        from ddl_tpu.obs import ship_every
        from ddl_tpu.transport.connection import ThreadChannel

        return ship_every() > 0 and any(
            not isinstance(ch, ThreadChannel)
            for ch in self.connection.channels
        )

    def drain_obs_reports(
        self, timeout_s: float = 0.0, wait_for_all: bool = False
    ) -> int:
        """Drain producer ObsReports, optionally waiting up to
        ``timeout_s`` for stragglers (a PROCESS worker's FINAL report
        races teardown) — the shutdown/bench/test hook; the per-window
        poll is :meth:`_poll_obs`.  ``wait_for_all`` exits EARLY once a
        FRESH report (one applied after this call started) has arrived
        from every producer — a clean teardown pays only the real
        straggler latency, never the whole deadline; crashed producers
        never report, so the deadline stays the upper bound.  Returns
        reports applied."""
        import threading

        deadline = time.monotonic() + timeout_s
        waiter = threading.Event()
        applied = 0
        start_state = (
            self._obs_merger.fence_state()
            if self._obs_merger is not None
            else {}
        )
        targets = set(range(self.n_producers))
        while True:
            applied += self._drain_obs_once()
            if wait_for_all and self._obs_merger is not None:
                state = self._obs_merger.fence_state()
                if all(
                    t in state and state[t] != start_state.get(t)
                    for t in targets
                ):
                    return applied
            if timeout_s <= 0 or time.monotonic() >= deadline:
                return applied
            waiter.wait(0.02)

    def _drain_obs_once(self) -> int:
        # Retry due unacked control envelopes first (the acked seam,
        # ddl_tpu.transport.envelope): this drain runs once per window
        # boundary and from every teardown/straggler wait, so it is the
        # consumer's natural delivery heartbeat.
        self.connection.pump_control()
        applied = 0
        for target in range(self.n_producers):
            while True:
                msg = self.connection.try_recv_control(target)
                if msg is NOTHING:
                    break
                if isinstance(msg, ObsReport):
                    if self._obs_merger is None:
                        from ddl_tpu.obs import ReportMerger

                        self._obs_merger = ReportMerger(
                            self.metrics, obs_spans.log
                        )
                    if self._obs_merger.apply(msg):
                        applied += 1
                elif isinstance(msg, ControlAck):
                    # Producer acked an enveloped command: clear the
                    # sender's pending retry (dedup/fence verdicts land
                    # as ctrl.* counters inside the sender).
                    self.connection.note_ack(msg)
                else:
                    logger.warning(
                        "consumer: ignoring unexpected producer "
                        "message %r on channel %d",
                        type(msg).__name__, target,
                    )
        return applied

    # -- loader-pool decoupling seam (ddl_tpu.cluster) ---------------------

    def apply_pool(self, pool: Any) -> None:
        """Adopt a published :class:`~ddl_tpu.cluster.pool.LoaderPool`.

        Thread-safe entry point (called from the cluster supervisor's
        sweep thread): the pool is only RECORDED here; rotation state
        changes on the consumer thread at the next window boundary
        (``_apply_pending_pool``), and a consumer blocked on a ring the
        new pool drops is unblocked by target revocation inside the
        sliced acquire.  Stale generations (<= the applied one) are
        ignored — the epoch fence.
        """
        cur = self._pending_pool
        if cur is not None and cur.generation >= pool.generation:
            return  # a newer pool is already pending; keep the fence
        if pool.generation <= self._pool_generation:
            return  # stale relative to what was already applied
        self._pending_pool = pool

    def _apply_pending_pool(self) -> None:
        """Consumer-thread half of :meth:`apply_pool`."""
        pool = self._pending_pool
        if pool is None:
            return
        self._pending_pool = None
        if pool.generation <= self._pool_generation:
            return  # stale fence: view N must never undo view N+1
        from ddl_tpu.cluster.pool import LoaderPool

        members = tuple(
            m for m in pool.members if 0 <= m < self.n_producers
        )
        if not members:
            raise LoaderStateError(
                "loader pool update left no local ring targets "
                f"(pool={pool.members}, rings={self.n_producers})"
            )
        self._pool = LoaderPool(members=members, generation=pool.generation)
        self._pool_generation = pool.generation
        self.metrics.incr("consumer.pool_updates")
        self.metrics.set_gauge("consumer.pool_size", len(members))
        if self._target not in self._pool:
            # The current target's host left: drop any partially-served
            # window (its remaining batches are re-partitioned to the
            # survivors by shard adoption) and rotate onto the pool.
            self._batches_in_window = 0
            self._release_current()
            self._target = self._next_target(self._target)

    def gate_release_on(self, done: Any) -> None:
        """Fused-step protocol: gate the most recently yielded stream
        window's deferred slot release on the CONSUMING step's
        done-future, not the bare transfer.

        ``done`` is any device value (or pytree of them) produced by
        the step that consumed the window — e.g. the scanned
        multistep's per-step losses.  The window's backlog entry grows
        the future as an ADDITIONAL release condition: the
        non-blocking sweep (``_sweep_release_backlog``) then frees the
        slot only once both the transfer AND the consuming step have
        completed, which is the two-slot ring discipline — the
        producer may overwrite a slot only when the step that read its
        window is done, so a re-fill can never race a still-running
        scan's device reads (on clients that alias host pages the
        transfer-done edge alone is not that guarantee).

        No-op when the window's slot was already released at yield
        (staged early release, or a detached CPU-client source): gating
        is only ever an extra condition on an entry that exists, so a
        consumer that never calls this keeps the plain transfer-probe
        behavior, and the protocol cannot deadlock — the blocking flush
        paths ``block_until_ready`` the combined future, and the step
        completes independently of any slot.  One window at a time: the
        gate applies to the LAST yielded window and is consumed by the
        call (the fused trainer loop calls it once per step dispatch).
        """
        entry = self._last_stream_entry
        self._last_stream_entry = None
        if entry is None:
            return
        for e in self._release_backlog:
            if e is entry:
                # Tuple pytree: both the transfer value and the step
                # future must probe ready before the sweep releases.
                e[2] = (e[2], done)
                self.metrics.incr("ingest.fused_gated")
                return

    def last_window_key(self) -> Any:
        """Identity ``(producer_idx, seq)`` of the most recently yielded
        stream window — the trainer's consume spans key on it
        (``ddl_tpu.obs``).  None before the first yield."""
        return self._last_window_key

    def bind_admission(self, admission: Any) -> None:
        """Attach a multi-tenant admission gate (``ddl_tpu.serve``).

        ``admission`` speaks the two-method protocol of
        :class:`~ddl_tpu.serve.tenancy.Tenant`: ``admit(timeout_s)``
        blocks (deadline-bounded) until the fair-share scheduler grants
        this tenant its next window — raising
        :class:`~ddl_tpu.exceptions.StallTimeoutError` on a
        non-blocking probe (``timeout_s <= 0``, the lookahead-deepening
        path) exactly like a not-yet-committed window — and
        ``note_served(nbytes)`` charges the acquired window's bytes
        against the tenant's share and budgets.  The hook lives in
        ``_acquire_verified``, the one choke point every window
        acquisition (batch, stream, lookahead, replay) already passes
        through, so tenancy cannot be bypassed by any iteration style —
        the same bypass-proof property the pool seam's
        :meth:`~ddl_tpu.cluster.pool.LoaderPool.next_member` rotation
        rule has.  ``None`` unbinds.
        """
        self._admission = admission

    def _next_target(self, t: int, include: bool = False) -> int:
        """The next ACTIVE ring target cyclically after ``t`` (or ``t``
        itself when ``include`` and it is active) — all rotation goes
        through here, delegating to the applied pool's
        :meth:`~ddl_tpu.cluster.pool.LoaderPool.next_member` (ONE
        implementation of the rotation rule), so the pool seam has one
        bypass-proof gate."""
        if self._pool is None:
            return t % self.n_producers if include else (
                (t + 1) % self.n_producers
            )
        return self._pool.next_member(t, include=include)

    def _target_revoked(self, target: int) -> bool:
        """True when ``target`` is outside the active pool or about to
        be dropped by a pending one — the sliced acquire polls this so
        a consumer blocked on a dead host's ring unblocks at the view
        change instead of its full timeout."""
        if self._pool is not None and target not in self._pool:
            return True
        pool = self._pending_pool
        return (
            pool is not None
            and pool.generation > self._pool_generation
            and target not in pool
        )

    # -- progress marks ------------------------------------------------------

    def mark(self, marker: Marker) -> None:
        """Report progress (reference ``mpi_dataloader.py:236-241``)."""
        if marker is Marker.END_OF_BATCH:
            self._on_batch_end()
        elif marker is Marker.END_OF_EPOCH:
            self._on_epoch_end()
        else:
            raise ValueError(f"unknown marker {marker!r}")

    def _on_batch_end(self) -> None:
        self._batches_in_window += 1
        if self._batches_in_window >= self._lens[self._target]:
            self._batches_in_window = 0
            self._release_current()
            self._advance_to_next_producer()
            # Next window is acquired lazily by the next __getitem__.

    def _on_epoch_end(self) -> None:
        self._served_in_epoch = 0
        if self._batches_in_window:
            # Epoch ended mid-window (user broke out early): discard the
            # partially consumed window so the next epoch starts on a fresh
            # window boundary instead of silently re-serving stale batches.
            self._batches_in_window = 0
            self._release_current()
            self._advance_to_next_producer()
        self._epoch += 1
        if self._epoch >= self.n_epochs:
            self.shutdown()

    # -- window rotation (reference mpi_dataloader.py:200-234) -------------

    def _ring(self):
        return self.connection.rings[self._target]

    def _advance_to_next_producer(self) -> None:
        self._apply_pending_pool()
        self._target = self._next_target(self._target)

    def _slot_array(self, target: int, slot: int) -> np.ndarray:
        """Window array of an acquired slot, shaped for ``target``.

        Raw producers: a zero-copy view of the slot payload.  Wire-
        encoded producers (``ddl_tpu.wire``): the slot holds the
        bf16/int8 payload + trailer scales; this is the CONSUMER EDGE
        decode — a fresh array per acquire (never a shared scratch:
        lookahead holds several of one target's windows live at once),
        after which nothing downstream reads the slot.  A decode
        failure (the ``wire.decode`` chaos site's ``DECODE_FAIL``, or
        real bit rot the CRC somehow missed) retries once, then
        escalates to :class:`IntegrityError` — by then the bytes are
        provably undecodable, the same terminal rung a persistent
        backend failure reaches.
        """
        ring = self.connection.rings[target]
        nbytes = ring.slot_payload(slot)
        if self._wire_dtypes[target] == "raw":
            return (
                ring.slot_view(slot)[:nbytes]
                .view(self.dtypes[target])
                .reshape(self.shapes[target])
            )
        from ddl_tpu import wire
        from ddl_tpu.exceptions import DecodeError
        from ddl_tpu.faults import fault_point

        view = ring.slot_view(slot)
        hdr = integrity.read_header(view, nbytes)
        scales = (
            integrity.read_scales(view, nbytes, hdr.scale_bytes)
            if hdr.scale_bytes
            else None
        )
        _span_t0 = obs_spans.t0()
        for attempt in (1, 2):
            try:
                fault_point("wire.decode", view=view[:nbytes])
                dec = wire.decode_window(
                    np.array(view[:nbytes]), scales,
                    self.shapes[target], self.dtypes[target],
                    hdr.wire_dtype,
                )
                break
            except DecodeError as e:
                self.metrics.incr("wire.decode_fails")
                if attempt == 2:
                    flight_dump(
                        "wire.undecodable",
                        producer_idx=target + 1, seq=hdr.seq,
                        metrics=self.metrics,
                        extra={"wire_dtype": hdr.wire_dtype},
                    )
                    raise IntegrityError(
                        f"window from producer {target + 1} undecodable "
                        f"after retry ({hdr.wire_dtype} wire): {e}"
                    ) from e
        obs_spans.record("wire.decode", target + 1, hdr.seq, _span_t0)
        self.metrics.incr("wire.decoded_windows")
        # The wire accounting pair (encoded bytes that traveled the
        # slot vs the logical raw bytes served) — counted HERE, the one
        # registry every run mode shares.
        self.metrics.incr(
            "wire.encoded_bytes", float(nbytes + hdr.scale_bytes)
        )
        self.metrics.incr("wire.payload_bytes", float(dec.nbytes))
        return dec

    # -- deferred (transfer-gated) slot release ----------------------------

    def _sweep_release_backlog(self, held=None) -> None:
        """Release yielded inline-stream windows whose transfers have
        COMPLETED (non-blocking ``is_ready`` probe), in per-ring FIFO
        order — a not-yet-ready transfer blocks only later entries of
        the same ring.  ``held`` (the live stream's per-target hold
        counter) is decremented alongside each release."""
        blocked: set = set()
        remaining = []
        for entry in self._release_backlog:
            target, slot, dev = entry[:3]
            if target not in blocked and _transfer_ready(dev):
                self.connection.rings[target].release(slot)
                if len(entry) > 3:
                    obs_spans.mark("consumer.release", *entry[3])
                if held is not None:
                    held[target] -= 1
            else:
                blocked.add(target)
                remaining.append(entry)
        self._release_backlog = remaining

    def _flush_release_backlog(self, held=None, target=None) -> None:
        """BLOCKING release of backlog entries: all of them (stream
        teardown / path switches), or only the oldest entry of
        ``target`` (a ring out of free slots).  The wait is the
        transfer completing — accounted as ``ingest.release_wait`` so
        a stream losing its overlap shows up in the north-star report
        instead of hiding inside opaque wall time."""
        import jax

        remaining = []
        done = False
        for entry in self._release_backlog:
            t, slot, dev = entry[:3]
            if done or (target is not None and t != target):
                remaining.append(entry)
                continue
            with self.metrics.timed("ingest.release_wait"):
                jax.block_until_ready(dev)
            self.connection.rings[t].release(slot)
            if len(entry) > 3:
                obs_spans.mark("consumer.release", *entry[3])
            if held is not None:
                held[t] -= 1
            if target is not None:
                done = True
        self._release_backlog = remaining

    # -- end-to-end integrity (ddl_tpu.integrity) --------------------------

    def _expected_seq(self, target: int, ahead: int) -> int:
        """Logical window number of the slot ``acquire_drain_ahead(ahead)``
        returns on ``target``: released count plus lookahead, minus the
        commits discarded by past quarantine replays — offset into this
        job's integrity namespace (``seq_base``)."""
        ring = self.connection.rings[target]
        return (
            self._seq_base
            + int(ring.stats()["released"]) + ahead
            - self._seq_skew[target]
        )

    def _verify_slot(
        self, target: int, slot: int, expect_seq: int
    ) -> Optional[str]:
        """Drain-time header check; None when the window is intact."""
        ring = self.connection.rings[target]
        return integrity.verify_window(
            ring.slot_view(slot),
            ring.slot_payload(slot),
            expect_seq=expect_seq,
            expect_producer=target + 1,
        )

    def _acquire_verified(self, target: int, ahead: int, timeout_s: float):
        """Acquire the next committed slot on ``target`` and verify its
        integrity header — behind the fair-share admission gate when a
        tenant is bound (``bind_admission``).

        Admission runs FIRST (ddl_tpu.serve): no ring wait may start
        before the tenant's turn is granted — otherwise a slot could be
        held hostage while the scheduler throttles the holder.
        Non-blocking probes (``timeout_s <= 0``) raise
        :class:`StallTimeoutError` when not grantable, which the
        lookahead deepening treats as "not committed yet".  The
        admission wait SPENDS FROM the same budget the ring acquire
        gets: one acquisition, one ``timeout_s`` — a throttled tenant
        must not silently double the documented stall budget.  A grant
        whose ring acquire then FAILS (stall timeout, revoked target,
        shutdown) is released via ``note_aborted`` — a leaked in-flight
        grant would make every later ``revoke_inflight`` burn its full
        SLO on a phantom window.
        """
        if self._admission is None:
            return self._acquire_with_spans(target, ahead, timeout_s)
        t_admit = time.monotonic()
        _span_t0 = obs_spans.t0()
        self._admission.admit(timeout_s)
        admit_wait = time.monotonic() - t_admit
        if timeout_s > 0:
            timeout_s = max(0.0, timeout_s - admit_wait)
        try:
            slot = self._acquire_with_spans(target, ahead, timeout_s)
        except BaseException:
            abort = getattr(self._admission, "note_aborted", None)
            if abort is not None:
                abort()
            raise
        # Admission observability: the span is keyed on the window the
        # grant actually bought (seq known only post-acquire), and the
        # wait lands in the bounded consumer.admission_wait histogram —
        # the first-class home of the p99 the tenancy bench previously
        # computed ad hoc (per-tenant histograms ride
        # ingest.<tenant>.admission_wait in ddl_tpu.serve).
        obs_spans.record(
            "consumer.admission", target + 1, self._last_acquired_seq,
            _span_t0, _span_t0 + admit_wait if _span_t0 else None,
        )
        self.metrics.observe("consumer.admission_wait", admit_wait)
        # The charge-after half of the fair-share gate: the window's
        # actual byte size is only known post-acquire.
        self._admission.note_served(
            int(self.connection.rings[target].slot_payload(slot))
        )
        return slot

    def _acquire_with_spans(
        self, target: int, ahead: int, timeout_s: float
    ):
        """The acquire choke point's observability shim: spans the
        verified acquire, stashes the logical seq for downstream keying
        (staging jobs, yields, releases), and feeds the bounded
        ``consumer.window_latency`` histogram — head acquires only, so
        the percentile measures "time to obtain the next committed
        window" and non-blocking lookahead probes cannot dilute it."""
        _span_t0 = obs_spans.t0()
        t0 = time.perf_counter() if ahead == 0 and timeout_s > 0 else 0.0
        slot = self._acquire_slot_verified(target, ahead, timeout_s)
        # The logical window number, by the same arithmetic the
        # integrity verify pins (valid with integrity off too: the skew
        # term is only ever advanced by quarantine replays).
        seq = self._expected_seq(target, ahead)
        self._last_acquired_seq = seq
        if t0:
            self.metrics.observe(
                "consumer.window_latency", time.perf_counter() - t0
            )
        obs_spans.record("consumer.acquire", target + 1, seq, _span_t0)
        return slot

    def _acquire_slot_verified(
        self, target: int, ahead: int, timeout_s: float
    ):
        """The admission-free acquire: next committed slot on
        ``target``, integrity-verified.  A corrupt head slot (``ahead
        == 0``) enters quarantine-and-replay; corruption discovered
        during lookahead deepening (``ahead > 0``) raises
        :class:`_CorruptAhead` — held slots make out-of-FIFO quarantine
        impossible, so the caller stops deepening and the window
        re-verifies when it reaches the head."""
        ring = self.connection.rings[target]
        pool_managed = (
            self._cluster is not None
            or self._pool is not None
            or self._pending_pool is not None
        )
        if not pool_managed:
            slot = (
                ring.acquire_drain_ahead(ahead, timeout_s)
                if ahead
                else ring.acquire_drain(timeout_s)
            )
        else:
            # Cluster-attached acquire (head AND lookahead): sliced so
            # a view change that drops THIS target mid-wait revokes the
            # acquire promptly (the dead host's producer will never
            # commit again; waiting out the full timeout would stall
            # recovery by minutes).  A shut-down ring below a pending
            # view change is the same revocation, not run teardown.
            deadline = time.monotonic() + timeout_s
            while True:
                if self._target_revoked(target):
                    raise _TargetRevoked(target)
                try:
                    remaining = min(
                        0.25, max(0.0, deadline - time.monotonic())
                    )
                    slot = (
                        ring.acquire_drain_ahead(ahead, remaining)
                        if ahead
                        else ring.acquire_drain(remaining)
                    )
                    break
                except StallTimeoutError:
                    if time.monotonic() >= deadline:
                        raise
                except ShutdownRequested:
                    if self._target_revoked(target):
                        raise _TargetRevoked(target)
                    raise
        if self._integrity:
            expect = self._expected_seq(target, ahead)
            err = self._verify_slot(target, slot, expect)
            if err is not None:
                if ahead or timeout_s <= 0:
                    # Deferred, NOT counted yet: held slots forbid
                    # out-of-FIFO quarantine, and a non-blocking
                    # deepening probe (timeout_s == 0) must not run a
                    # replay wait under a zero-second budget — either
                    # way the same corrupt window re-verifies when a
                    # BLOCKING head acquire reaches it, which is where
                    # it is counted once and replayed under the
                    # loader's real timeout.
                    raise _CorruptAhead(err)
                self.metrics.incr("integrity.corrupt_windows")
                # Post-mortem artifact (ddl_tpu.obs): the corrupt
                # window is THE event a chaos row or chip-run anomaly
                # needs explained — dump the flight ring naming the
                # faulted window's trailer identity (no-op disarmed).
                flight_dump(
                    "integrity.corrupt_window",
                    producer_idx=target + 1, seq=expect,
                    metrics=self.metrics, extra={"verify_error": err},
                )
                slot = self._quarantine_and_replay(
                    target, expect, err, timeout_s
                )
        return slot

    def _quarantine_and_replay(
        self, target: int, seq: int, err: str, timeout_s: float
    ) -> int:
        """The corrupt-slot recovery ladder (docs/ROBUSTNESS.md).

        The head slot of ``target`` failed verification as logical
        window ``seq``.  Re-request ``seq`` from the producer (which
        rewinds via the deterministic-replay contract), discard the
        quarantined slot plus any stale in-flight successors, and serve
        the re-committed window — byte-identical, exactly once.  Rungs:

        1. up to ``DDL_TPU_MAX_REPLAYS`` replay attempts per window;
        2. cross-instance exchange active → no local replay is possible
           → :class:`IntegrityError` immediately;
        3. budget exhausted (persistent corruption) → IntegrityError.

        The caller's acquired head slot is owned by this method from
        entry: every discard releases it and acquires the next commit.
        """
        ring = self.connection.rings[target]
        for attempt in range(1, self._max_replays + 1):
            if self._shuffle_fraction > 0.0:
                raise IntegrityError(
                    f"corrupt window {seq} from producer {target + 1} "
                    f"({err}); not replayable: cross-instance exchange "
                    "contributed rows no local rewind can regenerate"
                )
            logger.error(
                "ddl_tpu: corrupt window %d from producer %d (%s) — "
                "quarantined; replay attempt %d/%d",
                seq, target + 1, err, attempt, self._max_replays,
            )
            self.metrics.incr("integrity.replays")
            self.connection.request_replay(target, seq)
            deadline = time.monotonic() + max(timeout_s, 1.0)
            last_request = time.monotonic()
            reattempt = False
            while not reattempt:
                # Discard the head (quarantined or stale) and take the
                # next commit; the producer is re-committing seq, seq+1,
                # ... behind us, so this loop is bounded by the in-flight
                # depth plus one replayed window.
                ring.release(int(ring.stats()["released"]) % ring.nslots)
                self._seq_skew[target] += 1
                while True:
                    now = time.monotonic()
                    if now >= deadline:
                        raise IntegrityError(
                            f"replayed window {seq} from producer "
                            f"{target + 1} never arrived within "
                            f"{timeout_s}s"
                        )
                    if now - last_request >= 2.0:
                        # Re-send periodically: the original request is
                        # LOST if the producer died (or was respawned —
                        # fresh channel) before reading it; requests are
                        # idempotent rewinds, and a respawned replacement
                        # polls its new channel like any incarnation.
                        # Rides the acked seam (request_replay wraps in
                        # an envelope), so a merely-DROPPED wire attempt
                        # is retried by pump below long before this
                        # coarse 2s incarnation-loss backstop fires.
                        self.connection.request_replay(target, seq)
                        last_request = now
                    self.connection.pump_control(now)
                    try:
                        slot = ring.acquire_drain(
                            min(2.0, deadline - now)
                        )
                        break
                    except StallTimeoutError:
                        continue  # wake to re-send, then wait again
                hdr = integrity.read_header(
                    ring.slot_view(slot), ring.slot_payload(slot)
                )
                if not hdr.valid_magic or hdr.seq != seq:
                    continue  # stale in-flight successor: discard too
                err = self._verify_slot(target, slot, seq)
                if err is None:
                    # The replayed commit is served (and later released)
                    # through the normal path — skew already counts
                    # exactly the discarded commits before it.
                    logger.warning(
                        "ddl_tpu: window %d from producer %d recovered "
                        "by replay", seq, target + 1,
                    )
                    return slot
                # Replayed copy is corrupt AGAIN: burn a replay attempt.
                self.metrics.incr("integrity.corrupt_windows")
                reattempt = True
        flight_dump(
            "integrity.replay_exhausted",
            producer_idx=target + 1, seq=seq,
            metrics=self.metrics, extra={"verify_error": err},
        )
        raise IntegrityError(
            f"window {seq} from producer {target + 1} still corrupt "
            f"after {self._max_replays} replay(s): {err}"
        )

    def _acquire_current(self) -> None:
        from ddl_tpu.profiling import annotate

        if self._release_backlog:
            # Batch-path acquire tracks no per-stream hold counter, so a
            # stream's deferred releases must land first — otherwise the
            # drain-head acquire below would re-serve their slots.
            self._flush_release_backlog()
        if self._staged_orphans:
            # The next unserved windows live in staging buffers (an
            # abandoned staged stream released their slots early); the
            # batch path serves host slot views and cannot reach them.
            raise LoaderStateError(
                "an abandoned windows() stream left staged windows in "
                "flight; drain them with a new windows() stream before "
                "batch iteration"
            )
        # The annotation makes window-wait stalls visible on the profiler
        # timeline next to the XLA ops (SURVEY §5.1 TPU-native tracing).
        self._apply_pending_pool()
        self._poll_obs()
        with annotate("ddl.window_acquire"), self.metrics.timed(
            "consumer.wait"
        ):
            while True:
                try:
                    slot = self._acquire_verified(
                        self._target, 0, self.timeout_s
                    )
                    break
                except _TargetRevoked:
                    # The target's host left the view mid-acquire:
                    # adopt the published pool and retry on a survivor.
                    self._apply_pending_pool()
                    self._target = self._next_target(
                        self._target, include=True
                    )
        self._cur_slot = slot
        self._cur_array = self._slot_array(self._target, slot)
        self.metrics.incr("consumer.windows")

    def fast_forward(self, n_windows: int) -> None:
        """Discard ``n_windows`` windows without serving them (resume
        support): producers regenerate their window sequence
        deterministically from their seeds, so skipping the windows the
        pre-checkpoint run consumed puts the pipeline at the exact data
        position where it stopped (one window per epoch — Q7 semantics)."""
        if self._release_backlog:
            self._flush_release_backlog()
        # Resume replay is bookkeeping, not service: the discarded
        # windows are never delivered to the tenant, so they must not
        # pass (or be charged at) the fair-share admission gate — a
        # byte-budgeted tenant would otherwise spend ~history/budget
        # wall time (and its counters) replaying windows it never sees.
        admission, self._admission = self._admission, None
        try:
            self._fast_forward_unadmitted(n_windows)
        finally:
            self._admission = admission

    def _fast_forward_unadmitted(self, n_windows: int) -> None:
        for _ in range(n_windows):
            if self._staged_orphans:
                # Early-released staged window: already off the ring;
                # discarding it is dropping the handle.
                self._staged_orphans.pop(0)
                self._advance_to_next_producer()
                self.metrics.incr("consumer.windows_skipped")
                continue
            self._acquire_current()
            self._release_current()
            self._advance_to_next_producer()
            self.metrics.incr("consumer.windows_skipped")

    def _release_current(self) -> None:
        if self._cur_slot is not None:
            if self._ingestor is not None and self._ingestor._engine is not None:
                # Slot-safety barrier: a staged prefetch may still hold
                # queued jobs whose sources VIEW this window (a mid-epoch
                # break abandons lookahead batches before their copies
                # ran).  Their staging copies must land before the
                # producer may overwrite the slot.  O(1) when all copies
                # already completed — the steady-state case.
                self._ingestor._engine.executor.flush_copies()
            self._ring().release(self._cur_slot)
            self._cur_slot = None
            self._cur_array = None

    # -- shutdown (reference mpi_dataloader.py:229-234, §3.5) --------------

    def shutdown(self) -> None:
        if self._finalized:
            return
        self._finalized = True
        # Deferred stream releases first: their transfers must complete
        # (and their slots return) before the rings go away.
        self._flush_release_backlog()
        self._release_current()
        if self._ingestor is not None:
            # Stop the staging executor BEFORE the rings go away: pending
            # jobs error with ShutdownRequested instead of racing teardown,
            # and completed staging buffers flush back to their pool.
            self._ingestor.close()
        self.connection.shutdown_operation()
        # Final observability drain: PROCESS workers ship a last
        # cumulative ObsReport on their way out — give stragglers a
        # short bounded window before the channels close, exiting
        # early once every producer's final report landed (a run
        # SHORTER than the periodic ship cadence has its whole
        # aggregation riding on exactly this drain, so the gate is
        # "could reports exist at all", not "did one arrive already";
        # a crashed worker never ships and the deadline bounds it).
        if self._obs_reports_possible():
            self.drain_obs_reports(timeout_s=0.5, wait_for_all=True)
        else:
            self._drain_obs_once()
        self.connection.finalize()
        logger.debug("consumer: shutdown complete after epoch %d", self._epoch)

    def __del__(self) -> None:  # pragma: no cover - best effort
        try:
            self.shutdown()
        except ShutdownRequested:
            # Raced a concurrent teardown: the shutdown flag is already
            # set, which is all this finalizer wanted.  Handled BY NAME
            # (DDL007) rather than re-raised — PEP 442 means nothing can
            # propagate out of a finalizer anyway; an accidental broad
            # swallow and a deliberate no-op must not look alike.
            pass
        except Exception:
            # GC-time shutdown may run after interpreter state this
            # loader depends on is already gone; anything else is
            # best-effort by construction.
            pass


def _split_columns(
    batch: np.ndarray, splits: Sequence[int]
) -> Tuple[np.ndarray, ...]:
    """Split a (B, sum(splits)) window slice into column views.

    The analog of ``torch.split(..., dim=1)`` in the reference consumer
    (``mpi_dataloader.py:195-197``) — plain numpy slicing, still zero-copy.
    """
    out: List[np.ndarray] = []
    off = 0
    for w in splits:
        out.append(batch[:, off : off + w])
        off += w
    return tuple(out)
