"""ddl_tpu.serve — multi-tenant ingest service over the elastic cluster.

The service control plane that turns ``ddl_tpu.cluster``'s mechanism
(resizable loader pool, epoch-fenced views, ``rejoin_host``) into a
shared, demand-scaled ingest fabric (docs/SERVING.md):

- **tenancy** — N independent loader jobs register as tenants against
  one producer pool and one shard-cache tier; a deficit-round-robin
  fair-share scheduler with per-tenant byte/slot budgets arbitrates
  every window acquisition at the ring-acquire seam
  (:class:`AdmissionController`, :class:`FairShareScheduler`,
  :class:`TenantSpec`).
- **autoscaler** — a DDL018-compliant policy loop reading the stall-
  fraction / queue-depth demand signals, scaling the loader pool up
  (``rejoin_host`` of standby hosts) and down (drain-then-release)
  with hysteresis, cooldown, and a never-empty floor — re-running
  ``plan_placement`` on every resize (:class:`Autoscaler`,
  :class:`AutoscalerPolicy`).
- **fabric** — the cross-host shape: ONE authoritative scheduler +
  job registry resident beside the journaled supervisor, driven over
  acked control envelopes, decisions journaled so admission order
  survives supervisor failover bit-exact (:class:`IngestFabric`,
  :class:`FabricClient`, :class:`FabricJob`); **jobs** — the job
  model and per-job isolation seams: integrity namespaces, checkpoint
  cursors, obs/cache accounting (:class:`JobSpec`,
  :class:`JobRegistry`, :class:`JobCacheView`).
"""

from ddl_tpu.serve.autoscaler import Autoscaler, AutoscalerPolicy
from ddl_tpu.serve.fabric import FabricClient, FabricJob, IngestFabric
from ddl_tpu.serve.jobs import (
    JobCacheView,
    JobRecord,
    JobRegistry,
    JobSpec,
    integrity_namespace,
)
from ddl_tpu.serve.tenancy import (
    AdmissionController,
    FairShareScheduler,
    Tenant,
    TenantSpec,
)

__all__ = [
    "AdmissionController",
    "Autoscaler",
    "AutoscalerPolicy",
    "FabricClient",
    "FabricJob",
    "FairShareScheduler",
    "IngestFabric",
    "JobCacheView",
    "JobRecord",
    "JobRegistry",
    "JobSpec",
    "Tenant",
    "TenantSpec",
    "integrity_namespace",
]
