"""Cross-host multi-job ingest fabric: admission as a supervisor service.

PR 9–14 built multi-tenant admission as THREADS inside one consumer
process (:mod:`ddl_tpu.serve.tenancy`); the production shape is MPMD
role disaggregation — K independent training jobs on separate hosts
drawing from one shared, elastically-scaled loader fleet (ROADMAP item
1; arXiv:2412.14374, arXiv:2105.14088).  This module lifts the
admission authority into the supervisor tier:

- **One authoritative scheduler.**  :class:`IngestFabric` owns THE
  :class:`~ddl_tpu.serve.tenancy.FairShareScheduler` and the
  :class:`~ddl_tpu.serve.jobs.JobRegistry`, resident beside the
  :class:`~ddl_tpu.cluster.supervision.JournaledSupervisor` (they share
  a journal).  Jobs never touch the scheduler directly — ddl-lint
  DDL026 bans it — they speak the admission protocol over the control
  plane.
- **Admission over acked envelopes.**  Every command (``admit`` /
  ``note_served`` / ``note_aborted`` / register / revoke / crash) rides
  the PR-18 seam: the client's :class:`~ddl_tpu.transport.envelope.
  ControlSender` wraps it in a fenced ``(incarnation, seq)`` envelope,
  retries drops under backoff, and the fabric's per-client
  :class:`~ddl_tpu.transport.envelope.EnvelopeReceiver` dedups
  re-deliveries — with the applied set **journal-seeded**, so a
  duplicate arriving after a supervisor failover is still recognized
  and answered from the journaled reply instead of re-mutating the
  ledger (exactly-once across the failover boundary).
- **Journaled decisions.**  Every applied decision appends a
  ``job_admission`` record (client, incarnation, seq, op, reply) and,
  on the ``DDL_TPU_FABRIC_SNAPSHOT_EVERY`` cadence, a full scheduler
  snapshot; registry mutations snapshot the registry.  A promoted
  standby rebuilds via :meth:`IngestFabric.from_journal` and continues
  granting in an order bit-identical to what the dead leader would
  have produced (the property ``tests/test_fabric.py`` pins and the
  ``DDL_BENCH_MODE=fabric`` supervisor-kill leg measures).

Transport: this PR ships the **loopback** channel — clients call the
fabric in-process (same-host supervisor, or tests/bench), with the full
envelope discipline (drops, dups, fencing, retry exhaustion) live on
the path.  A socket adapter is the remaining step for true cross-host
deployment and changes no protocol above ``raw_send`` —
docs/SERVING.md states the limits honestly.

Chaos: ``serve.fabric.admit`` fires once per admission WIRE attempt
(``JOB_ADMISSION_DROP`` loses it; retry + journal-seeded dedup keep the
ledger exactly-once) and ``serve.fabric.grant`` fires between a granted
admit and its ``note_served`` (``JOB_CRASH`` kills the job mid-grant;
the fabric revokes its in-flight windows, releases its budget, and its
neighbours stay byte-correct).
"""

from __future__ import annotations

import dataclasses
import logging

from ddl_tpu.concurrency import named_lock
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ddl_tpu import envspec
from ddl_tpu.exceptions import (
    AdmissionDropped,
    DDLError,
    JobCrashed,
    StallTimeoutError,
    WindowsRevoked,
)
from ddl_tpu.faults import fault_point, FaultKind
from ddl_tpu.observability import Metrics, metrics as default_metrics
from ddl_tpu.serve.jobs import JobRegistry, JobSpec
from ddl_tpu.serve.tenancy import FairShareScheduler
from ddl_tpu.transport.envelope import ControlSender, EnvelopeReceiver
from ddl_tpu.types import ControlAck, ControlEnvelope

logger = logging.getLogger("ddl_tpu")

#: Journal record kinds (ddl_tpu.cluster.supervision replays both).
KIND_ADMISSION = "job_admission"
KIND_JOBS = "job_registry"

#: Reply cache bound: newest entries win (a client retry storm never
#: spans thousands of outstanding commands — the envelope WINDOW bound).
REPLY_WINDOW = 8192


# -- the admission protocol (ControlEnvelope payloads) ----------------------


@dataclasses.dataclass(frozen=True)
class RegisterJob:
    spec: dict


@dataclasses.dataclass(frozen=True)
class UnregisterJob:
    job_id: str


@dataclasses.dataclass(frozen=True)
class AdmitRequest:
    job_id: str
    timeout_s: float
    #: Registration index, for fault-site selection on the wire.
    index: int = 0


@dataclasses.dataclass(frozen=True)
class ServedNote:
    job_id: str
    nbytes: int


@dataclasses.dataclass(frozen=True)
class AbortNote:
    job_id: str


@dataclasses.dataclass(frozen=True)
class RevokeJobs:
    slo_s: float
    job_ids: Optional[tuple] = None


@dataclasses.dataclass(frozen=True)
class ClearRevocations:
    job_ids: Optional[tuple] = None


@dataclasses.dataclass(frozen=True)
class CrashNote:
    job_id: str


@dataclasses.dataclass
class FabricReply:
    """One command's outcome, JSON-round-trippable (it is journaled
    with the decision and re-served to post-failover duplicates)."""

    ok: bool
    error: Optional[str] = None
    #: Typed-error discriminator the client re-raises from:
    #: stall_timeout | revoked | fenced | error.
    error_type: Optional[str] = None
    value: Any = None

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "error": self.error,
            "error_type": self.error_type,
            "value": self.value,
        }


_OPS = {
    RegisterJob: "register",
    UnregisterJob: "unregister",
    AdmitRequest: "admit",
    ServedNote: "served",
    AbortNote: "aborted",
    RevokeJobs: "revoke",
    ClearRevocations: "clear_revocations",
    CrashNote: "crash",
}


# -- the supervisor-resident authority --------------------------------------


class IngestFabric:
    """THE admission authority: one scheduler + one job registry,
    resident in the supervisor tier, driven exclusively through applied
    control commands.

    ``journal`` is a :class:`~ddl_tpu.cluster.supervision.
    SupervisorJournal` (or its path) — pass the JournaledSupervisor's
    own journal so admission records interleave with view changes in
    ONE durable history.  ``None`` runs unjournaled (unit tests).
    """

    def __init__(
        self,
        journal: Any = None,
        scheduler: Optional[FairShareScheduler] = None,
        registry: Optional[JobRegistry] = None,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
        term: int = 0,
        snapshot_every: Optional[int] = None,
    ):
        self.metrics = metrics or default_metrics()
        self._clock = clock
        self.scheduler = scheduler or FairShareScheduler(
            quantum_bytes=int(envspec.get("DDL_TPU_FABRIC_QUANTUM_BYTES")),
            metrics=self.metrics,
            clock=clock,
        )
        self.registry = registry or JobRegistry(metrics=self.metrics)
        if isinstance(journal, str):
            from ddl_tpu.cluster.supervision import SupervisorJournal

            journal = SupervisorJournal(journal)
        self.journal = journal
        #: Fencing term this authority answers under (the promoted
        #: standby's term; envelopes below it are zombie commands).
        self.term = int(term)
        self.snapshot_every = (
            int(envspec.get("DDL_TPU_FABRIC_SNAPSHOT_EVERY"))
            if snapshot_every is None else int(snapshot_every)
        )
        self._lock = named_lock("serve.fabric")
        # client_id -> receiver; bounded by the connected client set.
        self._receivers: Dict[str, EnvelopeReceiver] = {}  # ddl-lint: disable=DDL013
        # (client, incarnation, seq) -> reply; trimmed to REPLY_WINDOW.
        self._replies: Dict[tuple, FabricReply] = {}  # ddl-lint: disable=DDL013
        self._decisions = 0
        #: Successful grants in decision order — the admission-order
        #: audit the failover property compares bit-exact.
        self.admission_log: List[str] = []

    # -- rebuild after failover (the promoted standby's half) --------------

    @classmethod
    def from_journal(
        cls,
        journal: Any,
        term: Optional[int] = None,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
        snapshot_every: Optional[int] = None,
    ) -> "IngestFabric":
        """Replay the journal and stand up the successor authority:
        registry + scheduler ledgers adopted from the newest snapshots,
        dedup seams and reply cache seeded from the decision records
        (exactly-once across the failover boundary), fencing term
        bumped past every journaled promotion."""
        from ddl_tpu.cluster.supervision import replay_journal

        replayed = replay_journal(journal)
        fab = cls(
            journal=journal,
            metrics=metrics,
            clock=clock,
            term=(replayed.term + 1) if term is None else int(term),
            snapshot_every=snapshot_every,
        )
        if replayed.job_registry is not None:
            fab.registry.adopt_state(replayed.job_registry)
        if replayed.scheduler_state is not None:
            fab.scheduler.adopt_state(
                replayed.scheduler_state, now=clock()
            )
        for rec in replayed.admissions:
            client = rec["client"]
            rx = fab._receivers.get(client)
            if rx is None:
                rx = fab._receivers[client] = EnvelopeReceiver()
                rx.fence = fab.term
            if client != LOCAL_CLIENT:
                rx.seed(int(rec["incarnation"]), int(rec["seq"]))
                fab._replies[
                    (client, int(rec["incarnation"]), int(rec["seq"]))
                ] = FabricReply(**rec["reply"])
            fab._decisions = max(fab._decisions, int(rec["n"]) + 1)
            if rec["op"] == "admit" and rec["reply"].get("ok"):
                fab.admission_log.append(rec["job"])
        fab.metrics.incr("fabric.rebuilds")
        return fab

    # -- the envelope seam --------------------------------------------------

    def handle(
        self, client_id: str, env: ControlEnvelope
    ) -> Tuple[FabricReply, ControlAck]:
        """Apply one client envelope exactly once.

        Dedup/fencing run under the fabric lock; the apply itself runs
        OUTSIDE it (a blocking ``admit`` must not stall other clients'
        ``note_served`` — the DRR needs concurrent waiters to be fair).
        Per client, commands are serial (one outstanding RPC per
        consumer thread — the loader's admission protocol), so a
        retry never races its own first delivery.
        """
        with self._lock:
            rx = self._receivers.get(client_id)
            if rx is None:
                rx = self._receivers[client_id] = EnvelopeReceiver()
                rx.fence = self.term
            payload, ack = rx.accept(env)
            if payload is None:
                if ack.fence_rejected:
                    self.metrics.incr("fabric.fence_drops")
                    return FabricReply(
                        ok=False,
                        error=f"fenced off (authority term {self.term})",
                        error_type="fenced",
                    ), ack
                self.metrics.incr("fabric.dup_replies")
                reply = self._replies.get(
                    (client_id, env.incarnation, env.seq)
                )
                if reply is None:
                    reply = FabricReply(
                        ok=False,
                        error="duplicate past the reply window",
                        error_type="error",
                    )
                return reply, ack
        reply = self._apply(payload)
        self._record(client_id, env.incarnation, env.seq, payload, reply)
        return reply, ack

    def apply_local(self, payload: Any) -> FabricReply:
        """Apply a supervisor-local command through the same journaled
        decision path remote envelopes take — no envelope, no dedup
        (the caller IS the authority)."""
        reply = self._apply(payload)
        self._record(LOCAL_CLIENT, 0, -1, payload, reply)
        return reply

    # -- supervisor-side conveniences ---------------------------------------

    def register_job(self, spec: JobSpec) -> FabricReply:
        return self.apply_local(RegisterJob(spec.to_dict()))

    def job_crashed(self, job_id: str) -> FabricReply:
        """Absorb a job crash detected supervisor-side (lease expiry,
        operator report): revoke its in-flight grants, release its
        budget, unregister — neighbours untouched."""
        return self.apply_local(CrashNote(job_id))

    def revoke_jobs(
        self, slo_s: Optional[float] = None, job_ids: Optional[list] = None
    ) -> FabricReply:
        """Preemption/scale-down drain over the control plane; the SLO
        defaults to ``DDL_TPU_FABRIC_DRAIN_SLO_S``."""
        if slo_s is None:
            slo_s = float(envspec.get("DDL_TPU_FABRIC_DRAIN_SLO_S"))
        return self.apply_local(
            RevokeJobs(float(slo_s), tuple(job_ids) if job_ids else None)
        )

    def clear_job_revocations(
        self, job_ids: Optional[list] = None
    ) -> FabricReply:
        return self.apply_local(
            ClearRevocations(tuple(job_ids) if job_ids else None)
        )

    # -- decision application ----------------------------------------------

    def _apply(self, payload: Any) -> FabricReply:
        """Translate one command into scheduler/registry mutations.

        The ONLY function that drives the resident scheduler (ddl-lint
        DDL026 allowlists it): every mutation pairs with a journaled
        decision in :meth:`_record`, so replay sees what happened here.
        """
        try:
            if isinstance(payload, RegisterJob):
                spec = JobSpec(**payload.spec)
                rec = self.registry.register(spec)
                self.scheduler.register(spec.tenant_spec())
                return FabricReply(
                    ok=True,
                    value={"index": rec.index, "seq_base": rec.seq_base},
                )
            if isinstance(payload, UnregisterJob):
                self.registry.unregister(payload.job_id)
                self.scheduler.unregister(payload.job_id)
                return FabricReply(ok=True)
            if isinstance(payload, AdmitRequest):
                self.scheduler.admit(payload.job_id, payload.timeout_s)
                self.metrics.incr("fabric.admissions")
                return FabricReply(ok=True)
            if isinstance(payload, ServedNote):
                self.scheduler.note_served(payload.job_id, payload.nbytes)
                return FabricReply(
                    ok=True, value={"charged": int(payload.nbytes)}
                )
            if isinstance(payload, AbortNote):
                self.scheduler.note_aborted(payload.job_id)
                return FabricReply(ok=True)
            if isinstance(payload, RevokeJobs):
                drained = self.scheduler.revoke_inflight(
                    payload.slo_s,
                    names=(
                        list(payload.job_ids)
                        if payload.job_ids is not None else None
                    ),
                )
                return FabricReply(ok=True, value={"drained": drained})
            if isinstance(payload, ClearRevocations):
                self.scheduler.clear_revocations(
                    names=(
                        list(payload.job_ids)
                        if payload.job_ids is not None else None
                    )
                )
                return FabricReply(ok=True)
            if isinstance(payload, CrashNote):
                return self._crash(payload.job_id)
            return FabricReply(
                ok=False,
                error=f"unknown fabric command {type(payload).__name__}",
                error_type="error",
            )
        except WindowsRevoked as e:
            return FabricReply(
                ok=False, error=str(e), error_type="revoked"
            )
        except StallTimeoutError as e:
            return FabricReply(
                ok=False, error=str(e), error_type="stall_timeout"
            )
        except DDLError as e:
            return FabricReply(ok=False, error=str(e), error_type="error")

    def _crash(self, job_id: str) -> FabricReply:
        """The JOB_CRASH ladder: release the dead job's in-flight
        grants (its ``note_served`` will never arrive — a leaked grant
        would make every later drain burn its full SLO), then drop its
        registration so its byte budget and DRR share vanish.  The
        neighbours' ledgers are untouched."""
        if job_id not in self.registry:
            return FabricReply(
                ok=False,
                error=f"job {job_id!r} is not registered",
                error_type="error",
            )
        state = self.scheduler.export_state()
        inflight = int(
            state["tenants"].get(job_id, {}).get("inflight", 0)
        )
        for _ in range(inflight):
            self.scheduler.note_aborted(job_id)
        self.scheduler.unregister(job_id)
        self.registry.unregister(job_id)
        self.metrics.incr("fabric.job_crashes")
        logger.warning(
            "fabric: job %r crashed mid-grant — released %d in-flight "
            "window(s), budget freed, registration dropped",
            job_id, inflight,
        )
        return FabricReply(ok=True, value={"revoked_inflight": inflight})

    # -- the decision journal ----------------------------------------------

    def _record(
        self,
        client_id: str,
        incarnation: int,
        seq: int,
        payload: Any,
        reply: FabricReply,
    ) -> None:
        op = _OPS.get(type(payload), "unknown")
        job_id = getattr(payload, "job_id", None)
        if isinstance(payload, RegisterJob):
            job_id = payload.spec.get("job_id")
        with self._lock:
            n = self._decisions
            self._decisions += 1
            if op == "admit" and reply.ok:
                self.admission_log.append(job_id)
            if client_id != LOCAL_CLIENT:
                self._replies[(client_id, incarnation, seq)] = reply
                while len(self._replies) > REPLY_WINDOW:
                    self._replies.pop(next(iter(self._replies)))
            if self.journal is None:
                return
            self.journal.append(
                KIND_ADMISSION,
                {
                    "n": n,
                    "client": client_id,
                    "incarnation": int(incarnation),
                    "seq": int(seq),
                    "op": op,
                    "job": job_id,
                    "reply": reply.to_dict(),
                },
            )
            if op in ("register", "unregister", "crash"):
                self.journal.append(
                    KIND_JOBS, {"state": self.registry.export_state()}
                )
            if self.snapshot_every > 0 and (n + 1) % self.snapshot_every == 0:
                from ddl_tpu.cluster.supervision import KIND_SCHEDULER

                self.journal.append(
                    KIND_SCHEDULER,
                    {"state": self.scheduler.export_state()},
                )
                self.metrics.incr("fabric.scheduler_snapshots")

    # -- reporting ----------------------------------------------------------

    def report(self) -> dict:
        """Per-job admission + cache blocks, the bench's ``fabric``
        body (the :meth:`AdmissionController.report` shape, keyed by
        job)."""
        m = self.metrics
        per_job = {}
        for job_id in self.registry.jobs():
            block = m.prefixed(f"ingest.{job_id}.")
            block["admission_wait_p50_s"] = m.quantile(
                f"ingest.{job_id}.admission_wait", 0.5
            )
            block["admission_wait_p99_s"] = m.quantile(
                f"ingest.{job_id}.admission_wait", 0.99
            )
            block["cache_hits"] = m.counter(f"job.{job_id}.cache.hits")
            block["cache_misses"] = m.counter(f"job.{job_id}.cache.misses")
            per_job[job_id] = block
        return {
            "jobs": per_job,
            "admissions": m.counter("fabric.admissions"),
            "job_crashes": m.counter("fabric.job_crashes"),
            "dup_replies": m.counter("fabric.dup_replies"),
            "fence_drops": m.counter("fabric.fence_drops"),
            "decisions": self._decisions,
        }


#: Client id the authority's own apply_local decisions journal under.
LOCAL_CLIENT = "_local"


# -- the client side --------------------------------------------------------


class FabricClient:
    """One training-job host's connection to the fabric authority.

    ``channel`` is the wire: ``(client_id, envelope) -> (reply, ack)``.
    The loopback default calls an in-process :class:`IngestFabric`
    directly — synchronous delivery, with drops/dups/fencing injected
    on the attempt itself, so the acked-envelope discipline is live on
    exactly the path a socket adapter would run.
    """

    def __init__(
        self,
        fabric: Any,
        client_id: str,
        incarnation: int = 0,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
        retries: Optional[int] = None,
        backoff_s: Optional[float] = None,
    ):
        self.client_id = client_id
        self.metrics = metrics or default_metrics()
        self._clock = clock
        if isinstance(fabric, IngestFabric):
            self._channel = fabric.handle
            self.set_fence(fabric.term)
        else:
            self._channel = fabric
        self._sender = ControlSender(
            raw_send=self._raw_send,
            target=0,
            incarnation=incarnation,
            metrics=self.metrics,
            retries=retries,
            backoff_s=backoff_s,
            clock=clock,
        )
        # seq -> reply for in-flight RPCs (serial per consumer thread;
        # bounded by the outstanding command count).
        self._replies: Dict[int, FabricReply] = {}  # ddl-lint: disable=DDL013
        self._fault_index = 0

    def set_fence(self, term: int) -> None:
        """Adopt a (new) authority term — the re-fence after failover.
        Called automatically when constructed over a live fabric."""
        self._pending_fence = int(term)

    def rebind(self, fabric: "IngestFabric") -> None:
        """Point this client at a successor authority (failover): swap
        the channel and adopt its fencing term.  Pending envelopes on
        the old term would be fenced off — the protocol is serial per
        client, so there are none by construction when this is called
        between RPCs."""
        self._channel = fabric.handle
        self.set_fence(fabric.term)

    def _raw_send(self, env: ControlEnvelope) -> None:
        """One wire attempt.  ``serve.fabric.admit`` fires here, per
        attempt, for admission commands — a ``JOB_ADMISSION_DROP``
        raises the real :class:`AdmissionDropped` (a
        ``TransportError``), which :class:`ControlSender` absorbs into
        its pending set for backoff retry; ``CONTROL_MSG_DUP`` delivers
        the SAME envelope twice (the fabric's dedup answers the second
        from its reply cache)."""
        fired: list = []
        if isinstance(env.payload, AdmitRequest):
            fired = fault_point(
                "serve.fabric.admit", producer_idx=env.payload.index
            )
        reply, ack = self._channel(self.client_id, env)
        self._replies[env.seq] = reply
        self._sender.ack(ack)
        if fired and FaultKind.CONTROL_MSG_DUP.value in fired:
            dup_reply, dup_ack = self._channel(self.client_id, env)
            self._replies[env.seq] = dup_reply
            self._sender.ack(dup_ack)

    def _rpc(self, payload: Any) -> FabricReply:
        """Send one command and drive retries until its reply lands.

        Loopback delivery is synchronous, so a missing reply after an
        attempt means the attempt was LOST — pump immediately with the
        backoff horizon forced due (waiting wall-clock buys nothing on
        an in-process wire; an async adapter would sleep here
        instead).  Retry exhaustion surfaces as the real
        :class:`AdmissionDropped`."""
        fence = getattr(self, "_pending_fence", None)
        if fence is not None:
            self._sender.fence = max(self._sender.fence, fence)
        seq = self._sender.send(payload)
        while seq not in self._replies:
            if any(e.seq == seq for e in self._sender.exhausted):
                self.metrics.incr("fabric.client_exhausted")
                raise AdmissionDropped(
                    f"fabric command {type(payload).__name__} for "
                    f"{self.client_id!r} exhausted its retry cap"
                )
            self._sender.pump(now=self._clock() + 1e9)
        return self._replies.pop(seq)

    def _raise_typed(self, reply: FabricReply) -> None:
        if reply.error_type == "stall_timeout":
            raise StallTimeoutError(reply.error)
        if reply.error_type == "revoked":
            raise WindowsRevoked(reply.error)
        raise DDLError(reply.error or "fabric command failed")

    # -- the job-facing API --------------------------------------------------

    def register_job(self, spec: JobSpec) -> "FabricJob":
        reply = self._rpc(RegisterJob(spec.to_dict()))
        if not reply.ok:
            self._raise_typed(reply)
        return FabricJob(
            self,
            spec.job_id,
            index=int(reply.value["index"]),
            seq_base=int(reply.value["seq_base"]),
        )

    def unregister_job(self, job_id: str) -> None:
        reply = self._rpc(UnregisterJob(job_id))
        if not reply.ok:
            self._raise_typed(reply)

    def report_crash(self, job_id: str) -> None:
        """Report a job death to the authority (the client-side half of
        the JOB_CRASH ladder — a harness that catches
        :class:`JobCrashed` forwards it here)."""
        self._rpc(CrashNote(job_id))


class FabricJob:
    """One registered job's admission handle — the
    :class:`~ddl_tpu.serve.tenancy.Tenant` protocol
    (``admit``/``note_served``/``note_aborted``), every call riding the
    acked control plane, so ``loader.bind_admission(job)`` works
    unchanged against a remote authority.

    ``seq_base`` is the job's integrity namespace: set it as the
    ``seq_base`` attribute on the job's producer function and its
    loaders verify trailer seqs in the job's own slice of the u64
    space (:mod:`ddl_tpu.serve.jobs`).
    """

    def __init__(
        self, client: FabricClient, job_id: str, index: int, seq_base: int
    ):
        self.client = client
        self.job_id = job_id
        self.name = job_id
        self.index = index
        self.seq_base = seq_base

    def admit(self, timeout_s: Optional[float] = None) -> None:
        if timeout_s is None:
            timeout_s = float(envspec.get("DDL_TPU_FABRIC_ADMIT_TIMEOUT_S"))
        reply = self.client._rpc(
            AdmitRequest(self.job_id, float(timeout_s), index=self.index)
        )
        if not reply.ok:
            self.client._raise_typed(reply)

    def note_served(self, nbytes: int) -> None:
        try:
            # Mid-grant chaos: admit returned, the window is in flight,
            # the charge has not landed — exactly where a trainer dies.
            fault_point("serve.fabric.grant", producer_idx=self.index)
        except JobCrashed:
            self.client.report_crash(self.job_id)
            raise
        reply = self.client._rpc(ServedNote(self.job_id, int(nbytes)))
        if not reply.ok:
            self.client._raise_typed(reply)

    def note_aborted(self) -> None:
        self.client._rpc(AbortNote(self.job_id))

    def bind(self, loader: Any) -> "FabricJob":
        loader.bind_admission(self)
        return self
