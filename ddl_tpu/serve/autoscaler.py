"""Demand-driven autoscaling of the loader tier (the policy loop).

PR 9 built the *mechanism* for an elastically-sized loader pool
(``ElasticCluster.rejoin_host`` / host loss → epoch-fenced pool shrink);
nothing drove it.  This module is the driver: a DDL018-compliant
deadline loop that reads the demand signals already surfaced by
``north_star_report`` — the consumer stall fraction and the staging
queue depth — and turns *sustained* demand into ``rejoin_host`` of
standby loader hosts, and *sustained* idleness into drain-then-release
of surplus ones.

Policy discipline (docs/SERVING.md "Autoscaler"):

- **Hysteresis band.**  Scale up above ``up_stall_fraction``, down
  below ``down_stall_fraction`` — the gap between them is the dead band
  that stops flapping.  A signal must hold beyond its threshold for
  ``sustain_s`` continuously before any action (one noisy sample never
  resizes the fleet).
- **Cooldown.**  After any action, no further action for
  ``cooldown_s`` — a fresh host needs time to show up in the signal
  before it can be judged insufficient.
- **Never-empty floor.**  The pool never shrinks below ``min_hosts``
  loader hosts, and scale-down never touches a host carrying trainer
  ranks.
- **Placement follows the pool.**  Every resize re-runs
  :func:`~ddl_tpu.cluster.placement.plan_placement` over the new view
  (Cloud Collectives, arXiv:2105.14088) when link costs are known, so
  the producer→consumer assignment tracks membership instead of
  decaying across resizes.

Observability: ``serve.scale_ups`` / ``serve.scale_downs`` counters,
the ``serve.scale_up_reaction`` timer (sustained-signal start → rejoin
complete — the bench's reaction-time headline), and the
``serve.pool_hosts`` / ``serve.standby_hosts`` gauges.

Chaos: the ``serve.scale`` fault site fires at the top of every
:meth:`Autoscaler.step`; the ``SCALE_DECISION_DELAY`` kind sleeps there,
modelling a slow control plane — the chaos leg proves a delayed decision
degrades reaction time, never correctness.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Iterable, List, Optional

from ddl_tpu.cluster.membership import HostInfo
from ddl_tpu.cluster.placement import Placement, plan_placement
from ddl_tpu.exceptions import DDLError, ShutdownRequested
from ddl_tpu.faults import fault_point
from ddl_tpu.observability import Metrics, metrics as default_metrics

logger = logging.getLogger("ddl_tpu")


@dataclasses.dataclass(frozen=True)
class AutoscalerPolicy:
    """Hysteresis + pacing knobs for the policy loop."""

    #: Scale up when the windowed stall fraction holds above this.
    up_stall_fraction: float = 0.25
    #: Scale down when it holds below this (the hysteresis floor).
    down_stall_fraction: float = 0.05
    #: Optional second up-signal: staged-ingest queue depth at/above
    #: this also counts as demand (0 disables the queue signal).
    up_queue_depth: float = 0.0
    #: How long a signal must hold beyond its threshold before acting.
    sustain_s: float = 1.0
    #: Minimum spacing between consecutive scale actions.
    cooldown_s: float = 5.0
    #: The never-empty floor: loader hosts the pool may not drop below.
    min_hosts: int = 1
    #: Ceiling on loader hosts (0 = bounded only by standby supply).
    max_hosts: int = 0

    def __post_init__(self) -> None:
        if not (0.0 <= self.down_stall_fraction < self.up_stall_fraction):
            raise DDLError(
                "hysteresis band requires 0 <= down_stall_fraction < "
                f"up_stall_fraction, got [{self.down_stall_fraction}, "
                f"{self.up_stall_fraction}]"
            )
        if self.min_hosts < 1:
            raise DDLError("min_hosts must be >= 1 (never-empty floor)")
        if self.sustain_s < 0 or self.cooldown_s < 0:
            raise DDLError("sustain_s/cooldown_s must be >= 0")


class Autoscaler:
    """The policy loop binding demand signals to pool resizes.

    ``cluster`` is an :class:`~ddl_tpu.cluster.elastic.ElasticCluster`
    (or anything exposing ``supervisor.view``, ``rejoin_host(HostInfo)``
    and ``drain_host(host_id)`` — the bench's multi-tenant fan-out
    adapter does).  ``standby`` seeds the idle-host reserve scale-up
    draws from; drained hosts return to it.

    ``signal`` overrides the demand reading — a zero-arg callable
    returning ``{"stall_fraction": float, "queue_depth": float}``.  The
    default reads the shared metrics registry and computes a WINDOWED
    stall fraction (deltas of the ``consumer.wait`` timer over deltas of
    wall clock, normalised by ``n_consumers``) — the cumulative
    ``Metrics.stall_fraction`` would dilute a fresh burst under a long
    quiet history and never cross the band.
    """

    def __init__(
        self,
        cluster,
        standby: Iterable[HostInfo] = (),
        policy: AutoscalerPolicy = AutoscalerPolicy(),
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
        signal: Optional[Callable[[], dict]] = None,
        link_costs=None,
        n_consumers: int = 1,
        poll_interval_s: float = 0.25,
    ):
        self.cluster = cluster
        self.policy = policy
        self.metrics = metrics or default_metrics()
        self.link_costs = link_costs
        self.n_consumers = max(1, int(n_consumers))
        self.poll_interval_s = poll_interval_s
        self._clock = clock
        self._signal = signal or self._windowed_signal
        self._standby: List[HostInfo] = list(standby)
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._last_action_t = -float("inf")
        self._last_wait_s = (
            self.metrics.timer("consumer.wait").total_s
            - self.metrics.timer("serve.admission_wait").total_s
        )
        self._last_wall = self._clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_placement: Optional[Placement] = None
        self._set_gauges()

    # -- signals -----------------------------------------------------------

    def _windowed_signal(self) -> dict:
        """Stall fraction over the span since the previous reading.

        Admission-gate waits are SUBTRACTED: a tenant parked by its own
        byte budget is throttled, not starved — scaling the pool up
        cannot help it, and counting that wait as demand would let one
        over-budget tenant inflate the fleet for everyone."""
        now = self._clock()
        wait = (
            self.metrics.timer("consumer.wait").total_s
            - self.metrics.timer("serve.admission_wait").total_s
        )
        dt = max(now - self._last_wall, 1e-9)
        stall = (wait - self._last_wait_s) / dt / self.n_consumers
        self._last_wait_s, self._last_wall = wait, now
        return {
            "stall_fraction": max(0.0, stall),
            "queue_depth": self.metrics.gauge("staging.queue_depth"),
        }

    def _loader_hosts(self) -> List[HostInfo]:
        return [
            h for h in self.cluster.supervisor.view.hosts if h.loader_ranks
        ]

    def _set_gauges(self) -> None:
        self.metrics.set_gauge("serve.pool_hosts", len(self._loader_hosts()))
        self.metrics.set_gauge("serve.standby_hosts", len(self._standby))

    # -- one policy evaluation ---------------------------------------------

    def step(self, now: Optional[float] = None) -> Optional[str]:
        """Evaluate the policy once; returns ``"up"`` / ``"down"`` /
        ``None``.  Driven by :meth:`start`'s loop or called directly
        (tests, an external scheduler tick)."""
        # Chaos site: SCALE_DECISION_DELAY sleeps here — a slow control
        # plane delays the decision, never corrupts it.
        fault_point("serve.scale")
        now = self._clock() if now is None else now
        sig = self._signal()
        stall = float(sig.get("stall_fraction", 0.0))
        queue = float(sig.get("queue_depth", 0.0))
        pol = self.policy
        demand = stall >= pol.up_stall_fraction or (
            pol.up_queue_depth > 0 and queue >= pol.up_queue_depth
        )
        idle = stall <= pol.down_stall_fraction and not demand
        if demand:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
        elif idle:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
        else:  # inside the hysteresis dead band: hold state, no timers
            self._above_since = None
            self._below_since = None
        if now - self._last_action_t < pol.cooldown_s:
            return None
        if (
            self._above_since is not None
            and now - self._above_since >= pol.sustain_s
        ):
            return self._scale_up(now)
        if (
            self._below_since is not None
            and now - self._below_since >= pol.sustain_s
        ):
            return self._scale_down(now)
        return None

    def _scale_up(self, now: float) -> Optional[str]:
        pol = self.policy
        if not self._standby:
            return None  # demand without supply: nothing to admit
        if pol.max_hosts and len(self._loader_hosts()) >= pol.max_hosts:
            return None
        host = self._standby.pop(0)
        reaction0 = self._above_since if self._above_since is not None else now
        try:
            view = self.cluster.rejoin_host(host)
        except (ShutdownRequested, KeyboardInterrupt):
            self._standby.insert(0, host)
            raise
        except Exception:
            # A failed rejoin (host never came back, channel dead) must
            # not lose the reserve entry OR kill the policy loop.
            self._standby.insert(0, host)
            logger.exception("serve: scale-up rejoin of host %d failed",
                             host.host_id)
            return None
        self._last_action_t = now
        self._above_since = None
        self.metrics.incr("serve.scale_ups")
        self.metrics.add_time(
            "serve.scale_up_reaction", max(0.0, self._clock() - reaction0)
        )
        self._replan(view)
        self._set_gauges()
        logger.warning(
            "serve: scaled UP — host %d joined the loader pool (%d hosts)",
            host.host_id, len(self._loader_hosts()),
        )
        return "up"

    def _scale_down(self, now: float) -> Optional[str]:
        pol = self.policy
        loaders = self._loader_hosts()
        if len(loaders) <= pol.min_hosts:
            return None  # the never-empty floor
        # Drain the newest (highest-id) loader-only host: trainer-role
        # hosts are never drained, and low ids are the stable base set.
        candidates = [h for h in loaders if not h.trainer_ranks]
        if not candidates:
            return None
        host = max(candidates, key=lambda h: h.host_id)
        try:
            drained = self.cluster.drain_host(host.host_id)
        except (ShutdownRequested, KeyboardInterrupt):
            raise
        except Exception:
            logger.exception("serve: scale-down drain of host %d failed",
                             host.host_id)
            return None
        self._last_action_t = now
        self._below_since = None
        self._standby.append(drained)
        self.metrics.incr("serve.scale_downs")
        self._replan(self.cluster.supervisor.view)
        self._set_gauges()
        logger.warning(
            "serve: scaled DOWN — host %d drained to standby (%d hosts)",
            host.host_id, len(self._loader_hosts()),
        )
        return "down"

    def retune(self, policy: AutoscalerPolicy) -> None:
        """Swap the hysteresis policy live (the ddl_tpu.tune seam).

        Sustain timers reset: a threshold that just moved must be held
        beyond for a FULL sustain span before acting — carrying a timer
        accumulated against the old band would let the first post-retune
        tick fire on stale evidence.  The cooldown clock is kept: a
        retune is not an action and must not unlock one early.
        """
        self.policy = policy
        self._above_since = None
        self._below_since = None

    def _replan(self, view) -> None:
        """Placement follows the pool: re-run the Cloud-Collectives
        reorder over the resized view whenever link costs are known."""
        if self.link_costs is None:
            return
        try:
            self.last_placement = plan_placement(view, self.link_costs)
        except DDLError:
            # A view with no loader ranks mid-transition: placement is
            # meaningless until the next resize lands.
            self.last_placement = None
            return
        self.metrics.incr("serve.replans")
        self.metrics.set_gauge(
            "serve.placement_reordered",
            1.0 if self.last_placement.reordered else 0.0,
        )

    # -- the background loop (DDL018: timed stop-event wait) ---------------

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(
            target=self._run, name="ddl-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.poll_interval_s * 2 + 1)

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        # DDL018/DDL019: bounded by the stop event's timed wait; step()
        # itself does bounded per-tenant work (snapshot reads, one
        # resize at most) — never a per-tenant blocking fan-out.
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.step()
            except (ShutdownRequested, KeyboardInterrupt):
                return  # teardown reached the policy loop: stop cleanly
            except Exception:
                # A crashing step must never silently disable
                # autoscaling (the watchdog.sweep contract).
                logger.exception("serve: autoscaler step raised; continuing")
                continue

    @property
    def standby(self) -> List[HostInfo]:
        return list(self._standby)
