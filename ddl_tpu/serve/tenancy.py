"""Multi-tenant admission: fair-share scheduling at the ring-acquire seam.

The "millions of users" north star is many jobs hammering one shared
ingest fabric, not one big job (ROADMAP item 1; MPMD disaggregation,
arXiv:2412.14374).  PR 9 made the loader tier a resizable pool; this
module makes it a *shared* one: N independent
:class:`~ddl_tpu.dataloader.DistributedDataLoader` jobs register as
**tenants** against one producer pool and one shard-cache tier, and a
deficit-round-robin (DRR) fair-share scheduler arbitrates every window
acquisition at the ring-acquire seam — the single bypass-proof gate the
pool seam already owns (``LoaderPool.next_member`` rotation feeds
``DistributedDataLoader._acquire_verified``, which is where the
admission hook fires).

Mechanics (docs/SERVING.md has the operator view):

- **Charge-after DRR.**  ``admit()`` blocks until the tenant is
  *grantable*; the actual byte charge lands at ``note_served(nbytes)``
  (window size is only known post-acquire).  A tenant may therefore
  overshoot its fair share by at most ONE window — the standard DRR
  burst bound — and is then held until a replenish round restores its
  deficit.  Rounds advance only when no waiting tenant is grantable, so
  a backlogged tenant is never starved: per round every tenant earns
  ``quantum_bytes * weight`` of credit (capped at one round's worth —
  idle tenants cannot bank unbounded credit).
- **Byte budget.**  ``byte_budget_per_s`` is a token bucket (charged at
  ``note_served``, refilled by wall clock): a tenant over its rate
  budget waits for refill even when the DRR would grant it.
- **Slot budget.**  ``slot_budget`` caps the windows a tenant may be
  granted per DRR round — a concurrency brake on top of the byte share.
- **Bounded waits.**  ``admit`` is deadline-bounded and wakes on a timed
  condition wait (DDL018/DDL019 discipline): a wedged peer can age a
  tenant's wait into :class:`~ddl_tpu.exceptions.StallTimeoutError`,
  never into a silent spin.

Per-tenant observability rides the ``ingest.<tenant>.*`` name family
(``bytes``/``windows``/``bursts`` counters, the ``admission_wait``
timer) and is read back with :meth:`Metrics.prefixed` — see
:meth:`AdmissionController.report`.  Aggregates live under ``serve.*``
(``serve.admissions``, ``serve.tenant_bursts``, ``serve.rounds``, the
``serve.admission_wait`` timer, the ``serve.tenants`` gauge).

Chaos: the ``serve.admit`` fault site fires once per admission attempt
(``producer_idx`` carries the tenant's registration index); the
``TENANT_BURST`` kind raises the REAL :class:`~ddl_tpu.exceptions.
TenantBurst` type, which the scheduler absorbs as phantom demand —
``param`` bytes charged to the bursting tenant, so the burst is paid
for by the burster's own share, never by its neighbours.
"""

from __future__ import annotations

import dataclasses
import logging
import threading

from ddl_tpu.concurrency import named_condition
import time
from typing import Callable, Dict, Optional

from ddl_tpu.exceptions import (
    DDLError,
    StallTimeoutError,
    TenantBurst,
    WindowsRevoked,
)
from ddl_tpu.faults import fault_point
from ddl_tpu.observability import Metrics, metrics as default_metrics

logger = logging.getLogger("ddl_tpu")

#: Default DRR quantum: credit earned per tenant per replenish round,
#: scaled by the tenant's weight.  Sized at a typical bench window so
#: one round buys one window for a weight-1.0 tenant.
DEFAULT_QUANTUM_BYTES = 4 << 20


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's admission contract.

    ``weight`` scales the DRR quantum (2.0 = twice the fair share);
    ``byte_budget_per_s`` caps sustained throughput (0 = uncapped);
    ``slot_budget`` caps windows granted per DRR round (0 = uncapped).
    """

    name: str
    weight: float = 1.0
    byte_budget_per_s: float = 0.0
    slot_budget: int = 0

    def __post_init__(self) -> None:
        if not self.name or "." in self.name:
            # The name becomes a metrics key segment (ingest.<name>.*):
            # a dot would alias into another family's namespace.
            raise DDLError(f"invalid tenant name {self.name!r}")
        if self.weight <= 0:
            raise DDLError(f"tenant weight must be > 0, got {self.weight}")
        if self.byte_budget_per_s < 0 or self.slot_budget < 0:
            raise DDLError("tenant budgets must be >= 0")


class _TenantState:
    """Scheduler-internal per-tenant accounting (guarded by the
    scheduler's condition lock)."""

    def __init__(self, spec: TenantSpec, index: int, now: float):
        self.spec = spec
        self.index = index
        self.deficit = 0.0
        # Token bucket: starts one second full so a fresh tenant's first
        # window is never budget-blocked; refilled lazily from `stamp`.
        self.tokens = float(spec.byte_budget_per_s)
        self.stamp = now
        self.served_in_round = 0
        self.waiting = 0
        # Preemption/scale-down seam (ISSUE 14): windows granted by
        # admit() but not yet charged at note_served() — the in-flight
        # set revoke_inflight waits out under its SLO.
        self.inflight = 0
        self.revoked = False

    def refill(self, now: float) -> None:
        rate = self.spec.byte_budget_per_s
        if rate <= 0:
            return
        self.tokens = min(
            rate, self.tokens + rate * max(0.0, now - self.stamp)
        )
        self.stamp = now


class FairShareScheduler:
    """Deficit-round-robin arbiter over registered tenants.

    Thread-safe: every tenant's consumer thread calls :meth:`admit` /
    :meth:`note_served` concurrently; all state lives under one
    condition lock.  The scheduler never touches rings — it only decides
    *when* a tenant's next ring acquire may proceed.
    """

    def __init__(
        self,
        quantum_bytes: int = DEFAULT_QUANTUM_BYTES,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if quantum_bytes <= 0:
            raise DDLError(f"quantum_bytes must be > 0, got {quantum_bytes}")
        self.quantum_bytes = float(quantum_bytes)
        self.metrics = metrics or default_metrics()
        self._clock = clock
        self._cond = named_condition("serve.tenancy.cond")
        # name -> state: bounded by the registered tenant set
        # (register/unregister are the only growth/shrink sites).
        self._tenants: Dict[str, _TenantState] = {}  # ddl-lint: disable=DDL013
        self._next_index = 0
        self._round = 0

    # -- registration ------------------------------------------------------

    def register(self, spec: TenantSpec) -> None:
        with self._cond:
            if spec.name in self._tenants:
                raise DDLError(f"tenant {spec.name!r} is already registered")
            self._tenants[spec.name] = _TenantState(
                spec, self._next_index, self._clock()
            )
            self._next_index += 1
            self.metrics.set_gauge("serve.tenants", len(self._tenants))
            self._cond.notify_all()

    def unregister(self, name: str) -> None:
        with self._cond:
            if self._tenants.pop(name, None) is not None:
                self.metrics.set_gauge("serve.tenants", len(self._tenants))
                # A departing tenant may have been the only non-grantable
                # waiter blocking a round advance — wake the others.
                self._cond.notify_all()
        # Retire the departed tenant's stall gauge WITH its ``.max``
        # high-water companion: zeroing (or just dropping the base)
        # leaves a stale ``serve.stall.<name>.max`` observable between
        # bench reps, and north_star_report's per-tenant dict would
        # keep reporting a tenant that no longer exists.
        self.metrics.clear_gauge(f"serve.stall.{name}")

    def tenants(self) -> "list[str]":
        with self._cond:
            return sorted(self._tenants)

    # -- the admission gate ------------------------------------------------

    def admit(self, name: str, timeout_s: float) -> None:
        """Block until ``name`` is grantable (deadline-bounded).

        ``timeout_s <= 0`` is the NON-BLOCKING probe the loader's
        lookahead deepening uses: not-grantable raises
        :class:`StallTimeoutError` immediately (the deepening loop
        treats it exactly like a not-yet-committed window).
        """
        st = self._state(name)
        try:
            # Chaos site (producer_idx = tenant registration index).
            fault_point("serve.admit", producer_idx=st.index)
        except TenantBurst as burst:
            self._charge_burst(name, st, burst.burst_bytes)
        t0 = time.perf_counter()
        deadline = self._clock() + max(0.0, timeout_s)
        with self._cond:
            st.waiting += 1
            try:
                while True:
                    if st.revoked:
                        # Preemption/scale-down revocation (ISSUE 14):
                        # the typed wake-up — never a silent timeout.
                        self.metrics.incr("serve.revoked_waiters")
                        self.metrics.incr(f"ingest.{name}.revocations")
                        raise WindowsRevoked(
                            f"tenant {name!r} admission revoked "
                            "(preemption/scale-down drain in progress)"
                        )
                    st.refill(self._clock())
                    if self._grantable(st):
                        st.inflight += 1
                        break
                    if self._advance_round_if_stuck():
                        # Rounds replenish instantly (they are logical,
                        # not wall-clock): re-check without sleeping —
                        # a multi-quantum window costs loop passes, not
                        # 50 ms apiece.  Terminates because each round
                        # adds >= quantum * weight credit and rounds
                        # only advance while NO waiter is grantable.
                        continue
                    now = self._clock()
                    if now >= deadline:
                        raise StallTimeoutError(
                            f"tenant {name!r} admission not granted "
                            f"within {timeout_s}s (deficit "
                            f"{st.deficit:.0f}, tokens {st.tokens:.0f}, "
                            f"round slots {st.served_in_round})"
                        )
                    self._cond.wait(min(0.05, deadline - now))
            finally:
                st.waiting -= 1
        wait = time.perf_counter() - t0
        self.metrics.incr("serve.admissions")
        self.metrics.add_time("serve.admission_wait", wait)
        self.metrics.add_time(f"ingest.{name}.admission_wait", wait)
        # First-class percentiles (ddl_tpu.obs): the global and
        # per-tenant admission-wait distributions land in bounded
        # log-spaced histograms — north_star_report's
        # admission_wait_p99 / per-tenant p99s read them back, and the
        # tenancy bench's independently computed percentile must agree
        # (tests/test_obs.py pins the agreement).
        self.metrics.observe("serve.admission_wait", wait)
        self.metrics.observe(f"ingest.{name}.admission_wait", wait)

    def note_aborted(self, name: str) -> None:
        """Release a grant whose ring acquire FAILED (stall timeout,
        revoked target, shutdown): the window was never served, so
        nothing is charged — but the in-flight count must come back
        down, or every later :meth:`revoke_inflight` would burn its
        full SLO waiting on a phantom grant."""
        with self._cond:
            st = self._tenants.get(name)
            if st is not None:
                st.inflight = max(0, st.inflight - 1)
                self._cond.notify_all()

    def note_served(self, name: str, nbytes: int) -> None:
        """Charge one served window against ``name``'s share + budgets
        (the charge-after half of :meth:`admit`)."""
        nbytes = int(nbytes)
        with self._cond:
            st = self._tenants.get(name)
            if st is None:
                return  # unregistered mid-flight: nothing left to charge
            st.refill(self._clock())
            st.deficit -= nbytes
            if st.spec.byte_budget_per_s > 0:
                st.tokens -= nbytes
            st.served_in_round += 1
            st.inflight = max(0, st.inflight - 1)
            self._cond.notify_all()
        self.metrics.incr(f"ingest.{name}.bytes", float(nbytes))
        self.metrics.incr(f"ingest.{name}.windows")

    # -- preemption / scale-down revocation (ISSUE 14) ---------------------

    def revoke_inflight(
        self, slo_s: float, names: "Optional[list] | None" = None
    ) -> bool:
        """Revoke active tenants' in-flight windows under an SLO —
        the scale-down/preemption rung (ROADMAP 1(c)): instead of
        waiting for tenant idleness, every waiting ``admit`` wakes with
        the typed :class:`WindowsRevoked` and the already-GRANTED
        windows (admit returned, ``note_served`` pending — at most one
        per consumer thread, the DRR burst bound) are waited out for at
        most ``slo_s`` seconds.  Size the SLO from the per-tenant p99
        window latency the tenancy bench measures
        (``per_tenant.<t>.p99_window_latency_s``): one p99 is the time
        a granted window legitimately needs to finish its ring acquire.

        ``names=None`` revokes every registered tenant (a whole-host
        drain); a list narrows it.  Returns True when all revoked
        in-flight windows completed inside the SLO.  Revoked tenants
        stay refused until :meth:`clear_revocations` (rejoin).
        """
        deadline = self._clock() + max(0.0, slo_s)
        with self._cond:
            targets = [
                st
                for n, st in self._tenants.items()
                if names is None or n in names
            ]
            for st in targets:
                st.revoked = True
            self._cond.notify_all()
            # ONE bounded wait per pass (DDL019 shape): the fan-out
            # above only flips flags; the SLO wait lives outside it.
            while any(st.inflight > 0 for st in targets):
                rem = deadline - self._clock()
                if rem <= 0:
                    break
                self._cond.wait(min(0.05, rem))
            leftover = sum(st.inflight for st in targets)
        self.metrics.incr("serve.revocations")
        if leftover:
            self.metrics.incr("serve.revoked_inflight", float(leftover))
            logger.warning(
                "serve: %d in-flight window(s) still unfinished at the "
                "%.2fs revocation SLO — proceeding with the drain",
                leftover, slo_s,
            )
        return leftover == 0

    def clear_revocations(
        self, names: "Optional[list] | None" = None
    ) -> None:
        """Re-admit previously revoked tenants (the rejoin edge)."""
        with self._cond:
            for n, st in self._tenants.items():
                if names is None or n in names:
                    st.revoked = False
            self._cond.notify_all()

    # -- failover state transfer (ddl_tpu.cluster.supervision) -------------

    def export_state(self, now: Optional[float] = None) -> dict:
        """Snapshot the full DRR ledger as a JSON-serializable dict —
        the supervisor journal's scheduler record.

        Clock handling: absolute token-bucket stamps are exported
        together with the export-time ``now``; :meth:`adopt_state`
        shifts them by its own clock delta, so a snapshot adopted with
        the same ``now`` roundtrips BIT-EXACT (the property the
        failover suite pins) and one adopted later ages the buckets by
        exactly the elapsed gap.  Live thread state (``waiting`` — the
        blocked callers themselves) is deliberately NOT exported: a
        promoted standby has its own callers; the ledger (deficits,
        buckets, round/slot counters, in-flight grants, revocation
        flags) is what fairness continuity needs.
        """
        with self._cond:
            if now is None:
                now = self._clock()
            return {
                "version": 1,
                "now": float(now),
                "quantum_bytes": self.quantum_bytes,
                "round": self._round,
                "next_index": self._next_index,
                "tenants": {
                    name: {
                        "spec": {
                            "name": st.spec.name,
                            "weight": st.spec.weight,
                            "byte_budget_per_s": st.spec.byte_budget_per_s,
                            "slot_budget": st.spec.slot_budget,
                        },
                        "index": st.index,
                        "deficit": st.deficit,
                        "tokens": st.tokens,
                        "stamp": st.stamp,
                        "served_in_round": st.served_in_round,
                        "inflight": st.inflight,
                        "revoked": st.revoked,
                    }
                    for name, st in self._tenants.items()
                },
            }

    def adopt_state(self, state: dict, now: Optional[float] = None) -> None:
        """Replace this scheduler's ledger with an exported snapshot
        (the promoted standby's half of :meth:`export_state`).

        The adopted scheduler grants the same next-admission order the
        snapshot's owner would have: deficits, buckets (aged by the
        export→adopt clock gap), per-round slot counters, and the DRR
        round/registration cursors all carry over.
        """
        if state.get("version") != 1:
            raise DDLError(
                f"unknown scheduler snapshot version {state.get('version')!r}"
            )
        with self._cond:
            if now is None:
                now = self._clock()
            shift = float(now) - float(state["now"])
            self.quantum_bytes = float(state["quantum_bytes"])
            self._round = int(state["round"])
            self._next_index = int(state["next_index"])
            adopted: Dict[str, _TenantState] = {}
            for name, t in state["tenants"].items():
                spec = TenantSpec(**t["spec"])
                st = _TenantState(spec, int(t["index"]), float(now))
                st.deficit = float(t["deficit"])
                st.tokens = float(t["tokens"])
                st.stamp = float(t["stamp"]) + shift
                st.served_in_round = int(t["served_in_round"])
                st.inflight = int(t["inflight"])
                st.revoked = bool(t["revoked"])
                adopted[name] = st
            self._tenants = adopted
            self.metrics.set_gauge("serve.tenants", len(self._tenants))
            self._cond.notify_all()

    # -- internals (condition lock held) -----------------------------------

    def _state(self, name: str) -> _TenantState:
        with self._cond:
            st = self._tenants.get(name)
            if st is None:
                raise DDLError(f"tenant {name!r} is not registered")
            return st

    def _grantable(self, st: _TenantState) -> bool:
        if st.spec.byte_budget_per_s > 0 and st.tokens < 0:
            return False
        if st.spec.slot_budget > 0 and (
            st.served_in_round >= st.spec.slot_budget
        ):
            return False
        return st.deficit >= 0

    def _budget_blocked(self, st: _TenantState) -> bool:
        """Blocked by the WALL-CLOCK token bucket (only time heals it —
        a replenish round must not bypass the rate budget)."""
        return st.spec.byte_budget_per_s > 0 and st.tokens < 0

    def _advance_round_if_stuck(self) -> bool:
        """One DRR replenish round, taken only when every waiting tenant
        is blocked by deficit/slots (not by its wall-clock byte budget):
        everyone earns ``quantum * weight`` credit — capped at one
        round's worth — and the per-round slot counters reset.  Returns
        True when a round advanced (the caller re-checks immediately)."""
        waiters = [t for t in self._tenants.values() if t.waiting]
        if not waiters:
            return False
        if any(self._grantable(t) for t in waiters):
            return False  # someone can proceed; fairness says wait for them
        if all(self._budget_blocked(t) for t in waiters):
            return False  # only the clock may refill a rate budget
        self._round += 1
        for t in self._tenants.values():
            credit = self.quantum_bytes * t.spec.weight
            t.deficit = min(t.deficit + credit, credit)
            t.served_in_round = 0
        self.metrics.incr("serve.rounds")
        self._cond.notify_all()
        return True

    def _charge_burst(
        self, name: str, st: _TenantState, nbytes: float
    ) -> None:
        """Absorb an injected :class:`TenantBurst` as phantom demand:
        the burst bytes are charged to the BURSTING tenant's deficit and
        bucket, so its neighbours' shares are untouched and the burster
        simply waits out its own spike."""
        with self._cond:
            st.refill(self._clock())
            st.deficit -= nbytes
            if st.spec.byte_budget_per_s > 0:
                st.tokens -= nbytes
        self.metrics.incr("serve.tenant_bursts")
        self.metrics.incr(f"ingest.{name}.bursts")
        logger.warning(
            "serve: tenant %r absorbed an injected burst of %.0f bytes",
            name, nbytes,
        )


class Tenant:
    """One registered tenant's handle: the admission object a loader
    binds (``loader.bind_admission(tenant)`` — or ``tenant.bind(loader)``)
    so every ring acquire passes through the fair-share gate."""

    def __init__(self, controller: "AdmissionController", spec: TenantSpec):
        self.controller = controller
        self.spec = spec
        self.name = spec.name
        self._closed = False

    # The two-method admission protocol DistributedDataLoader speaks.

    def admit(self, timeout_s: float) -> None:
        self.controller.scheduler.admit(self.name, timeout_s)

    def note_served(self, nbytes: int) -> None:
        self.controller.scheduler.note_served(self.name, nbytes)

    def note_aborted(self) -> None:
        self.controller.scheduler.note_aborted(self.name)

    def revoke_inflight(self, slo_s: float) -> bool:
        """Revoke THIS tenant's in-flight windows under ``slo_s``."""
        return self.controller.scheduler.revoke_inflight(
            slo_s, names=[self.name]
        )

    def clear_revocations(self) -> None:
        self.controller.scheduler.clear_revocations(names=[self.name])

    def bind(self, loader) -> "Tenant":
        """Attach this tenant's admission gate to a loader (and hand it
        the shared shard-cache tier's store for its producers via
        ``controller.cache`` if the caller wires that themselves)."""
        loader.bind_admission(self)
        return self

    def metrics(self) -> Dict[str, float]:
        """This tenant's ``ingest.<name>.*`` family, prefix-stripped."""
        return self.controller.metrics.prefixed(f"ingest.{self.name}.")

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self.controller._release(self.name)


class AdmissionController:
    """The tenancy facade: one shared scheduler + one shared shard-cache
    tier, fronted by :class:`Tenant` handles.

    ``cache`` is the shared :class:`~ddl_tpu.cache.CacheStore` every
    tenant's producers should be constructed over (``cache=`` kwarg on
    the shard readers) — the controller does not inject it into
    producers itself (producer functions cross spawn boundaries), it
    just owns the single instance so N tenants share one warm tier.
    """

    def __init__(
        self,
        scheduler: Optional[FairShareScheduler] = None,
        cache=None,
        metrics: Optional[Metrics] = None,
    ):
        self.metrics = metrics or default_metrics()
        self.scheduler = scheduler or FairShareScheduler(
            metrics=self.metrics
        )
        self.cache = cache
        # name -> Tenant handle; bounded by the registered tenant set.
        self._handles: Dict[str, Tenant] = {}  # ddl-lint: disable=DDL013

    def register(self, spec: TenantSpec) -> Tenant:
        self.scheduler.register(spec)
        handle = Tenant(self, spec)
        self._handles[spec.name] = handle
        return handle

    def tenant(self, name: str) -> Tenant:
        return self._handles[name]

    def _release(self, name: str) -> None:
        self.scheduler.unregister(name)
        self._handles.pop(name, None)

    def revoke_inflight(self, slo_s: float) -> bool:
        """Revoke EVERY tenant's in-flight windows under ``slo_s`` —
        the whole-host drain the :class:`~ddl_tpu.resilience.
        PreemptionGuard` runs (ROADMAP 1(c)); see
        :meth:`FairShareScheduler.revoke_inflight`."""
        return self.scheduler.revoke_inflight(slo_s)

    def clear_revocations(self) -> None:
        self.scheduler.clear_revocations()

    def report(self) -> dict:
        """Per-tenant ``ingest.<t>.*`` blocks plus the ``serve.*``
        aggregates — the bench's ``tenancy.per_tenant`` body.  Also
        refreshes the per-tenant ``serve.stall.<t>`` gauges (admission
        wait over scheduler wall time) that ``north_star_report``
        surfaces."""
        m = self.metrics
        elapsed = max(m.elapsed_s(), 1e-9)
        per_tenant = {}
        for name in self.scheduler.tenants():
            block = m.prefixed(f"ingest.{name}.")
            wait = m.timer(f"ingest.{name}.admission_wait")
            block["admission_wait_s"] = wait.total_s
            # First-class percentiles off the bounded histogram the
            # admit path observes into (ddl_tpu.obs) — the same values
            # north_star_report's per-tenant dict surfaces.
            block["admission_wait_p50_s"] = m.quantile(
                f"ingest.{name}.admission_wait", 0.5
            )
            block["admission_wait_p99_s"] = m.quantile(
                f"ingest.{name}.admission_wait", 0.99
            )
            stall = wait.total_s / elapsed
            m.set_gauge(f"serve.stall.{name}", stall)
            block["stall_fraction"] = stall
            per_tenant[name] = block
        return {
            "tenants": per_tenant,
            "admissions": m.counter("serve.admissions"),
            "rounds": m.counter("serve.rounds"),
            "tenant_bursts": m.counter("serve.tenant_bursts"),
            "admission_wait_s": m.timer("serve.admission_wait").total_s,
        }

    def close(self) -> None:
        for name in list(self._handles):
            self._handles[name].close()
