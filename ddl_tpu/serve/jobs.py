"""Job model for the multi-job ingest fabric (ROADMAP item 1).

A **job** is one independent training program drawing windows from the
shared loader fleet: the fabric's unit of admission, isolation, and
accounting.  This module is the pure data half of
:mod:`ddl_tpu.serve.fabric` — specs, the registry the supervisor
journals, and the per-job isolation seams:

- **Integrity namespace.**  Every job owns a disjoint 2^32-window slice
  of the integrity trailer's u64 ``seq`` space
  (:func:`integrity_namespace`): producers serving job J stamp
  ``seq_base(J) + iteration`` and J's loader expects exactly that
  range, so a window that leaks across jobs (a misrouted ring, a stale
  shared-cache mapping) fails seq verification instead of silently
  feeding the wrong trainer.  The base rides the producer function as a
  ``seq_base`` attribute — the ``wire_dtype`` handshake pattern — so it
  crosses the spawn boundary for free.
- **Checkpoint cursors.**  :meth:`JobRecord.checkpoint_dir` maps each
  job to its own ``resilience/`` generation directory, so cursor+step
  fencing (``ddl_tpu.resilience.ckpt``) is per job: job A's restore can
  never resurrect job B's cursor.
- **Obs namespace.**  :meth:`JobRecord.obs_prefix` is the
  ``job.<id>.*`` family the fabric merges worker registries under —
  the PR-15 ``producer.<idx>.*`` merge pattern, one level up
  (:func:`ddl_tpu.obs.aggregate.adopt_job`).
- **Cache accounting.**  :class:`JobCacheView` fronts the ONE shared
  :class:`~ddl_tpu.cache.CacheStore` with per-job hit/miss counters
  (``job.<id>.cache.*``) so the bench can attribute warm-tier value to
  the jobs that earn it.

The registry snapshot (:meth:`JobRegistry.export_state` /
:meth:`adopt_state`) roundtrips bit-exact — the same contract
``FairShareScheduler`` keeps — because it is journaled beside the
scheduler ledger and a promoted supervisor must reconstruct BOTH to
continue the admission order (docs/SERVING.md).
"""

from __future__ import annotations

import dataclasses
import os
import zlib

from ddl_tpu.concurrency import named_lock
from typing import Any, Dict, List, Optional

from ddl_tpu.exceptions import DDLError
from ddl_tpu.observability import Metrics, metrics as default_metrics
from ddl_tpu.serve.tenancy import TenantSpec

#: Width of each job's integrity-seq slice: bases are spaced 2^32
#: windows apart, far past any real run's window count.
NAMESPACE_SPAN = 1 << 32


def integrity_namespace(job_id: str) -> int:
    """Deterministic integrity-seq base for ``job_id``: a crc32-derived
    slot index scaled by :data:`NAMESPACE_SPAN`.  Stable across hosts
    and restarts (pure function of the id); collisions between distinct
    ids are possible in principle and rejected at registration
    (:meth:`JobRegistry.register`), where renaming is cheap."""
    return (zlib.crc32(job_id.encode("utf-8")) & 0xFFFFFFFF) * NAMESPACE_SPAN


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One training job's admission contract against the fabric.

    The fields mirror :class:`~ddl_tpu.serve.tenancy.TenantSpec` —
    a job IS a tenant of the fabric's resident scheduler — plus the
    job identity the isolation seams key on.
    """

    job_id: str
    weight: float = 1.0
    byte_budget_per_s: float = 0.0
    slot_budget: int = 0

    def __post_init__(self) -> None:
        if not self.job_id or "." in self.job_id or "/" in self.job_id:
            # The id becomes a metrics key segment (job.<id>.*) AND a
            # checkpoint path segment — dots would alias metric
            # families, slashes would escape the checkpoint root.
            raise DDLError(f"invalid job id {self.job_id!r}")
        if self.weight <= 0:
            raise DDLError(f"job weight must be > 0, got {self.weight}")
        if self.byte_budget_per_s < 0 or self.slot_budget < 0:
            raise DDLError("job budgets must be >= 0")

    def tenant_spec(self) -> TenantSpec:
        """The scheduler-facing half: jobs register in the fabric's
        ``FairShareScheduler`` under their own id."""
        return TenantSpec(
            name=self.job_id,
            weight=self.weight,
            byte_budget_per_s=self.byte_budget_per_s,
            slot_budget=self.slot_budget,
        )

    def to_dict(self) -> dict:
        return {
            "job_id": self.job_id,
            "weight": self.weight,
            "byte_budget_per_s": self.byte_budget_per_s,
            "slot_budget": self.slot_budget,
        }


@dataclasses.dataclass(frozen=True)
class JobRecord:
    """One registered job: the spec plus the fabric-assigned identity
    (registration index for fault-site selection, integrity-seq base
    for namespace isolation)."""

    spec: JobSpec
    index: int
    seq_base: int

    @property
    def job_id(self) -> str:
        return self.spec.job_id

    @property
    def obs_prefix(self) -> str:
        """The job's metric family — the ``producer.<idx>.*`` merge
        pattern one level up."""
        return f"job.{self.spec.job_id}."

    def checkpoint_dir(self, root: str) -> str:
        """This job's private ``resilience/`` generation directory
        under the shared checkpoint root (created on first use)."""
        path = os.path.join(root, f"job-{self.spec.job_id}")
        os.makedirs(path, exist_ok=True)
        return path


class JobRegistry:
    """The fabric's job table: id → :class:`JobRecord`, with the same
    export/adopt snapshot contract the scheduler keeps so registrations
    survive supervisor failover bit-exact.

    Thread-safe under its own lock (``serve.fabric.jobs``): the fabric
    apply path mutates it while bench reporters read it.
    """

    def __init__(self, metrics: Optional[Metrics] = None):
        self.metrics = metrics or default_metrics()
        self._lock = named_lock("serve.fabric.jobs")
        # job_id -> record: bounded by the registered job set.
        self._jobs: Dict[str, JobRecord] = {}  # ddl-lint: disable=DDL013
        self._next_index = 0

    def register(self, spec: JobSpec) -> JobRecord:
        with self._lock:
            if spec.job_id in self._jobs:
                raise DDLError(f"job {spec.job_id!r} is already registered")
            base = integrity_namespace(spec.job_id)
            for rec in self._jobs.values():
                if rec.seq_base == base:
                    # A crc32 collision between distinct ids: renaming
                    # one job is cheap; silently sharing a namespace
                    # would void the isolation guarantee.
                    raise DDLError(
                        f"job {spec.job_id!r} collides with "
                        f"{rec.job_id!r} in the integrity namespace — "
                        "rename one of them"
                    )
            rec = JobRecord(spec=spec, index=self._next_index, seq_base=base)
            self._next_index += 1
            self._jobs[spec.job_id] = rec
            self.metrics.set_gauge("fabric.jobs", len(self._jobs))
            return rec

    def unregister(self, job_id: str) -> Optional[JobRecord]:
        with self._lock:
            rec = self._jobs.pop(job_id, None)
            if rec is not None:
                self.metrics.set_gauge("fabric.jobs", len(self._jobs))
            return rec

    def get(self, job_id: str) -> JobRecord:
        with self._lock:
            rec = self._jobs.get(job_id)
            if rec is None:
                raise DDLError(f"job {job_id!r} is not registered")
            return rec

    def jobs(self) -> List[str]:
        with self._lock:
            return sorted(self._jobs)

    def __len__(self) -> int:
        with self._lock:
            return len(self._jobs)

    def __contains__(self, job_id: str) -> bool:
        with self._lock:
            return job_id in self._jobs

    # -- failover state transfer (the scheduler export/adopt contract) --

    def export_state(self) -> dict:
        """Snapshot the registry as a JSON-serializable dict; adopting
        the same snapshot roundtrips bit-exact (the failover suite
        pins export → adopt → export equality)."""
        with self._lock:
            return {
                "version": 1,
                "next_index": self._next_index,
                "jobs": {
                    job_id: {
                        "spec": rec.spec.to_dict(),
                        "index": rec.index,
                        "seq_base": rec.seq_base,
                    }
                    for job_id, rec in self._jobs.items()
                },
            }

    def adopt_state(self, state: dict) -> None:
        if state.get("version") != 1:
            raise DDLError(
                f"unknown job-registry snapshot version "
                f"{state.get('version')!r}"
            )
        with self._lock:
            adopted: Dict[str, JobRecord] = {}
            for job_id, rec in state["jobs"].items():
                adopted[job_id] = JobRecord(
                    spec=JobSpec(**rec["spec"]),
                    index=int(rec["index"]),
                    seq_base=int(rec["seq_base"]),
                )
            self._jobs = adopted
            self._next_index = int(state["next_index"])
            self.metrics.set_gauge("fabric.jobs", len(self._jobs))


class JobCacheView:
    """Per-job accounting facade over the ONE shared
    :class:`~ddl_tpu.cache.CacheStore`.

    The store's ``cache.*`` counters stay fleet-global; this view adds
    ``job.<id>.cache.hits`` / ``.misses`` so the bench can attribute
    warm-tier value per job.  It holds no entries of its own — eviction
    and spill policy remain the shared store's.
    """

    def __init__(self, store: Any, job_id: str, metrics: Optional[Metrics] = None):
        self.store = store
        self.job_id = job_id
        self.metrics = metrics or default_metrics()
        self._prefix = f"job.{job_id}.cache."

    def get(self, key: Any) -> Any:
        arr = self.store.get(key)
        self.metrics.incr(
            self._prefix + ("hits" if arr is not None else "misses")
        )
        return arr

    def put(self, key: Any, arr: Any) -> Any:
        return self.store.put(key, arr)

    def get_or_load(self, key: Any, loader: Any) -> Any:
        arr = self.get(key)
        if arr is None:
            arr = self.store.put(key, loader())
        return arr

    def contains(self, key: Any) -> bool:
        return self.store.contains(key)

    def counts(self) -> Dict[str, float]:
        """This job's ``{hits, misses}`` counter pair."""
        return {
            "hits": self.metrics.counter(self._prefix + "hits"),
            "misses": self.metrics.counter(self._prefix + "misses"),
        }
