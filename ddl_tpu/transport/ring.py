"""Abstract SPSC window ring — the TPU-native ``Connection`` data plane.

This replaces the reference's MPI-3 RMA machinery (``Win.Allocate_shared`` +
``Lock_all`` passive epochs + zero-byte ``Ssend``/``Issend`` token ping-pong,
reference ``ddl/connection.py:88-182``) with a single-producer
single-consumer ring of window-sized slots:

- The reference's "access epoch token" (tag-7 message, ``connection.py:153-182``)
  becomes a pair of monotonic counters (``committed`` by the producer,
  ``released`` by the consumer) with acquire/release memory ordering.
- The reference's one-window-per-producer strict alternation is the
  ``nslots=1`` special case; ``nslots>=2`` delivers the double-buffering the
  reference left as a ToDo (reference ``ddl/mpi_dataloader.py:21-28``).
- The reference's shutdown Ibarrier race (``connection.py:36-37,184-187``)
  becomes a shutdown flag observed by every blocked wait: any wait returns
  by raising :class:`ShutdownRequested`, matching the any-time
  cancellability of ``MPI.Request.Waitany`` + ``Cancel``.

Three interchangeable implementations:

- :class:`ThreadRing` (this module) — in-process, for THREAD mode and tests.
- ``NativeShmRing`` (``shm_ring.py``) — C++ atomics over POSIX shm, the
  production cross-process path.
- ``PyShmRing`` (``shm_ring.py``) — pure-Python fallback with the same
  memory layout.
"""

from __future__ import annotations

import abc
import threading

from ddl_tpu.concurrency import named_condition
import time
from typing import Dict

import numpy as np

from ddl_tpu.exceptions import ShutdownRequested, StallTimeoutError
from ddl_tpu.faults import fault_point

#: Default wait deadline. The reference had none — a lost peer hung forever
#: (SURVEY §5.3); 5 minutes is generous for any real refill.
DEFAULT_TIMEOUT_S = 300.0


class WindowRing(abc.ABC):
    """SPSC ring of fixed-size window slots.

    Producer side: ``acquire_fill() -> slot``, write into ``slot_view``,
    ``commit(slot, nbytes)``.  Consumer side: ``acquire_drain() -> slot``,
    read ``slot_view``, ``release(slot)``.  Slots hand off in FIFO order.
    """

    nslots: int
    slot_bytes: int

    # -- producer side -----------------------------------------------------
    @abc.abstractmethod
    def acquire_fill(self, timeout_s: float = DEFAULT_TIMEOUT_S) -> int:
        """Block until a free slot is available; return its index."""

    @abc.abstractmethod
    def commit(self, slot: int, payload_bytes: int) -> None:
        """Publish a filled slot to the consumer."""

    # -- consumer side -----------------------------------------------------
    @abc.abstractmethod
    def acquire_drain(self, timeout_s: float = DEFAULT_TIMEOUT_S) -> int:
        """Block until a committed slot is available; return its index."""

    def acquire_drain_ahead(
        self, ahead: int, timeout_s: float = DEFAULT_TIMEOUT_S
    ) -> int:
        """Acquire the next committed slot while still holding ``ahead``
        drained-but-unreleased slots (the double-buffered window-stream
        lookahead).  ``ahead == 0`` is exactly :meth:`acquire_drain`.
        Slots must still be released in FIFO order.
        """
        if ahead == 0:
            return self.acquire_drain(timeout_s)
        raise NotImplementedError(
            f"{type(self).__name__} does not support drain lookahead"
        )

    def poll_drain_ready(self, ahead: int = 0) -> bool:
        """Non-blocking: would :meth:`acquire_drain_ahead` succeed now?

        A cheap counter comparison with no wait machinery or stall
        accounting — the window-stream lookahead probes with this before
        acquiring, so a not-yet-committed window costs one read instead
        of a timed wait event (which would inflate wait-event frequency
        in stall diagnostics on slow-producer runs).  SPSC makes the
        answer stable: only the caller (the consumer) can consume the
        committed slot the peek observed.
        """
        s = self.stats()
        return s["committed"] - s["released"] > ahead

    @abc.abstractmethod
    def release(self, slot: int) -> None:
        """Return a drained slot to the producer."""

    # -- shared ------------------------------------------------------------
    @abc.abstractmethod
    def slot_view(self, slot: int) -> np.ndarray:
        """Zero-copy uint8 view of the slot payload region."""

    @abc.abstractmethod
    def slot_payload(self, slot: int) -> int:
        """Committed payload byte count of the slot."""

    @abc.abstractmethod
    def shutdown(self) -> None:
        """Wake every blocked wait with :class:`ShutdownRequested`."""

    @abc.abstractmethod
    def is_shutdown(self) -> bool: ...

    @abc.abstractmethod
    def stats(self) -> Dict[str, float]:
        """Stall/progress counters: producer_stall_s, consumer_stall_s,
        committed, released."""

    def close(self) -> None:  # pragma: no cover - overridden where needed
        pass

    def unlink(self) -> None:  # pragma: no cover
        pass


class ThreadRing(WindowRing):
    """In-process ring over plain numpy buffers and a condition variable.

    Backs THREAD mode, where producers are threads of the trainer process —
    the fix for SURVEY Q9 (the reference silently yielded an empty loader
    without MPI, reference ``ddl/mpi_dataloader.py:173-174``).
    """

    def __init__(self, nslots: int, slot_bytes: int):
        if nslots < 1:
            raise ValueError("nslots must be >= 1")
        self.nslots = nslots
        self.slot_bytes = slot_bytes
        self._slots = [np.zeros(slot_bytes, dtype=np.uint8) for _ in range(nslots)]
        self._payload = [0] * nslots
        self._committed = 0
        self._released = 0
        self._shutdown = False
        self._cond = named_condition("transport.ring.cond")
        self._prod_stall = 0.0
        self._cons_stall = 0.0

    def _wait(self, pred, timeout_s: float, stall_attr: str) -> None:
        t0 = time.perf_counter()
        try:
            with self._cond:
                # Shutdown first, matching the native ring: post-shutdown,
                # trailing committed slots are dropped, not drained.
                while True:
                    if self._shutdown:
                        raise ShutdownRequested()
                    if pred():
                        break
                    remaining = timeout_s - (time.perf_counter() - t0)
                    if remaining <= 0:
                        raise StallTimeoutError(
                            f"ring wait exceeded {timeout_s}s "
                            f"(committed={self._committed} released={self._released})"
                        )
                    self._cond.wait(min(remaining, 0.5))
        finally:
            setattr(
                self, stall_attr,
                getattr(self, stall_attr) + time.perf_counter() - t0,
            )

    def acquire_fill(self, timeout_s: float = DEFAULT_TIMEOUT_S) -> int:
        fault_point("ring.fill", should_abort=self.is_shutdown)
        self._wait(
            lambda: self._committed - self._released < self.nslots,
            timeout_s,
            "_prod_stall",
        )
        return self._committed % self.nslots

    def commit(self, slot: int, payload_bytes: int) -> None:
        with self._cond:
            assert slot == self._committed % self.nslots, "out-of-order commit"
            self._payload[slot] = payload_bytes
            self._committed += 1
            self._cond.notify_all()

    def acquire_drain(self, timeout_s: float = DEFAULT_TIMEOUT_S) -> int:
        fault_point("ring.drain", should_abort=self.is_shutdown)
        self._wait(
            lambda: self._committed > self._released, timeout_s, "_cons_stall"
        )
        return self._released % self.nslots

    def acquire_drain_ahead(
        self, ahead: int, timeout_s: float = DEFAULT_TIMEOUT_S
    ) -> int:
        if not 0 <= ahead < self.nslots:
            raise ValueError(
                f"ahead must be in [0, nslots={self.nslots}), got {ahead}"
            )
        self._wait(
            lambda: self._committed > self._released + ahead,
            timeout_s,
            "_cons_stall",
        )
        return (self._released + ahead) % self.nslots

    def release(self, slot: int) -> None:
        with self._cond:
            assert slot == self._released % self.nslots, "out-of-order release"
            self._released += 1
            self._cond.notify_all()

    def slot_view(self, slot: int) -> np.ndarray:
        return self._slots[slot]

    def slot_payload(self, slot: int) -> int:
        return self._payload[slot]

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def is_shutdown(self) -> bool:
        return self._shutdown

    def stats(self) -> Dict[str, float]:
        return {
            "producer_stall_s": self._prod_stall,
            "consumer_stall_s": self._cons_stall,
            "committed": float(self._committed),
            "released": float(self._released),
        }
