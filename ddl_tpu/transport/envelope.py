"""Acked control-envelope seam: at-least-once + dedup for the control plane.

Until PR 18 every control-channel send (``ShardAdoption`` re-partitions,
``ReplayRequest`` rewinds) was fire-and-forget: one lost or duplicated
pipe write silently stranded an adoption or double-applied a replay —
an *implicit* exactly-once assumption with no delivery model behind it.
This module makes the contract explicit:

- **At-least-once.**  :class:`ControlSender` wraps each payload in a
  :class:`~ddl_tpu.types.ControlEnvelope` carrying ``(incarnation,
  seq)`` and retries unacked sends with exponential backoff
  (``DDL_TPU_CTRL_BACKOFF_S`` doubling, ``DDL_TPU_CTRL_RETRIES`` cap).
- **Dedup.**  :class:`EnvelopeReceiver` suppresses re-deliveries by
  ``(incarnation, seq)``: a duplicate is re-acked (the sender's retry
  must terminate) but never re-applied.
- **Fencing.**  Every envelope carries the sender's fencing term
  (:mod:`ddl_tpu.cluster.supervision`): a receiver that has seen a
  newer term drops the payload unapplied but still acks — a zombie
  ex-leader's stale commands die at every applier, and the zombie's
  retry loop drains instead of spinning forever.

Chaos coverage rides the ``transport.control_send`` fault site inside
:meth:`ControlSender._wire`: ``CONTROL_MSG_DROP``/``NETWORK_PARTITION``
lose the wire attempt (the send stays pending; backoff retry absorbs
it), ``CONTROL_MSG_DUP`` sends the same envelope twice (the receiver's
dedup absorbs it).  Both legs are asserted with counters by the
``DDL_BENCH_MODE=failover`` chaos leg and ``tests/test_supervision.py``.

Threading: :class:`ControlSender` is intentionally lock-free —
:class:`~ddl_tpu.transport.connection.ConsumerConnection` serializes
every sender operation (send / pump / ack routing) under its existing
``transport.connection`` rlock, exactly as raw ``send_control`` already
was.  :class:`EnvelopeReceiver` lives on the producer's single control
thread (``DataPusher._poll_control``) and needs no lock at all.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ddl_tpu import envspec, faults
from ddl_tpu.exceptions import TransportError
from ddl_tpu.faults import FaultKind
from ddl_tpu.types import ControlAck, ControlEnvelope


class _Pending:
    """One unacked envelope: wire attempts so far + next retry due."""

    __slots__ = ("envelope", "attempts", "due", "backoff_s")

    def __init__(self, envelope: ControlEnvelope, due: float, backoff_s: float):
        self.envelope = envelope
        self.attempts = 1
        self.due = due
        self.backoff_s = backoff_s


class ControlSender:
    """Per-target acked sender (consumer → one producer).

    ``raw_send`` is the wire primitive (a closure over the live channel
    slot, so elastic channel swaps are transparent); ``target`` names
    the producer for fault-site matching and diagnostics.  All state
    mutation must happen under the owner's lock — see the module
    docstring.
    """

    def __init__(
        self,
        raw_send: Callable[[Any], None],
        target: int,
        incarnation: int = 0,
        metrics: Any = None,
        retries: Optional[int] = None,
        backoff_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._raw_send = raw_send
        self.target = target
        self.incarnation = int(incarnation)
        self.metrics = metrics
        self.retries = (
            int(envspec.get("DDL_TPU_CTRL_RETRIES"))
            if retries is None else int(retries)
        )
        self.backoff_s = (
            float(envspec.get("DDL_TPU_CTRL_BACKOFF_S"))
            if backoff_s is None else float(backoff_s)
        )
        self._clock = clock
        self.fence = 0
        self._next_seq = 0
        # seq -> pending retry state: bounded by outstanding sends (acks
        # and the retry cap both clear entries).
        self._pending: Dict[int, _Pending] = {}  # ddl-lint: disable=DDL013
        #: Envelopes that exhausted the retry cap unacked, for callers
        #: that escalate (the HA tier re-fences; tests introspect).
        self.exhausted: List[ControlEnvelope] = []

    # -- sending -----------------------------------------------------------

    def send(self, payload: Any) -> int:
        """Wrap ``payload`` in a fenced envelope, register it pending,
        and make the first wire attempt.  Returns the assigned seq."""
        seq = self._next_seq
        self._next_seq += 1
        env = ControlEnvelope(
            seq=seq,
            incarnation=self.incarnation,
            fence=self.fence,
            payload=payload,
        )
        self._pending[seq] = _Pending(
            env, due=self._clock() + self.backoff_s, backoff_s=self.backoff_s
        )
        self._wire(env)
        return seq

    def _wire(self, env: ControlEnvelope) -> None:
        """One wire attempt.  A lost attempt (chaos drop/partition, a
        real broken pipe) leaves the envelope pending for ``pump``."""
        try:
            fired = faults.fault_point(  # ddl-verify: disable=VP002
                "transport.control_send", producer_idx=self.target
            )
            self._raw_send(env)
            if fired and FaultKind.CONTROL_MSG_DUP.value in fired:
                # The duplicate is the SAME envelope — the receiver's
                # (incarnation, seq) dedup is what the injection tests.
                self._raw_send(env)
                self._incr("ctrl.wire_dups")
        except TransportError:
            # Injected drop/partition, or an adapter reporting a real
            # wire loss as its typed error: the attempt is gone, the
            # envelope stays pending, backoff retry absorbs it.
            self._incr("ctrl.wire_drops")
        except (OSError, ValueError):
            # Broken/closed pipe mid-swap: same contract as above — the
            # elastic rejoin will restore the channel and pump retries.
            self._incr("ctrl.wire_drops")

    # -- retry / ack -------------------------------------------------------

    def pump(self, now: Optional[float] = None) -> int:
        """Re-send every due unacked envelope (exponential backoff).
        Past the retry cap an envelope is moved to :attr:`exhausted`
        and counted — never silently forgotten.  Returns resend count."""
        now = self._clock() if now is None else now
        resent = 0
        for seq in sorted(self._pending):
            p = self._pending.get(seq)
            if p is None or p.due > now:
                continue
            if p.attempts > self.retries:
                del self._pending[seq]
                self.exhausted.append(p.envelope)
                self._incr("ctrl.send_exhausted")
                continue
            p.attempts += 1
            p.backoff_s *= 2.0
            p.due = now + p.backoff_s
            self._wire(p.envelope)
            resent += 1
        if resent:
            self._incr("ctrl.retries", resent)
        return resent

    def ack(self, ack: ControlAck) -> bool:
        """Route one :class:`ControlAck` back; True when it cleared a
        pending envelope (stale/foreign acks are counted, not errors)."""
        if ack.incarnation != self.incarnation:
            self._incr("ctrl.stale_acks")
            return False
        p = self._pending.pop(ack.seq, None)
        if p is None:
            self._incr("ctrl.stale_acks")
            return False
        self._incr("ctrl.acked")
        if ack.dup:
            self._incr("ctrl.acked_dup")
        if ack.fence_rejected:
            self._incr("ctrl.fence_rejected")
        return True

    def pending_count(self) -> int:
        return len(self._pending)

    def _incr(self, name: str, value: float = 1.0) -> None:
        if self.metrics is not None:
            self.metrics.incr(name, value)


class EnvelopeReceiver:
    """Producer-side envelope unwrap: dedup + fencing + ack synthesis.

    ``accept`` returns ``(payload, ack)``: ``payload`` is the inner
    command to apply exactly once (``None`` for a duplicate or a
    fenced-off zombie command), ``ack`` always goes back on the wire —
    the sender's retry loop must terminate in every case.
    """

    #: Per-incarnation dedup window: seqs older than this many behind
    #: the newest are forgotten (a retry storm never spans thousands of
    #: outstanding control commands; window re-delivery past it would
    #: re-apply — sized far beyond any real pipeline's outstanding set).
    WINDOW = 4096

    def __init__(self, producer_idx: int = 0):
        self.producer_idx = int(producer_idx)
        #: Highest fencing term observed; commands below it are zombies.
        self.fence = 0
        self.dups = 0
        self.fence_drops = 0
        self.accepted = 0
        # incarnation -> seen seq set; only the two newest incarnations
        # are retained (older ones can no longer send).
        self._seen: Dict[int, Set[int]] = {}  # ddl-lint: disable=DDL013

    def accept(
        self, env: ControlEnvelope
    ) -> Tuple[Optional[Any], ControlAck]:
        ack = ControlAck(
            seq=env.seq,
            incarnation=env.incarnation,
            producer_idx=self.producer_idx,
        )
        if env.fence < self.fence:
            # A zombie ex-leader's stale command: drop unapplied, but
            # ack so the dead sender's retry loop drains.
            self.fence_drops += 1
            ack.fence_rejected = True
            return None, ack
        self.fence = max(self.fence, env.fence)
        seen = self._seen.get(env.incarnation)
        if seen is None:
            seen = self._seen[env.incarnation] = set()
            if len(self._seen) > 2:
                for inc in sorted(self._seen)[:-2]:
                    del self._seen[inc]
        if env.seq in seen:
            self.dups += 1
            ack.dup = True
            return None, ack
        seen.add(env.seq)
        if len(seen) > self.WINDOW:
            seen.discard(min(seen))
        self.accepted += 1
        return env.payload, ack

    def seed(self, incarnation: int, seq: int) -> None:
        """Pre-mark ``(incarnation, seq)`` as already applied — the
        journal-seeded dedup a rebuilt receiver runs after supervisor
        failover (:meth:`ddl_tpu.serve.fabric.IngestFabric.
        from_journal`): a retry of a command the DEAD leader applied
        must dedup here, not re-mutate the successor's ledger."""
        seen = self._seen.get(incarnation)
        if seen is None:
            seen = self._seen[incarnation] = set()
            if len(self._seen) > 2:
                for inc in sorted(self._seen)[:-2]:
                    del self._seen[inc]
        seen.add(int(seq))
        if len(seen) > self.WINDOW:
            seen.discard(min(seen))
