// shm_ring.cpp — native SPSC shared-memory window ring for ddl_tpu.
//
// TPU-native replacement for the reference's MPI-3 RMA shared-memory windows
// and token protocol (reference ddl/connection.py:88-182): the reference got
// cross-process window handoff from MPI's native core (Win.Allocate_shared +
// Lock_all + Ssend tokens); here the same semantics are implemented directly
// on POSIX shm + C11/C++ atomics:
//
//   * one shm segment per (producer, consumer) pair
//   * `committed` / `released` monotonic counters with release/acquire
//     ordering play the role of the zero-byte tag-7 token messages
//     (connection.py:153-182) — a slot's data is fully written before the
//     counter store that publishes it is visible (the property MPI gave via
//     synchronous-mode sends, connection.py:157-159)
//   * a `shutdown` flag observed inside every wait loop replaces the
//     cancellable Waitany-vs-Ibarrier race (connection.py:161-182, §3.5)
//   * waits are bounded (timeout) and account their stall time, feeding the
//     input-pipeline-stall% north-star metric (BASELINE.md)
//
// Exposed as a plain C ABI consumed via ctypes (ddl_tpu/transport/shm_ring.py).

#include <atomic>
#include <cerrno>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#ifdef __linux__
#include <linux/futex.h>
#include <sys/syscall.h>
#endif

namespace {

constexpr uint64_t kMagic = 0xDD17B0F5A11C0DE5ULL;
constexpr uint32_t kVersion = 2;  // v2: doorbell word in the header
constexpr size_t kCacheLine = 64;

struct alignas(kCacheLine) Header {
  uint64_t magic;
  uint32_t version;
  uint32_t nslots;
  uint64_t slot_bytes;
  uint64_t data_offset;  // byte offset of slot 0 payload from segment base
  // Producer- and consumer-owned counters on separate cache lines to avoid
  // false sharing in the spin loops.
  alignas(kCacheLine) std::atomic<uint64_t> committed;
  alignas(kCacheLine) std::atomic<uint64_t> released;
  alignas(kCacheLine) std::atomic<uint32_t> shutdown;
  // Futex doorbell: every publishable event (commit, release, shutdown)
  // increments it and wakes its waiters.  Waiters snapshot it BEFORE
  // evaluating their predicate and park with that snapshot as `expect`,
  // so an event landing between predicate check and park flips the word
  // and FUTEX_WAIT returns EAGAIN — the condition-variable pattern with
  // no lost-wake window, covering shutdown too (a flag store alone
  // could land after a waiter's check but before it parks).
  alignas(kCacheLine) std::atomic<uint32_t> doorbell;
  // Stall counters on their own line: they are fetch_add'ed from both
  // processes once per wait and must not bounce the hot doorbell line.
  alignas(kCacheLine) std::atomic<uint64_t> prod_stall_us;
  std::atomic<uint64_t> cons_stall_us;
  // Variable-length: per-slot committed payload sizes, then slot payloads.
  // payload_bytes[i] is written by the producer before the `committed`
  // release-store that publishes slot i, so the consumer's acquire-load
  // ordering covers it too.
  alignas(kCacheLine) uint64_t payload_bytes[1];
};

inline size_t header_bytes(uint32_t nslots) {
  size_t h = offsetof(Header, payload_bytes) + nslots * sizeof(uint64_t);
  return (h + kCacheLine - 1) / kCacheLine * kCacheLine;
}

inline uint64_t now_us() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000ull + ts.tv_nsec / 1000;
}

// Event-driven waits via the header doorbell (replaces the original
// 1ms-capped usleep ladder, which woke every idle producer ~1000x/s —
// each wake preempting the consumer on 1-core hosts).  NOT
// FUTEX_PRIVATE: waiter and waker are different processes sharing the
// mapping.
#ifdef __linux__
inline void futex_wait_on(std::atomic<uint32_t>* word, uint32_t expect,
                          int64_t timeout_us) {
  struct timespec ts;
  ts.tv_sec = timeout_us / 1000000;
  ts.tv_nsec = (timeout_us % 1000000) * 1000;
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(word), FUTEX_WAIT, expect,
          &ts, nullptr, 0);
}

inline void futex_wake_all(std::atomic<uint32_t>* word) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(word), FUTEX_WAKE,
          INT32_MAX, nullptr, nullptr, 0);
}
#else
// Non-Linux POSIX: no cross-process futex — fall back to the bounded
// usleep ladder (1ms cap, the pre-doorbell behavior).  The caller's
// loop re-checks the predicate after every nap, so correctness is
// unchanged; only idle-wakeup cost regresses to ~1000/s.
inline void futex_wait_on(std::atomic<uint32_t>* word, uint32_t expect,
                          int64_t timeout_us) {
  if (word->load(std::memory_order_acquire) != expect) return;
  if (timeout_us > 1000 || timeout_us < 0) timeout_us = 1000;
  usleep(static_cast<useconds_t>(timeout_us));
}

inline void futex_wake_all(std::atomic<uint32_t>*) {}
#endif

}  // namespace

struct ddlr_ring {
  Header* hdr;
  size_t map_bytes;
  int owner;  // created (vs opened) — owner unlinks
  char name[256];
};

extern "C" {

ddlr_ring* ddlr_create(const char* name, uint32_t nslots, uint64_t slot_bytes) {
  if (nslots < 1 || slot_bytes == 0) return nullptr;
  // Tolerate a stale segment from a crashed prior run.
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t hbytes = header_bytes(nslots);
  size_t total = hbytes + static_cast<size_t>(nslots) * slot_bytes;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* h = static_cast<Header*>(base);
  std::memset(base, 0, hbytes);
  h->version = kVersion;
  h->nslots = nslots;
  h->slot_bytes = slot_bytes;
  h->data_offset = hbytes;
  h->committed.store(0, std::memory_order_relaxed);
  h->released.store(0, std::memory_order_relaxed);
  h->shutdown.store(0, std::memory_order_relaxed);
  // Publish the header last: openers spin on magic until init is complete.
  std::atomic_thread_fence(std::memory_order_release);
  h->magic = kMagic;

  ddlr_ring* r = new ddlr_ring();
  r->hdr = h;
  r->map_bytes = total;
  r->owner = 1;
  std::snprintf(r->name, sizeof(r->name), "%s", name);
  return r;
}

ddlr_ring* ddlr_open(const char* name) {
  int fd = -1;
  // The peer may not have created the segment yet — retry briefly.
  for (int i = 0; i < 2000; ++i) {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd >= 0) break;
    usleep(1000);
  }
  if (fd < 0) return nullptr;
  struct stat st;
  // Wait until the creator has ftruncated + written the header.
  for (int i = 0; i < 2000; ++i) {
    if (fstat(fd, &st) == 0 && st.st_size > static_cast<off_t>(sizeof(Header)))
      break;
    usleep(1000);
  }
  size_t total = static_cast<size_t>(st.st_size);
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  Header* h = static_cast<Header*>(base);
  for (int i = 0; i < 2000 && h->magic != kMagic; ++i) usleep(1000);
  if (h->magic != kMagic || h->version != kVersion) {
    munmap(base, total);
    return nullptr;
  }
  ddlr_ring* r = new ddlr_ring();
  r->hdr = h;
  r->map_bytes = total;
  r->owner = 0;
  std::snprintf(r->name, sizeof(r->name), "%s", name);
  return r;
}

// Wait until pred (expressed via counters) holds. Returns slot index >= 0,
// -1 on timeout, -2 on shutdown. Ladder: brief pause-spin (the peer may
// be mid-commit on another core), one sched_yield round (single-CPU
// hosts — the peer literally needs our timeslice), then an event-driven
// futex sleep on the doorbell.  The doorbell snapshot is taken BEFORE
// the predicate loads, so any event (commit/release/shutdown) landing
// after the check flips the word and the park returns immediately —
// no lost-wake window for any of the three events.  Futex chunks are
// capped at 100ms as pure paranoia (the protocol needs no polling); in
// the normal path the peer's wake lands in microseconds and idle
// waiters cost ZERO periodic wakeups — the property that matters when
// producers and consumer share one core (docs/PERF_NOTES.md).
static int wait_slot(ddlr_ring* r, bool producer, int64_t timeout_us,
                     uint32_t ahead = 0) {
  Header* h = r->hdr;
  uint64_t start = now_us();
  int spins = 0;
  for (;;) {
    uint32_t bell = h->doorbell.load(std::memory_order_acquire);
    if (h->shutdown.load(std::memory_order_acquire)) return -2;
    uint64_t committed = h->committed.load(std::memory_order_acquire);
    uint64_t released = h->released.load(std::memory_order_acquire);
    if (producer) {
      if (committed - released < h->nslots)
        return static_cast<int>(committed % h->nslots);
    } else {
      // ahead > 0: the consumer still holds `ahead` drained-but-unreleased
      // slots and wants the next committed one after those — the lookahead
      // primitive behind double-buffered window streaming.
      if (committed > released + ahead)
        return static_cast<int>((released + ahead) % h->nslots);
    }
    uint64_t waited = now_us() - start;
    if (timeout_us >= 0 && waited > static_cast<uint64_t>(timeout_us)) {
      return -1;
    }
    ++spins;
    if (spins < 64) {
#if defined(__x86_64__)
      __builtin_ia32_pause();
#endif
    } else if (spins < 96) {
      sched_yield();
    } else {
      int64_t chunk = 100000;  // pure lost-wake paranoia, not polling
      if (timeout_us >= 0) {
        int64_t left = timeout_us - static_cast<int64_t>(waited);
        if (left < chunk) chunk = left > 0 ? left : 1;
      }
      futex_wait_on(&h->doorbell, bell, chunk);
    }
  }
}

// Ring an event: memory effects of the event must be published (their
// release-stores) BEFORE this increment, whose own release-store orders
// it after them; parked waiters wake and re-evaluate.
static void ring_doorbell(Header* h) {
  h->doorbell.fetch_add(1, std::memory_order_release);
  futex_wake_all(&h->doorbell);
}

static void add_stall(std::atomic<uint64_t>& ctr, uint64_t t0) {
  uint64_t dt = now_us() - t0;
  if (dt) ctr.fetch_add(dt, std::memory_order_relaxed);
}

int ddlr_acquire_fill(ddlr_ring* r, int64_t timeout_us) {
  uint64_t t0 = now_us();
  int s = wait_slot(r, /*producer=*/true, timeout_us);
  add_stall(r->hdr->prod_stall_us, t0);
  return s;
}

void ddlr_commit(ddlr_ring* r, uint32_t slot, uint64_t payload_bytes) {
  Header* h = r->hdr;
  h->payload_bytes[slot] = payload_bytes;
  // Release-store publishes the payload and payload_bytes together.
  h->committed.store(h->committed.load(std::memory_order_relaxed) + 1,
                     std::memory_order_release);
  ring_doorbell(h);
}

int ddlr_acquire_drain(ddlr_ring* r, int64_t timeout_us) {
  uint64_t t0 = now_us();
  int s = wait_slot(r, /*producer=*/false, timeout_us);
  add_stall(r->hdr->cons_stall_us, t0);
  return s;
}

// Acquire the (ahead+1)-th oldest committed slot while the consumer still
// holds `ahead` unreleased ones. Returns -3 when ahead >= nslots (the ring
// cannot hold that many outstanding drains). Release order stays FIFO.
int ddlr_acquire_drain_ahead(ddlr_ring* r, uint32_t ahead, int64_t timeout_us) {
  if (ahead >= r->hdr->nslots) return -3;
  uint64_t t0 = now_us();
  int s = wait_slot(r, /*producer=*/false, timeout_us, ahead);
  add_stall(r->hdr->cons_stall_us, t0);
  return s;
}

void ddlr_release(ddlr_ring* r, uint32_t slot) {
  (void)slot;
  Header* h = r->hdr;
  h->released.store(h->released.load(std::memory_order_relaxed) + 1,
                    std::memory_order_release);
  ring_doorbell(h);
}

uint8_t* ddlr_slot_ptr(ddlr_ring* r, uint32_t slot) {
  Header* h = r->hdr;
  return reinterpret_cast<uint8_t*>(h) + h->data_offset +
         static_cast<size_t>(slot) * h->slot_bytes;
}

uint64_t ddlr_slot_payload(ddlr_ring* r, uint32_t slot) {
  return r->hdr->payload_bytes[slot];
}

void ddlr_shutdown(ddlr_ring* r) {
  r->hdr->shutdown.store(1, std::memory_order_release);
  // The doorbell snapshot/park protocol makes this wake reliable even
  // against a waiter preempted between its flag check and its park.
  ring_doorbell(r->hdr);
}

int ddlr_is_shutdown(ddlr_ring* r) {
  return static_cast<int>(r->hdr->shutdown.load(std::memory_order_acquire));
}

uint64_t ddlr_stat(ddlr_ring* r, int which) {
  Header* h = r->hdr;
  switch (which) {
    case 0: return h->prod_stall_us.load(std::memory_order_relaxed);
    case 1: return h->cons_stall_us.load(std::memory_order_relaxed);
    case 2: return h->committed.load(std::memory_order_relaxed);
    case 3: return h->released.load(std::memory_order_relaxed);
    default: return 0;
  }
}

uint32_t ddlr_nslots(ddlr_ring* r) { return r->hdr->nslots; }
uint64_t ddlr_slot_bytes(ddlr_ring* r) { return r->hdr->slot_bytes; }

void ddlr_close(ddlr_ring* r) {
  if (!r) return;
  munmap(r->hdr, r->map_bytes);
  delete r;
}

void ddlr_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
