"""Transport layer: SPSC window rings + control-plane channels.

TPU-native re-design of reference ``ddl/connection.py`` — see
``ring.py`` (protocol + in-process ring), ``shm_ring.py`` (native C++
cross-process ring and Python fallback), ``connection.py`` (handshake).
"""

from ddl_tpu.transport.connection import (
    ConsumerConnection,
    ControlChannel,
    PipeChannel,
    ProducerConnection,
    ThreadChannel,
)
from ddl_tpu.transport.envelope import ControlSender, EnvelopeReceiver
from ddl_tpu.transport.ring import DEFAULT_TIMEOUT_S, ThreadRing, WindowRing
from ddl_tpu.transport.shm_ring import (
    NativeShmRing,
    PyShmRing,
    create_shm_ring,
    make_ring_name,
    native_available,
    open_shm_ring,
)

__all__ = [
    "ConsumerConnection",
    "ControlChannel",
    "ControlSender",
    "DEFAULT_TIMEOUT_S",
    "EnvelopeReceiver",
    "NativeShmRing",
    "PipeChannel",
    "ProducerConnection",
    "PyShmRing",
    "ThreadChannel",
    "ThreadRing",
    "WindowRing",
    "create_shm_ring",
    "make_ring_name",
    "native_available",
    "open_shm_ring",
]
