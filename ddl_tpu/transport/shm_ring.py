"""Cross-process shared-memory ring: ctypes bindings over the C++ core.

The native library (``csrc/shm_ring.cpp``) is compiled on demand with g++ —
the ddl_tpu analog of the reference leaning on OpenMPI's native core for its
shared-memory windows (SURVEY §2.4).  A pure-Python fallback
(:class:`PyShmRing`) with the same counter protocol over
``multiprocessing.shared_memory`` exists for environments without a
toolchain; set ``DDL_TPU_FORCE_PY_RING=1`` to force it.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from ddl_tpu import envspec
from ddl_tpu.concurrency import named_lock
import time
import uuid
from pathlib import Path
from typing import Dict, Optional

import numpy as np

from ddl_tpu.exceptions import (
    ShutdownRequested,
    StallTimeoutError,
    TransportError,
)
from ddl_tpu.faults import fault_point
from ddl_tpu.transport.ring import DEFAULT_TIMEOUT_S, WindowRing

_CSRC = Path(__file__).parent / "csrc" / "shm_ring.cpp"
_LIB_PATH = Path(__file__).parent / "csrc" / "_shm_ring.so"
_build_lock = named_lock("transport.shm.build")
_lib: Optional[ctypes.CDLL] = None


#: Stable build recipe (everything except the per-invocation paths).
#: Recorded next to the .so so that a FLAG change — e.g. adding -lrt —
#: invalidates caches built with the old recipe: the .so is gitignored
#: and survives `git pull`, so mtime-vs-source alone would reuse an
#: under-linked library forever on machines that built before the fix.
_CXXFLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-pthread"]
_LDLIBS = ["-lrt"]  # shm_open/shm_unlink live in librt until glibc 2.34
_BUILD_STAMP = " ".join(["g++", *_CXXFLAGS, *_LDLIBS])
_STAMP_PATH = _LIB_PATH.with_name(_LIB_PATH.name + ".cmd")


def _fresh_lib() -> bool:
    """Is the built .so present, newer than the source, and built with
    the current recipe?"""
    try:
        return (
            _LIB_PATH.stat().st_mtime >= _CSRC.stat().st_mtime
            and _STAMP_PATH.read_text() == _BUILD_STAMP
        )
    except OSError:
        return False


def _build_native() -> Path:
    """Compile the native ring if missing/stale. Returns the .so path.

    ``_build_lock`` serialises builds within one process, but two
    *processes* importing simultaneously still race: both see a stale
    .so, both compile (to per-pid tmp names, so the outputs never
    collide), both ``os.replace``.  That last-writer-wins replace is
    fine — the contents are identical — but a compile *failure* in one
    process (e.g. tmpfs briefly full because of the peer's tmp file)
    must not fail the caller when the peer has meanwhile published a
    fresh .so.  So: re-stat after a failed compile and use the winner's
    library instead of propagating, and clean our tmp up on every path.
    """
    with _build_lock:
        if _fresh_lib():
            return _LIB_PATH
        tmp = _LIB_PATH.with_suffix(f".{os.getpid()}.tmp.so")
        # Without -lrt an under-linked .so only loads in processes where
        # some OTHER import already dragged librt in with RTLD_GLOBAL
        # (jax/torch in the trainer), and fails with `undefined symbol:
        # shm_open` in freshly spawned producer processes — silently
        # demoting them to the polling Python ring.  On glibc >= 2.34
        # librt is a stub, so the flag is harmless there.
        cmd = ["g++", *_CXXFLAGS, str(_CSRC), "-o", str(tmp), *_LDLIBS]
        try:
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(tmp, _LIB_PATH)
            # Stamp AFTER publishing (atomic rename): a crash between the
            # two leaves a missing/old stamp, i.e. "stale", never a fresh
            # verdict on a wrong .so.  Concurrent winners write identical
            # content, so last-writer-wins is safe here too.
            stamp_tmp = _STAMP_PATH.with_suffix(f".{os.getpid()}.tmp")
            stamp_tmp.write_text(_BUILD_STAMP)
            os.replace(stamp_tmp, _STAMP_PATH)
        except (OSError, subprocess.CalledProcessError):
            if _fresh_lib():  # a concurrent builder won the race
                return _LIB_PATH
            raise
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass  # normally already renamed away
        return _LIB_PATH


def _load_native() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(str(_build_native()))
    lib.ddlr_create.restype = ctypes.c_void_p
    lib.ddlr_create.argtypes = [ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64]
    lib.ddlr_open.restype = ctypes.c_void_p
    lib.ddlr_open.argtypes = [ctypes.c_char_p]
    lib.ddlr_acquire_fill.restype = ctypes.c_int
    lib.ddlr_acquire_fill.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    # Void functions declare restype = None explicitly: ctypes defaults
    # restype to c_int, and the lint gate (DDL008) requires the intent to
    # be visible so "void" is distinguishable from "forgot" — an
    # undeclared restype on a pointer-returning binding truncates to 32
    # bits on LP64.
    lib.ddlr_commit.restype = None
    lib.ddlr_commit.argtypes = [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64]
    lib.ddlr_acquire_drain.restype = ctypes.c_int
    lib.ddlr_acquire_drain.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.ddlr_acquire_drain_ahead.restype = ctypes.c_int
    lib.ddlr_acquire_drain_ahead.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int64,
    ]
    lib.ddlr_release.restype = None
    lib.ddlr_release.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.ddlr_slot_ptr.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.ddlr_slot_ptr.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.ddlr_slot_payload.restype = ctypes.c_uint64
    lib.ddlr_slot_payload.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.ddlr_shutdown.restype = None
    lib.ddlr_shutdown.argtypes = [ctypes.c_void_p]
    lib.ddlr_is_shutdown.restype = ctypes.c_int
    lib.ddlr_is_shutdown.argtypes = [ctypes.c_void_p]
    lib.ddlr_stat.restype = ctypes.c_uint64
    lib.ddlr_stat.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ddlr_nslots.restype = ctypes.c_uint32
    lib.ddlr_nslots.argtypes = [ctypes.c_void_p]
    lib.ddlr_slot_bytes.restype = ctypes.c_uint64
    lib.ddlr_slot_bytes.argtypes = [ctypes.c_void_p]
    lib.ddlr_close.restype = None
    lib.ddlr_close.argtypes = [ctypes.c_void_p]
    lib.ddlr_unlink.restype = None
    lib.ddlr_unlink.argtypes = [ctypes.c_char_p]
    _lib = lib
    return lib


_build_failure_logged = False


def native_available() -> bool:
    if envspec.flag("DDL_TPU_FORCE_PY_RING"):
        return False
    try:
        _load_native()
        return True
    except (OSError, subprocess.SubprocessError) as e:
        # Everything the toolchain path can throw: g++ missing/compile
        # failure (CalledProcessError / FileNotFoundError), CDLL load
        # failure and source stat failure (OSError).  Deliberately NOT
        # `except Exception` (DDL007): a ShutdownRequested or programming
        # error must propagate, not demote the process to the slow ring.
        # Degrading to PyShmRing must be VISIBLE: the fallback refuses
        # non-TSO ISAs and polls instead of event-waiting, so a silently
        # failing g++ build would change both perf and platform support.
        global _build_failure_logged
        if not _build_failure_logged:
            _build_failure_logged = True
            import logging

            detail = e.stderr.decode(errors="replace")[:500] if isinstance(
                e, subprocess.CalledProcessError
            ) and e.stderr else str(e)
            logging.getLogger("ddl_tpu").warning(
                "native shm ring build failed (%s: %s) — falling back to "
                "the pure-Python ring (TSO ISAs only, polling waits)",
                type(e).__name__, detail,
            )
        return False


def make_ring_name(prefix: str = "ddl") -> str:
    """A shm name unique enough to survive crashed prior runs."""
    return f"/{prefix}-{os.getpid()}-{uuid.uuid4().hex[:12]}"


class NativeShmRing(WindowRing):
    """ctypes wrapper over the C++ seqcount ring (``csrc/shm_ring.cpp``)."""

    def __init__(self, name: str, nslots: int = 0, slot_bytes: int = 0,
                 create: bool = False):
        self._lib = _load_native()
        self.name = name
        self._closed = False
        if create:
            self._h = self._lib.ddlr_create(
                name.encode(), ctypes.c_uint32(nslots), ctypes.c_uint64(slot_bytes)
            )
        else:
            self._h = self._lib.ddlr_open(name.encode())
        if not self._h:
            raise TransportError(
                f"failed to {'create' if create else 'open'} shm ring {name!r}"
            )
        self._owner = create
        self.nslots = int(self._lib.ddlr_nslots(self._h))
        self.slot_bytes = int(self._lib.ddlr_slot_bytes(self._h))

    @classmethod
    def create(cls, name: str, nslots: int, slot_bytes: int) -> "NativeShmRing":
        return cls(name, nslots, slot_bytes, create=True)

    @classmethod
    def open(cls, name: str) -> "NativeShmRing":
        return cls(name, create=False)

    def _check_wait(self, rc: int, timeout_s: float) -> int:
        if rc == -2:
            raise ShutdownRequested()
        if rc == -1:
            raise StallTimeoutError(f"ring {self.name} wait exceeded {timeout_s}s")
        return rc

    def acquire_fill(self, timeout_s: float = DEFAULT_TIMEOUT_S) -> int:
        fault_point("ring.fill", should_abort=self.is_shutdown)
        rc = self._lib.ddlr_acquire_fill(self._h, int(timeout_s * 1e6))
        return self._check_wait(rc, timeout_s)

    def commit(self, slot: int, payload_bytes: int) -> None:
        self._lib.ddlr_commit(self._h, slot, payload_bytes)

    def acquire_drain(self, timeout_s: float = DEFAULT_TIMEOUT_S) -> int:
        fault_point("ring.drain", should_abort=self.is_shutdown)
        rc = self._lib.ddlr_acquire_drain(self._h, int(timeout_s * 1e6))
        return self._check_wait(rc, timeout_s)

    def acquire_drain_ahead(
        self, ahead: int, timeout_s: float = DEFAULT_TIMEOUT_S
    ) -> int:
        rc = self._lib.ddlr_acquire_drain_ahead(
            self._h, ahead, int(timeout_s * 1e6)
        )
        if rc == -3:
            raise ValueError(
                f"ahead must be in [0, nslots={self.nslots}), got {ahead}"
            )
        return self._check_wait(rc, timeout_s)

    def release(self, slot: int) -> None:
        self._lib.ddlr_release(self._h, slot)

    def slot_view(self, slot: int) -> np.ndarray:
        ptr = self._lib.ddlr_slot_ptr(self._h, slot)
        buf = (ctypes.c_uint8 * self.slot_bytes).from_address(
            ctypes.addressof(ptr.contents)
        )
        return np.frombuffer(buf, dtype=np.uint8)

    def slot_payload(self, slot: int) -> int:
        return int(self._lib.ddlr_slot_payload(self._h, slot))

    def shutdown(self) -> None:
        self._lib.ddlr_shutdown(self._h)

    def is_shutdown(self) -> bool:
        return bool(self._lib.ddlr_is_shutdown(self._h))

    def stats(self) -> Dict[str, float]:
        return {
            "producer_stall_s": self._lib.ddlr_stat(self._h, 0) / 1e6,
            "consumer_stall_s": self._lib.ddlr_stat(self._h, 1) / 1e6,
            "committed": float(self._lib.ddlr_stat(self._h, 2)),
            "released": float(self._lib.ddlr_stat(self._h, 3)),
        }

    def poll_drain_ready(self, ahead: int = 0) -> bool:
        # Two counter reads, skipping stats()'s stall-timer FFI calls and
        # dict build — this runs in the stream's per-window lookahead loop.
        return (
            int(self._lib.ddlr_stat(self._h, 2))
            - int(self._lib.ddlr_stat(self._h, 3))
            > ahead
        )

    def close(self) -> None:
        # Intentionally does NOT munmap: numpy views created by slot_view
        # hold raw pointers into the mapping, and unmapping under them would
        # be a use-after-free. The kernel reclaims mappings at process exit;
        # unlink() removes the name so the memory is freed once all
        # processes exit. (Same policy as PyShmRing.close.)
        self._closed = True

    def unlink(self) -> None:
        self._lib.ddlr_unlink(self.name.encode())


class PyShmRing(WindowRing):
    """Pure-Python fallback over a raw ``mmap`` of a ``/dev/shm`` file.

    Same counter protocol as the native ring but with Python-level polling.
    Counter stores are 8-byte aligned single writes with one writer each;
    this relies on x86-64's total-store-order — on weakly-ordered ISAs
    (ARM64) the publish order is NOT guaranteed from Python, so the native
    ring is required there (TPU hosts are x86-64).  Raw mmap is
    used instead of ``multiprocessing.shared_memory`` so that outstanding
    numpy views never trip BufferError at teardown and no resource-tracker
    chatter leaks into user processes.  Slower waits than the native ring —
    use only where g++ is unavailable.
    """

    _HDR = 4096  # [0]=committed u64, [8]=released u64, [16]=shutdown u64,
    #              [24]=nslots u64, [32]=slot_bytes u64, [40]=magic u64
    #              (written last by the creator), [64+8i]=payload[i]
    _MAGIC = 0xDD17_00F5_0000_0001  # py-format marker (≠ native kMagic)

    #: ISAs whose hardware memory model makes plain aligned stores publish
    #: in program order (total store order) — the property the Python
    #: counter protocol depends on.
    _TSO_MACHINES = ("x86_64", "amd64", "i686", "i386")

    def __init__(self, name: str, nslots: int = 0, slot_bytes: int = 0,
                 create: bool = False):
        import mmap
        import platform

        machine = platform.machine().lower()
        if (
            machine not in self._TSO_MACHINES
            and not envspec.flag("DDL_TPU_UNSAFE_PY_RING")
        ):
            # Hard gate, not a docstring caveat (VERDICT r2 Weak #7): on
            # weakly-ordered ISAs (ARM64 etc.) Python-level stores can
            # publish out of order and silently corrupt the handoff.
            raise TransportError(
                f"PyShmRing requires a total-store-order ISA "
                f"(x86-64); this machine is {machine!r}. Install a C++ "
                f"toolchain for the native ring (fenced atomics), or set "
                f"DDL_TPU_UNSAFE_PY_RING=1 to override at your own risk."
            )
        self.name = name
        path = f"/dev/shm/{name.lstrip('/')}"
        if create:
            total = self._HDR + nslots * slot_bytes
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, total)
                self._mm = mmap.mmap(fd, total)
            finally:
                os.close(fd)
            self._u64 = np.frombuffer(self._mm, dtype=np.uint64)
            self._u64[:8] = 0
            self._u64[3] = nslots
            self._u64[4] = slot_bytes
            self._u64[5] = self._MAGIC  # publish: header is now valid
        else:
            fd = -1
            try:
                for _ in range(2000):  # peer may still be creating it
                    try:
                        fd = os.open(path, os.O_RDWR)
                        break
                    except FileNotFoundError:
                        time.sleep(0.001)
                if fd < 0:
                    raise TransportError(f"shm ring {name!r} never appeared")
                total = 0
                for _ in range(2000):  # ... or still ftruncating it
                    total = os.fstat(fd).st_size
                    if total >= self._HDR:
                        break
                    time.sleep(0.001)
                if total < self._HDR:
                    raise TransportError(f"shm ring {name!r} never grew a header")
                self._mm = mmap.mmap(fd, total)
            finally:
                if fd >= 0:
                    os.close(fd)
            self._u64 = np.frombuffer(self._mm, dtype=np.uint64)
            for _ in range(2000):  # ... or still writing the header
                if int(self._u64[5]) == self._MAGIC:
                    break
                time.sleep(0.001)
            if int(self._u64[5]) != self._MAGIC:
                raise TransportError(
                    f"shm ring {name!r} is not py-format (native-format "
                    f"segment opened with DDL_TPU_FORCE_PY_RING, or corrupt)"
                )
        self._owner = create
        self.nslots = int(self._u64[3])
        self.slot_bytes = int(self._u64[4])
        # Fixed two-key accumulator: _wait only ever += into the keys
        # initialised here.
        self._stall = {"producer_stall_s": 0.0, "consumer_stall_s": 0.0}  # ddl-lint: disable=DDL013

    create = classmethod(lambda cls, name, nslots, slot_bytes: cls(
        name, nslots, slot_bytes, create=True))
    open = classmethod(lambda cls, name: cls(name, create=False))

    def _wait(self, ready, timeout_s: float, key: str) -> int:
        t0 = time.perf_counter()
        spins = 0
        try:
            while True:
                if self._u64[2]:
                    raise ShutdownRequested()
                slot = ready()
                if slot is not None:
                    return slot
                if time.perf_counter() - t0 > timeout_s:
                    raise StallTimeoutError(
                        f"ring {self.name} wait exceeded {timeout_s}s"
                    )
                spins += 1
                if spins > 100:
                    time.sleep(0.0002)
        finally:
            self._stall[key] += time.perf_counter() - t0

    def acquire_fill(self, timeout_s: float = DEFAULT_TIMEOUT_S) -> int:
        fault_point("ring.fill", should_abort=self.is_shutdown)

        def ready():
            c, r = int(self._u64[0]), int(self._u64[1])
            return c % self.nslots if c - r < self.nslots else None

        return self._wait(ready, timeout_s, "producer_stall_s")

    def commit(self, slot: int, payload_bytes: int) -> None:
        self._u64[8 + slot] = payload_bytes
        self._u64[0] = self._u64[0] + np.uint64(1)

    def acquire_drain(self, timeout_s: float = DEFAULT_TIMEOUT_S) -> int:
        fault_point("ring.drain", should_abort=self.is_shutdown)

        def ready():
            c, r = int(self._u64[0]), int(self._u64[1])
            return r % self.nslots if c > r else None

        return self._wait(ready, timeout_s, "consumer_stall_s")

    def acquire_drain_ahead(
        self, ahead: int, timeout_s: float = DEFAULT_TIMEOUT_S
    ) -> int:
        if not 0 <= ahead < self.nslots:
            raise ValueError(
                f"ahead must be in [0, nslots={self.nslots}), got {ahead}"
            )

        def ready():
            c, r = int(self._u64[0]), int(self._u64[1])
            return (r + ahead) % self.nslots if c > r + ahead else None

        return self._wait(ready, timeout_s, "consumer_stall_s")

    def release(self, slot: int) -> None:
        self._u64[1] = self._u64[1] + np.uint64(1)

    def slot_view(self, slot: int) -> np.ndarray:
        off = self._HDR + slot * self.slot_bytes
        return np.frombuffer(self._mm, dtype=np.uint8,
                             count=self.slot_bytes, offset=off)

    def slot_payload(self, slot: int) -> int:
        return int(self._u64[8 + slot])

    def shutdown(self) -> None:
        self._u64[2] = 1

    def is_shutdown(self) -> bool:
        return bool(self._u64[2])

    def stats(self) -> Dict[str, float]:
        return {
            **self._stall,
            "committed": float(self._u64[0]),
            "released": float(self._u64[1]),
        }

    def close(self) -> None:
        # The mmap stays mapped until process exit: numpy views handed to
        # user code may outlive the ring, and unmapping under them would
        # be a use-after-free. The kernel reclaims at exit.
        pass

    def unlink(self) -> None:
        try:
            os.unlink(f"/dev/shm/{self.name.lstrip('/')}")
        except OSError:
            pass


def create_shm_ring(name: str, nslots: int, slot_bytes: int) -> WindowRing:
    """Create the best available cross-process ring (native, else Python)."""
    if native_available():
        return NativeShmRing.create(name, nslots, slot_bytes)
    return PyShmRing.create(name, nslots, slot_bytes)


def open_shm_ring(name: str) -> WindowRing:
    if native_available():
        return NativeShmRing.open(name)
    return PyShmRing.open(name)
