"""Connection: handshake control plane + per-producer ring data plane.

Parity with reference ``ddl/connection.py``: that class bundled (a) pickled
metadata send/recv over MPI tag 0 (``connection.py:66-86``), (b) window
allocation (``:88-139``), (c) the access-epoch token protocol (``:144-182``)
and (d) shutdown (``:184-187``).  Here the token protocol and window storage
live in :mod:`ddl_tpu.transport.ring`; this module provides the control
plane — metadata handshake over mode-appropriate channels — and owns the
set of rings.

Channel realisations:
- THREAD mode: ``queue.Queue`` pairs (consumer and producers share a process).
- PROCESS mode: ``multiprocessing.Pipe`` (pickles metadata exactly as the
  reference pickled it over ``ssend``, ``connection.py:73``).
"""

from __future__ import annotations

import abc
import copy
import queue as queue_mod
from typing import Any, List, Optional, Sequence

from ddl_tpu.exceptions import StallTimeoutError, TransportError
from ddl_tpu.utils import for_all_methods, with_logging
from ddl_tpu.transport.ring import WindowRing
from ddl_tpu.types import (
    MetaData_Consumer_To_Producer,
    MetaData_Producer_To_Consumer,
)

_HANDSHAKE_TIMEOUT_S = 600.0


class ControlChannel(abc.ABC):
    """One bidirectional control-plane link (consumer ↔ one producer)."""

    @abc.abstractmethod
    def send(self, obj: Any) -> None: ...

    @abc.abstractmethod
    def recv(self, timeout_s: float = _HANDSHAKE_TIMEOUT_S) -> Any: ...

    def close(self) -> None:  # pragma: no cover
        pass


class ThreadChannel(ControlChannel):
    """In-process channel endpoint over a pair of queues."""

    def __init__(self, tx: "queue_mod.Queue[Any]", rx: "queue_mod.Queue[Any]"):
        self._tx, self._rx = tx, rx

    @staticmethod
    def pair() -> tuple["ThreadChannel", "ThreadChannel"]:
        a: "queue_mod.Queue[Any]" = queue_mod.Queue()
        b: "queue_mod.Queue[Any]" = queue_mod.Queue()
        return ThreadChannel(a, b), ThreadChannel(b, a)

    def send(self, obj: Any) -> None:
        self._tx.put(obj)

    def recv(self, timeout_s: float = _HANDSHAKE_TIMEOUT_S) -> Any:
        try:
            return self._rx.get(timeout=timeout_s)
        except queue_mod.Empty as e:
            raise StallTimeoutError(f"control recv exceeded {timeout_s}s") from e


class PipeChannel(ControlChannel):
    """Cross-process channel over a ``multiprocessing.Pipe`` end."""

    def __init__(self, conn: Any):
        self._conn = conn

    @staticmethod
    def pair() -> tuple["PipeChannel", "PipeChannel"]:
        import multiprocessing as mp

        a, b = mp.Pipe(duplex=True)
        return PipeChannel(a), PipeChannel(b)

    def send(self, obj: Any) -> None:
        self._conn.send(obj)

    def recv(self, timeout_s: float = _HANDSHAKE_TIMEOUT_S) -> Any:
        if not self._conn.poll(timeout_s):
            raise StallTimeoutError(f"control recv exceeded {timeout_s}s")
        try:
            return self._conn.recv()
        except EOFError as e:
            # Peer process died with the channel open — fail fast instead
            # of pretending the handshake may still complete.
            raise TransportError("control channel peer closed (process died)") from e

    def close(self) -> None:
        self._conn.close()


def _resolve_ring(reply: MetaData_Producer_To_Consumer) -> WindowRing:
    """Resolve a handshake reply's ring_ref to a usable ring."""
    ref = getattr(reply, "ring_ref", None)
    if isinstance(ref, WindowRing):
        return ref
    if isinstance(ref, str):
        from ddl_tpu.transport.shm_ring import open_shm_ring

        return open_shm_ring(ref)
    raise TransportError(f"producer {reply.producer_idx} sent no ring_ref")


# DEBUG call tracing, as the reference wrapped its Connection class
# (``for_all_methods(with_logging)``, reference ``connection.py:17``).
@for_all_methods(with_logging)
class ConsumerConnection:
    """Consumer endpoint: broadcasts metadata, collects replies, owns rings.

    Mirrors the consumer half of reference ``Connection``
    (``connection.py:66-73`` broadcast, ``:82-86`` gather), with rings
    replacing windows.
    """

    def __init__(self, channels: Sequence[ControlChannel]):
        self.channels = list(channels)
        self.rings: List[WindowRing] = []
        self.replies: List[MetaData_Producer_To_Consumer] = []

    @property
    def n_producers(self) -> int:
        return len(self.channels)

    def send_metadata(self, meta: MetaData_Consumer_To_Producer) -> None:
        # Each producer gets a DEEP COPY of the metadata (and with it the
        # user's producer function) so THREAD mode has the same
        # code-shipping semantics as PROCESS mode's pickling (reference
        # pickled over ssend, connection.py:73): a shared instance would
        # race on user state (shard cursors, RNGs) across producer threads.
        # deepcopy rather than a pickle round-trip keeps thread mode usable
        # with locally-defined producer classes.  Only this broadcast is
        # copied — ring handles and tokens on other paths must stay shared —
        # and only for thread channels: PipeChannel already copies by
        # pickling, so copying there would double the peak memory of a
        # producer function that closes over a large dataset.
        for ch in self.channels:
            ch.send(
                copy.deepcopy(meta) if isinstance(ch, ThreadChannel) else meta
            )

    def recv_metadata_as_consumer(self) -> List[MetaData_Producer_To_Consumer]:
        replies = [ch.recv() for ch in self.channels]
        # Record the valid replies FIRST: even when some producer failed,
        # shutdown_operation must be able to reach the healthy producers'
        # rings (via ring_ref) to wake them — otherwise an abort after a
        # partial handshake leaves them blocked until their wait timeout.
        self.replies = sorted(
            (r for r in replies if isinstance(r, MetaData_Producer_To_Consumer)),
            key=lambda r: r.producer_idx,
        )
        for i, r in enumerate(replies):
            if isinstance(r, Exception):
                # A producer shipped its handshake-time failure to us.
                raise TransportError(f"producer {i} failed during handshake") from r
            if not isinstance(r, MetaData_Producer_To_Consumer):
                raise TransportError(f"bad handshake reply from producer {i}: {r!r}")
        return self.replies

    def attach_rings(self) -> List[WindowRing]:
        """Open every producer's ring (by name or by in-process reference)."""
        self.rings = [_resolve_ring(r) for r in self.replies]
        return self.rings

    def shutdown_operation(self) -> None:
        """Wake every producer with the shutdown flag.

        Replaces the reference's Ibarrier-join trigger
        (``connection.py:184-187``, SURVEY §3.5): flag-based, idempotent,
        and observable from any blocked wait.  When rings were never
        attached (handshake failed mid-way), reachable rings are resolved
        from the recorded replies so healthy producers still wake.
        """
        rings = self.rings
        if not rings and self.replies:
            rings = []
            for r in self.replies:
                try:
                    rings.append(_resolve_ring(r))
                except Exception:  # pragma: no cover - best-effort wake
                    pass
        for ring in rings:
            ring.shutdown()

    def finalize(self) -> None:
        for ring in self.rings:
            ring.close()
        for ch in self.channels:
            ch.close()


@for_all_methods(with_logging)
class ProducerConnection:
    """Producer endpoint: one control channel + this producer's ring."""

    def __init__(self, channel: ControlChannel, producer_idx: int,
                 cross_process: bool):
        self.channel = channel
        self.producer_idx = producer_idx
        self.cross_process = cross_process
        self.ring: Optional[WindowRing] = None

    def recv_metadata_as_producer(self) -> MetaData_Consumer_To_Producer:
        meta = self.channel.recv()
        if not isinstance(meta, MetaData_Consumer_To_Producer):
            raise TransportError(f"bad handshake metadata: {meta!r}")
        return meta

    def create_ring(self, nslots: int, slot_bytes: int) -> WindowRing:
        if self.cross_process:
            from ddl_tpu.transport.shm_ring import create_shm_ring, make_ring_name

            name = make_ring_name(f"ddl-p{self.producer_idx}")
            self.ring = create_shm_ring(name, nslots, slot_bytes)
            self._ring_ref: Any = name
        else:
            from ddl_tpu.transport.ring import ThreadRing

            self.ring = ThreadRing(nslots, slot_bytes)
            self._ring_ref = self.ring
        return self.ring

    def send_metadata(self, reply: MetaData_Producer_To_Consumer) -> None:
        reply.ring_ref = self._ring_ref  # type: ignore[attr-defined]
        self.channel.send(reply)

    def finalize(self) -> None:
        if self.ring is not None:
            self.ring.close()
            if self.cross_process:
                self.ring.unlink()
        self.channel.close()
