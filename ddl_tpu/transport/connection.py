"""Connection: handshake control plane + per-producer ring data plane.

Parity with reference ``ddl/connection.py``: that class bundled (a) pickled
metadata send/recv over MPI tag 0 (``connection.py:66-86``), (b) window
allocation (``:88-139``), (c) the access-epoch token protocol (``:144-182``)
and (d) shutdown (``:184-187``).  Here the token protocol and window storage
live in :mod:`ddl_tpu.transport.ring`; this module provides the control
plane — metadata handshake over mode-appropriate channels — and owns the
set of rings.

Channel realisations:
- THREAD mode: ``queue.Queue`` pairs (consumer and producers share a process).
- PROCESS mode: ``multiprocessing.Pipe`` (pickles metadata exactly as the
  reference pickled it over ``ssend``, ``connection.py:73``).
"""

from __future__ import annotations

import abc
import copy
import logging
import queue as queue_mod
import threading

from ddl_tpu.concurrency import named_rlock
from typing import Any, List, Optional, Sequence

from ddl_tpu.exceptions import StallTimeoutError, TransportError
from ddl_tpu.utils import for_all_methods, with_logging
from ddl_tpu.transport.ring import WindowRing
from ddl_tpu.types import (
    MetaData_Consumer_To_Producer,
    MetaData_Producer_To_Consumer,
)

logger = logging.getLogger("ddl_tpu")

_HANDSHAKE_TIMEOUT_S = 600.0

#: Sentinel returned by :meth:`ControlChannel.try_recv` when nothing is
#: pending — distinct from None, which is a legal message payload.
NOTHING = object()


class ControlChannel(abc.ABC):
    """One bidirectional control-plane link (consumer ↔ one producer)."""

    @abc.abstractmethod
    def send(self, obj: Any) -> None: ...

    @abc.abstractmethod
    def recv(self, timeout_s: float = _HANDSHAKE_TIMEOUT_S) -> Any: ...

    def try_recv(self) -> Any:
        """Non-blocking receive: a pending message or :data:`NOTHING`.

        The producer's push loop polls this once per window (the replay
        re-request path, ``ddl_tpu.integrity``); a broken/raced channel
        reads as "nothing pending" — channel death is detected by the
        blocking paths and the ring shutdown flag, not here.
        """
        return NOTHING  # pragma: no cover - overridden by real channels

    def alive(self) -> bool:
        """Best-effort channel liveness: False only when the link is
        POSITIVELY known dead (closed fd / broken pipe).  The cluster
        membership layer (``ddl_tpu.cluster``) layers host heartbeats
        over this — a channel that cannot say is presumed alive, and
        lease EXPIRY (never a single probe) declares the loss."""
        return True

    def close(self) -> None:  # pragma: no cover
        pass


class ThreadChannel(ControlChannel):
    """In-process channel endpoint over a pair of queues."""

    def __init__(self, tx: "queue_mod.Queue[Any]", rx: "queue_mod.Queue[Any]"):
        self._tx, self._rx = tx, rx

    @staticmethod
    def pair() -> tuple["ThreadChannel", "ThreadChannel"]:
        a: "queue_mod.Queue[Any]" = queue_mod.Queue()
        b: "queue_mod.Queue[Any]" = queue_mod.Queue()
        return ThreadChannel(a, b), ThreadChannel(b, a)

    def send(self, obj: Any) -> None:
        self._tx.put(obj)

    def recv(self, timeout_s: float = _HANDSHAKE_TIMEOUT_S) -> Any:
        try:
            return self._rx.get(timeout=timeout_s)
        except queue_mod.Empty as e:
            raise StallTimeoutError(f"control recv exceeded {timeout_s}s") from e

    def try_recv(self) -> Any:
        try:
            return self._rx.get_nowait()
        except queue_mod.Empty:
            return NOTHING


class PipeChannel(ControlChannel):
    """Cross-process channel over a ``multiprocessing.Pipe`` end."""

    def __init__(self, conn: Any):
        self._conn = conn

    @staticmethod
    def pair() -> tuple["PipeChannel", "PipeChannel"]:
        import multiprocessing as mp

        a, b = mp.Pipe(duplex=True)
        return PipeChannel(a), PipeChannel(b)

    def send(self, obj: Any) -> None:
        self._conn.send(obj)

    def recv(self, timeout_s: float = _HANDSHAKE_TIMEOUT_S) -> Any:
        if not self._conn.poll(timeout_s):
            raise StallTimeoutError(f"control recv exceeded {timeout_s}s")
        try:
            return self._conn.recv()
        except EOFError as e:
            # Peer process died with the channel open — fail fast instead
            # of pretending the handshake may still complete.
            raise TransportError("control channel peer closed (process died)") from e

    def try_recv(self) -> Any:
        try:
            if not self._conn.poll(0):
                return NOTHING
            return self._conn.recv()
        except (EOFError, OSError):
            # Peer gone: the blocking paths / ring flag own that failure
            # mode; the poll stays quiet rather than double-reporting.
            return NOTHING

    def alive(self) -> bool:
        try:
            return not self._conn.closed
        except (OSError, AttributeError):
            return False

    def close(self) -> None:
        self._conn.close()


def _send_meta(
    ch: ControlChannel, meta: MetaData_Consumer_To_Producer
) -> None:
    """Send the consumer handshake metadata over one channel.

    Each producer gets a DEEP COPY of the metadata (and with it the
    user's producer function) so THREAD mode has the same code-shipping
    semantics as PROCESS mode's pickling (reference pickled over ssend,
    connection.py:73): a shared instance would race on user state (shard
    cursors, RNGs) across producer threads.  deepcopy rather than a
    pickle round-trip keeps thread mode usable with locally-defined
    producer classes.  Only this broadcast is copied — ring handles and
    tokens on other paths must stay shared — and only for thread
    channels: PipeChannel already copies by pickling, so copying there
    would double the peak memory of a producer function that closes over
    a large dataset.
    """
    ch.send(copy.deepcopy(meta) if isinstance(ch, ThreadChannel) else meta)


def _resolve_ring(reply: MetaData_Producer_To_Consumer) -> WindowRing:
    """Resolve a handshake reply's ring_ref to a usable ring."""
    ref = getattr(reply, "ring_ref", None)
    if isinstance(ref, WindowRing):
        return ref
    if isinstance(ref, str):
        from ddl_tpu.transport.shm_ring import open_shm_ring

        return open_shm_ring(ref)
    raise TransportError(f"producer {reply.producer_idx} sent no ring_ref")


# DEBUG call tracing, as the reference wrapped its Connection class
# (``for_all_methods(with_logging)``, reference ``connection.py:17``).
@for_all_methods(with_logging)
class ConsumerConnection:
    """Consumer endpoint: broadcasts metadata, collects replies, owns rings.

    Mirrors the consumer half of reference ``Connection``
    (``connection.py:66-73`` broadcast, ``:82-86`` gather), with rings
    replacing windows.
    """

    def __init__(self, channels: Sequence[ControlChannel]):
        self.channels = list(channels)
        self.rings: List[WindowRing] = []
        self.replies: List[MetaData_Producer_To_Consumer] = []
        self._sent_meta: Optional[MetaData_Consumer_To_Producer] = None
        # Acked control-envelope seam (ddl_tpu.transport.envelope): one
        # lazily-built sender per target; bounded by n_producers.
        self._senders: dict = {}  # ddl-lint: disable=DDL013
        #: Fencing term stamped on every acked send (the supervisor HA
        #: tier raises it at promotion — ddl_tpu.cluster.supervision).
        self._control_fence = 0
        #: Metrics sink for the senders' delivery counters (ctrl.*);
        #: attached by the loader/elastic layer when it has one.
        self.control_metrics: Any = None
        # Serialises the elastic-rejoin channel swap (watchdog thread,
        # rejoin_producer) against the consumer thread's shutdown /
        # finalize over the same lists: without it a shutdown racing an
        # in-flight rejoin can broadcast on the just-closed predecessor
        # channel and miss the replacement.  Ring shutdown flags are
        # persistent state the replacement's bounded waits observe, so
        # whichever side wins the lock, the fresh worker still exits
        # promptly.
        self._lock = named_rlock("transport.connection")
        self._finalized = False

    @property
    def n_producers(self) -> int:
        return len(self.channels)

    def send_metadata(self, meta: MetaData_Consumer_To_Producer) -> None:
        self._sent_meta = meta  # kept for elastic rejoin handshakes
        for ch in self.channels:
            _send_meta(ch, meta)

    def recv_metadata_as_consumer(self) -> List[MetaData_Producer_To_Consumer]:
        replies = [ch.recv() for ch in self.channels]
        # Record the valid replies FIRST: even when some producer failed,
        # shutdown_operation must be able to reach the healthy producers'
        # rings (via ring_ref) to wake them — otherwise an abort after a
        # partial handshake leaves them blocked until their wait timeout.
        self.replies = sorted(
            (r for r in replies if isinstance(r, MetaData_Producer_To_Consumer)),
            key=lambda r: r.producer_idx,
        )
        for i, r in enumerate(replies):
            if isinstance(r, Exception):
                # A producer shipped its handshake-time failure to us.
                raise TransportError(f"producer {i} failed during handshake") from r
            if not isinstance(r, MetaData_Producer_To_Consumer):
                raise TransportError(f"bad handshake reply from producer {i}: {r!r}")
        return self.replies

    def attach_rings(self) -> List[WindowRing]:
        """Open every producer's ring (by name or by in-process reference)."""
        self.rings = [_resolve_ring(r) for r in self.replies]
        return self.rings

    def rejoin_producer(
        self, producer_idx: int, channel: ControlChannel
    ) -> MetaData_Producer_To_Consumer:
        """Re-run the handshake with a RESPAWNED producer (elastic
        recovery).  The replacement re-derives its geometry from the same
        metadata, attaches to the surviving ring, and must report the
        geometry its predecessor reported — the consumer's window
        bookkeeping cannot change mid-run.
        """
        i = producer_idx - 1
        if self._sent_meta is None:
            raise TransportError("rejoin before the initial handshake")
        old = self.replies[i]
        _send_meta(channel, self._sent_meta)
        reply = channel.recv()
        if isinstance(reply, Exception):
            raise TransportError(
                f"producer {producer_idx} failed during rejoin"
            ) from reply
        if not isinstance(reply, MetaData_Producer_To_Consumer):
            raise TransportError(f"bad rejoin reply: {reply!r}")
        if (
            reply.batches_per_window != old.batches_per_window
            or tuple(reply.shape) != tuple(old.shape)
            or tuple(reply.splits) != tuple(old.splits)
            or reply.dtype != old.dtype
        ):
            raise TransportError(
                f"respawned producer {producer_idx} reported different "
                f"geometry than its predecessor"
            )
        if getattr(reply, "integrity", False) != getattr(
            old, "integrity", False
        ):
            # Env drift across a respawn (DDL_TPU_INTEGRITY changed): an
            # unstamped replacement on a verified ring would read as
            # unrecoverable corruption on every drain — fail HERE, at the
            # rejoin handshake, with the real cause.
            raise TransportError(
                f"respawned producer {producer_idx} disagrees with its "
                "predecessor about integrity headers (DDL_TPU_INTEGRITY "
                "changed between incarnations)"
            )
        # Swap only once the replacement validated; the dead producer's
        # channel fd is released rather than leaked.  Under the lock so a
        # concurrent shutdown/finalize sees either the old channel (still
        # open) or the new one — never a closed-but-unswapped slot.
        with self._lock:
            if self._finalized:
                # The run ended while this rejoin's control-plane recv was
                # in flight.  The reply above already VALIDATED: the
                # replacement completed its handshake and has been serving
                # the surviving ring directly (the data path never waits
                # on this swap), so a consumer that drained to completion
                # and finalized meanwhile is a recovery that raced run
                # completion — a success, not a failure to escalate.
                # Swapping in would leak an open channel into a dead
                # connection, so drop the channel instead; the fresh
                # worker exits via its ring's persistent shutdown flag.
                try:
                    channel.close()
                except OSError:  # pragma: no cover - best-effort
                    pass
                logger.info(
                    "rejoin of producer %d completed after finalize; "
                    "replacement channel dropped",
                    producer_idx,
                )
                return reply
            try:
                self.channels[i].close()
            except OSError:  # pragma: no cover - already-broken pipe
                pass
            self.channels[i] = channel
            self.replies[i] = reply
        # self.rings[i] stays as-is: the consumer's attachment to the
        # surviving ring is untouched by the producer's death.
        return reply

    def try_recv_control(self, target: int) -> Any:
        """Non-blocking receive of a producer-initiated control message
        (today: ``ObsReport`` — the cross-process observability
        shipping, ddl_tpu.obs).  Under the rejoin lock so a concurrent
        elastic channel swap sees a consistent channel list; returns
        :data:`NOTHING` when idle (or when the channel is already
        broken — a dying producer's last report is best-effort)."""
        with self._lock:
            if self._finalized:
                return NOTHING
            try:
                return self.channels[target].try_recv()
            except (OSError, EOFError, ValueError):
                return NOTHING

    def send_control(self, target: int, msg: Any) -> None:
        """Send a RAW (fire-and-forget) control-plane message to
        producer ``target`` (0-based ring index) under the rejoin lock —
        concurrent senders must serialize against each other AND against
        an in-flight elastic channel swap, or two writes interleave on
        one pipe / a send lands on a closed-but-unswapped channel.

        Command messages (adoption, replay) should ride
        :meth:`send_control_acked` instead — raw sends have no delivery
        model (ddl-lint DDL025 enforces this at the configured command
        sites); this primitive remains for the abort broadcast and as
        the seam's own wire layer.
        """
        with self._lock:
            self.channels[target].send(msg)

    # -- acked envelope seam (ddl_tpu.transport.envelope) ------------------

    def control_sender(self, target: int) -> Any:
        """The per-target acked sender, built on first use.  Its wire
        closure reads ``self.channels[target]`` at send time, so elastic
        channel swaps are transparent to pending retries."""
        from ddl_tpu.transport.envelope import ControlSender

        with self._lock:
            s = self._senders.get(target)
            if s is None:
                s = ControlSender(
                    lambda msg, t=target: self.send_control(t, msg),
                    target=target,
                    metrics=self.control_metrics,
                )
                s.fence = self._control_fence
                self._senders[target] = s
            return s

    def send_control_acked(self, target: int, msg: Any) -> int:
        """Send ``msg`` through the acked envelope seam: sequenced,
        fenced, deduped at the receiver, retried with backoff until
        acknowledged (at-least-once + dedup — the explicit contract
        replacing raw ``send_control``'s implicit exactly-once hope).
        Returns the assigned envelope seq."""
        with self._lock:
            if self._finalized:
                return -1
            return self.control_sender(target).send(msg)

    def pump_control(self, now: Optional[float] = None) -> int:
        """Retry every due unacked envelope across all targets (called
        from the consumer's periodic drains).  Returns resend count."""
        with self._lock:
            if self._finalized:
                return 0
            return sum(s.pump(now) for s in self._senders.values())

    def note_ack(self, ack: Any) -> bool:
        """Route a :class:`~ddl_tpu.types.ControlAck` drained off a
        producer channel back to its sender's pending table.

        ``ack.producer_idx`` carries the producer's 1-based rank (the
        repo-wide ring convention); senders are keyed by the 0-based
        channel index every ``send_control`` target uses."""
        with self._lock:
            s = self._senders.get(ack.producer_idx - 1)
            return s.ack(ack) if s is not None else False

    def set_control_fence(self, fence: int) -> None:
        """Stamp ``fence`` on every future acked send (supervisor
        promotion raises the term; appliers drop older ones)."""
        with self._lock:
            self._control_fence = int(fence)
            for s in self._senders.values():
                s.fence = self._control_fence

    def request_replay(self, target: int, seq: int) -> None:
        """Ask producer ``target`` (0-based ring index) to rewind and
        re-commit its window stream from logical window ``seq``
        (quarantine-and-replay for corrupt slots — ``ddl_tpu.integrity``).
        Rides the acked envelope seam: a lost request is retried with
        backoff instead of silently stranding the quarantine wait."""
        from ddl_tpu.types import ReplayRequest

        self.send_control_acked(target, ReplayRequest(seq=seq))

    def shutdown_operation(self) -> None:
        """Wake every producer with the shutdown flag.

        Replaces the reference's Ibarrier-join trigger
        (``connection.py:184-187``, SURVEY §3.5): flag-based, idempotent,
        and observable from any blocked wait.  When rings were never
        attached (handshake failed mid-way), reachable rings are resolved
        from the recorded replies so healthy producers still wake.
        """
        with self._lock:
            rings = self.rings
            if not rings and self.replies:
                rings = []
                for r in self.replies:
                    try:
                        rings.append(_resolve_ring(r))
                    except (TransportError, OSError):
                        # pragma: no cover - best-effort wake; an
                        # unresolvable ring only means that producer
                        # cannot be woken early (its bounded wait still
                        # times out).  Narrow on purpose (DDL007).
                        pass
            for ring in rings:
                ring.shutdown()

    def finalize(self) -> None:
        with self._lock:
            self._finalized = True
            for ring in self.rings:
                ring.close()
                # Backstop cleanup: a producer that CRASHED leaves its shm
                # name linked for elastic rejoin; if the run ends without a
                # respawn, remove it here (idempotent — clean producers
                # already unlinked their own).
                try:
                    ring.unlink()
                except (TransportError, OSError):  # pragma: no cover
                    pass  # best-effort: name may already be gone
            for ch in self.channels:
                ch.close()


@for_all_methods(with_logging)
class ProducerConnection:
    """Producer endpoint: one control channel + this producer's ring."""

    def __init__(self, channel: ControlChannel, producer_idx: int,
                 cross_process: bool):
        self.channel = channel
        self.producer_idx = producer_idx
        self.cross_process = cross_process
        self.ring: Optional[WindowRing] = None

    def recv_metadata_as_producer(self) -> MetaData_Consumer_To_Producer:
        meta = self.channel.recv()
        if not isinstance(meta, MetaData_Consumer_To_Producer):
            raise TransportError(f"bad handshake metadata: {meta!r}")
        return meta

    def attach_ring(self, ring_ref: Any) -> WindowRing:
        """Adopt a SURVIVING ring (elastic rejoin): by shm name cross-
        process, by object reference in-process.  The ring's counters are
        the respawned producer's source of truth for how far its
        predecessor got."""
        if isinstance(ring_ref, WindowRing):
            self.ring = ring_ref
        else:
            from ddl_tpu.transport.shm_ring import open_shm_ring

            self.ring = open_shm_ring(ring_ref)
        self._ring_ref = ring_ref
        return self.ring

    def create_ring(self, nslots: int, slot_bytes: int) -> WindowRing:
        if self.cross_process:
            from ddl_tpu.transport.shm_ring import create_shm_ring, make_ring_name

            name = make_ring_name(f"ddl-p{self.producer_idx}")
            self.ring = create_shm_ring(name, nslots, slot_bytes)
            self._ring_ref: Any = name
        else:
            from ddl_tpu.transport.ring import ThreadRing

            self.ring = ThreadRing(nslots, slot_bytes)
            self._ring_ref = self.ring
        return self.ring

    def send_metadata(self, reply: MetaData_Producer_To_Consumer) -> None:
        reply.ring_ref = self._ring_ref  # type: ignore[attr-defined]
        self.channel.send(reply)

    def finalize(self, unlink: bool = True) -> None:
        if self.ring is not None:
            self.ring.close()
            if self.cross_process and unlink:
                self.ring.unlink()
        self.channel.close()
