"""Run configuration: one typed dataclass, JSON- and env-overridable.

The reference had no config system — every knob was a constructor argument
plus SLURM env sniffing, and its harness rolled six ad-hoc nested
dataclasses (reference ``tests/run_ddl.py:243-298``, SURVEY §5.6).  This is
the librarified version: defaults → JSON file → ``DDL_TPU_*`` env vars →
explicit kwargs, later layers winning.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

from ddl_tpu.types import RunMode


@dataclasses.dataclass
class LoaderConfig:
    """Everything the pipeline needs, in one place."""

    # topology
    mode: str = RunMode.THREAD.value
    n_producers: int = 2
    nslots: int = 2
    # host identity (ddl_tpu.cluster): with several consumer processes
    # per physical host, jax.process_index() over-counts hosts — the
    # membership view and placement engine need REAL host boundaries.
    # -1/0 = auto-detect (DDL_TPU_HOST_ID/N_HOSTS env, then SLURM node
    # vars, then procs_per_host arithmetic over the process grid —
    # ddl_tpu.env.detect_host_identity).
    host_id: int = -1
    n_hosts: int = 0
    procs_per_host: int = 0  # 0 = auto (SLURM_NTASKS_PER_NODE or 1)
    # batch geometry
    batch_size: int = 32
    n_epochs: int = 1
    # global shuffle
    global_shuffle_fraction_exchange: float = 0.0
    exchange_method: str = "sendrecv_replace"
    shuffle_seed: int = 0
    # consumer output ("jax" — TPU-native default; the bare
    # DistributedDataLoader keeps the reference's torch-first default)
    output: str = "jax"
    # zero-copy window streaming (Trainer.fit window_stream; jax output)
    window_stream: bool = False
    # failure detection
    ring_timeout_s: float = 300.0
    stall_budget_s: float = 120.0
    # checkpointing
    checkpoint_dir: Optional[str] = None
    checkpoint_every_epochs: int = 0  # 0 = disabled
    # shard cache (ddl_tpu.cache; docs/CACHING.md).  Mirrors the
    # DDL_TPU_CACHE* env knobs — distributed_dataloader exports these
    # fields back into the environment so PROCESS-mode producer workers
    # build the same store.
    cache: bool = False
    cache_ram_mb: int = 256
    cache_spill_dir: Optional[str] = None
    cache_spill_mb: int = 1024
    cache_warm: bool = True
    # Lossless codec for spilled cache entries ("" = raw bytes; "zlib"
    # always available, "zstd"/"lz4" gated on the host library).  Was
    # env-only (DDL_TPU_CACHE_CODEC) with no config mirror — the stale
    # spawn-boundary drift ddl-verify VP003 now machine-checks.
    cache_codec: str = ""
    # Wire format (ddl_tpu.wire; docs/PERF_NOTES.md "Wire format").
    # ``wire_dtype``: "" = no opinion (the per-reader capability
    # decides), "raw" = kill switch, "bf16"/"int8" = force the lossy
    # tier (A/B runs; licensed by the loss-parity gate).  ``wire_codec``:
    # "" / "none" = off, else a lossless codec name ("zlib" always;
    # "zstd"/"lz4" where the host has the library) for the shuffle
    # exchange wire and compressed shard/cache reads.  Mirrored into
    # DDL_TPU_WIRE_DTYPE / DDL_TPU_WIRE_CODEC ahead of producer spawn
    # (ddl_tpu.env._export_wire_knobs).
    wire_dtype: str = ""
    wire_codec: str = ""
    # Device-tier global shuffle (ddl_tpu.ops.device_shuffle;
    # docs/PERF_NOTES.md "Device-side global shuffle").
    # ``device_shuffle``: "auto" = engage the device exchange when
    # plannable (THREAD topology, raw wire, in-process fabric),
    # "0"/"off"/"false" = host exchange only.  ``shuffle_impl``:
    # "ring" = Pallas remote-DMA ring (double-buffered, rides a landing
    # slot), "xla" = jitted ppermute lanes.  Mirrored into
    # DDL_TPU_DEVICE_SHUFFLE / DDL_TPU_SHUFFLE_IMPL ahead of producer
    # spawn (ddl_tpu.env._export_shuffle_knobs).
    device_shuffle: str = "auto"
    shuffle_impl: str = "ring"
    # Device transfers kept in flight by DistributedDataLoader.prefetch
    # (ddl_tpu.ingest.PrefetchIterator).  A first-class config field —
    # not a call-site literal — so the boot-time Calibrator and the
    # steady-state KnobController (ddl_tpu.tune) have a seam to retune
    # it through, with DDL_TPU_PREFETCH_DEPTH as the env mirror.
    prefetch_depth: int = 2

    _ENV_PREFIX = "DDL_TPU_"

    @classmethod
    def load(cls, path: Optional[str] = None, **overrides: Any) -> "LoaderConfig":
        """defaults → JSON file → env (`DDL_TPU_<FIELD>`) → kwargs."""
        return _load_layered(cls, path, overrides)

    def save(self, path: str) -> None:
        _save_json(self, path)

    def run_mode(self) -> RunMode:
        return RunMode(self.mode)


@dataclasses.dataclass
class TrainConfig:
    """Training hot-path knobs — the consumer-compute half of a run
    (the model/trainer twin of :class:`LoaderConfig`), env-overridable
    as ``DDL_TPU_TRAIN_<FIELD>``.

    ``remat`` names the rematerialisation policy
    (:mod:`ddl_tpu.models.remat`: none/full/selective/dots) and is
    applied to a model config with :meth:`model_config`; ``schedule`` /
    ``pp_chunks`` select the pipeline schedule
    (:func:`ddl_tpu.parallel.pipeline_apply`) and feed the models'
    ``*_pp`` entry points via :meth:`pipeline_kwargs`; ``accum_steps``
    flows into the :class:`~ddl_tpu.trainer.Trainer` constructor; the
    distributed-optimizer knobs (``optimizer_sharding`` / ``grad_comm``
    / ``grad_comm_block`` / ``stochastic_rounding``) flow into the step
    factories via :meth:`optimizer_kwargs`
    (``DDL_TPU_TRAIN_OPTIMIZER_SHARDING=zero1`` etc. from the env).
    """

    #: Remat policy for the backward pass (``ddl_tpu.models.remat``).
    remat: str = "none"
    #: Pipeline schedule: "gpipe" or "1f1b" (interleaved stage chunks).
    schedule: str = "gpipe"
    #: Stage chunks per device for 1f1b (0 = the schedule's default, 2).
    pp_chunks: int = 0
    #: Microbatches per pipeline step (1 = no microbatching).
    n_microbatches: int = 1
    #: Gradient-accumulation microbatches per optimizer update.
    accum_steps: int = 1
    #: Distributed optimizer (``ddl_tpu.parallel.optimizer``): "none"
    #: replicates the optimizer state across dp; "zero1" shards state +
    #: weight update over the dp axis (ZeRO-1 — bit-exact at fp32,
    #: ~dp× less optimizer HBM per replica).
    optimizer_sharding: str = "none"
    #: Gradient/update communication wire format: "fp32" (exact) or
    #: "int8" (blockwise-scaled EQuARX format, licensed by the
    #: loss-curve-parity gate — ``parallel.optimizer.loss_parity``).
    grad_comm: str = "fp32"
    #: int8 block size (values per fp32 scale); 0 = the collectives
    #: default (``parallel.collectives.QUANT_BLOCK``).
    grad_comm_block: int = 0
    #: Stochastic rounding on the int8 wire format (unbiased in
    #: expectation; deterministic given the step's gradient values).
    stochastic_rounding: bool = False

    _ENV_PREFIX = "DDL_TPU_TRAIN_"

    @classmethod
    def load(cls, path: Optional[str] = None, **overrides: Any) -> "TrainConfig":
        """defaults → JSON file → env (`DDL_TPU_TRAIN_<FIELD>`) → kwargs."""
        cfg = _load_layered(cls, path, overrides)
        from ddl_tpu.models import remat as _remat

        _remat.resolve(cfg.remat)  # fail on junk at load time
        if cfg.schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown schedule {cfg.schedule!r}")
        if cfg.optimizer_sharding not in ("none", "zero1"):
            raise ValueError(
                f"unknown optimizer_sharding {cfg.optimizer_sharding!r} "
                "(valid: none, zero1)"
            )
        if cfg.grad_comm not in ("fp32", "int8"):
            raise ValueError(
                f"unknown grad_comm {cfg.grad_comm!r} (valid: fp32, int8)"
            )
        return cfg

    def save(self, path: str) -> None:
        _save_json(self, path)

    def model_config(self, model_cfg: Any) -> Any:
        """The model config with this TrainConfig's remat policy applied
        (works on any of the frozen model config dataclasses)."""
        return dataclasses.replace(model_cfg, remat=self.remat)

    def pipeline_kwargs(self) -> dict:
        """kwargs for the models' ``*_pp`` losses / ``pipeline_apply``."""
        return {
            "schedule": self.schedule,
            "n_chunks": self.pp_chunks or None,
        }

    def optimizer_kwargs(self) -> dict:
        """kwargs for the step factories
        (:func:`ddl_tpu.parallel.train.make_train_step` /
        :func:`~ddl_tpu.parallel.train.make_multistep`): the
        distributed-optimizer knobs, shaped for ``**`` splatting — the
        single hand-off point, so the Trainer and the bench cannot
        plumb a different subset."""
        return {
            "optimizer_sharding": self.optimizer_sharding,
            "grad_comm": self.grad_comm,
            "grad_comm_block": self.grad_comm_block,
            "stochastic_rounding": self.stochastic_rounding,
        }


def _load_layered(cls: Any, path: Optional[str], overrides: dict) -> Any:
    """THE layered-config loader both config classes share: defaults →
    JSON file → env (``<cls._ENV_PREFIX><FIELD>``) → kwargs, later
    layers winning, unknown JSON keys rejected.  One implementation so
    the layering/coercion semantics cannot drift between
    :class:`LoaderConfig` and :class:`TrainConfig`."""
    values: dict = {}
    if path:
        with open(path) as f:
            loaded = json.load(f)
        unknown = set(loaded) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ValueError(
                f"unknown config keys in {path}: {sorted(unknown)}"
            )
        values.update(loaded)
    # Lazy: envspec imports this module to derive the knob families.
    from ddl_tpu import envspec

    for field in dataclasses.fields(cls):
        if field.name.startswith("_"):
            continue
        # envspec.raw fails loudly on an unregistered name, so a new
        # config field cannot silently bypass the knob registry (the
        # families auto-register from dataclasses.fields).
        env = envspec.raw(cls._ENV_PREFIX + field.name.upper())
        if env is not None:
            values[field.name] = _coerce(env, field.type)
    values.update(overrides)
    return cls(**values)


def _save_json(cfg: Any, path: str) -> None:
    with open(path, "w") as f:
        json.dump(dataclasses.asdict(cfg), f, indent=2)


def _coerce(raw: str, annot: Any) -> Any:
    annot = str(annot)
    if "int" in annot:
        return int(raw)
    if "float" in annot:
        return float(raw)
    if "bool" in annot:
        return raw.lower() in ("1", "true", "yes")
    return raw
