"""Run configuration: one typed dataclass, JSON- and env-overridable.

The reference had no config system — every knob was a constructor argument
plus SLURM env sniffing, and its harness rolled six ad-hoc nested
dataclasses (reference ``tests/run_ddl.py:243-298``, SURVEY §5.6).  This is
the librarified version: defaults → JSON file → ``DDL_TPU_*`` env vars →
explicit kwargs, later layers winning.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

from ddl_tpu.types import RunMode


@dataclasses.dataclass
class LoaderConfig:
    """Everything the pipeline needs, in one place."""

    # topology
    mode: str = RunMode.THREAD.value
    n_producers: int = 2
    nslots: int = 2
    # batch geometry
    batch_size: int = 32
    n_epochs: int = 1
    # global shuffle
    global_shuffle_fraction_exchange: float = 0.0
    exchange_method: str = "sendrecv_replace"
    shuffle_seed: int = 0
    # consumer output ("jax" — TPU-native default; the bare
    # DistributedDataLoader keeps the reference's torch-first default)
    output: str = "jax"
    # zero-copy window streaming (Trainer.fit window_stream; jax output)
    window_stream: bool = False
    # failure detection
    ring_timeout_s: float = 300.0
    stall_budget_s: float = 120.0
    # checkpointing
    checkpoint_dir: Optional[str] = None
    checkpoint_every_epochs: int = 0  # 0 = disabled
    # shard cache (ddl_tpu.cache; docs/CACHING.md).  Mirrors the
    # DDL_TPU_CACHE* env knobs — distributed_dataloader exports these
    # fields back into the environment so PROCESS-mode producer workers
    # build the same store.
    cache: bool = False
    cache_ram_mb: int = 256
    cache_spill_dir: Optional[str] = None
    cache_spill_mb: int = 1024
    cache_warm: bool = True

    _ENV_PREFIX = "DDL_TPU_"

    @classmethod
    def load(cls, path: Optional[str] = None, **overrides: Any) -> "LoaderConfig":
        """defaults → JSON file → env (`DDL_TPU_<FIELD>`) → kwargs."""
        values: dict = {}
        if path:
            with open(path) as f:
                loaded = json.load(f)
            unknown = set(loaded) - {f.name for f in dataclasses.fields(cls)}
            if unknown:
                raise ValueError(f"unknown config keys in {path}: {sorted(unknown)}")
            values.update(loaded)
        for field in dataclasses.fields(cls):
            if field.name.startswith("_"):
                continue
            env = os.environ.get(cls._ENV_PREFIX + field.name.upper())
            if env is not None:
                values[field.name] = _coerce(env, field.type)
        values.update(overrides)
        return cls(**values)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(dataclasses.asdict(self), f, indent=2)

    def run_mode(self) -> RunMode:
        return RunMode(self.mode)


def _coerce(raw: str, annot: Any) -> Any:
    annot = str(annot)
    if "int" in annot:
        return int(raw)
    if "float" in annot:
        return float(raw)
    if "bool" in annot:
        return raw.lower() in ("1", "true", "yes")
    return raw
