"""Device ingest: host windows → TPU HBM.

The reference stopped at host memory — GPU transfer was left to the user
(commented out in its harness, reference ``tests/run_ddl.py:233-235``).  On
TPU the HBM hop is mandatory, so hiding it is a core feature
(SURVEY §8.3 "hard part #3"):

- :class:`DeviceIngestor` — async ``device_put`` of host batches onto a
  device or a sharded mesh (``jax.device_put`` returns immediately; the
  transfer overlaps subsequent host work).  This backs the loader's
  ``output="jax"`` mode.
- :class:`PrefetchIterator` — keeps N transfers in flight ahead of
  compute; used by training loops and the benchmark harness around any
  host-batch iterator.
"""

from __future__ import annotations

import collections
import os
import time
from typing import Any, Optional, Sequence, Tuple

import numpy as np

from ddl_tpu import envspec
from ddl_tpu.observability import Metrics, metrics as default_metrics
from ddl_tpu.staging import StagedTransfer, staged_enabled


class DeviceIngestor:
    """Puts host batches onto a device (or a sharded mesh) asynchronously.

    With ``sharding`` set (a ``jax.sharding.Sharding``), batches land
    sharded across the mesh — the data-parallel ingest path.  Otherwise
    they land on ``device`` (default: first local device).

    ``staged`` (default: the ``DDL_TPU_STAGED`` env gate, on) routes
    staging copies through a recycled-buffer pool and, for the lookahead
    consumers (:class:`PrefetchIterator`, ``DistributedDataLoader.
    windows``), through a background copy/transfer executor
    (:mod:`ddl_tpu.staging`).  ``staged=False`` is the inline escape
    hatch: fresh ``copy=True`` staging on the caller thread, exactly the
    pre-engine behavior.
    """

    def __init__(
        self,
        device: Any = None,
        sharding: Any = None,
        metrics: Optional[Metrics] = None,
        staged: Optional[bool] = None,
        distribute: Optional[str] = None,
    ):
        import jax

        self._jax = jax
        self.sharding = sharding
        self.device = device
        if sharding is None and device is None:
            self.device = jax.local_devices()[0]
        self.metrics = metrics or default_metrics()
        self.staged = staged_enabled(staged)
        #: Explicit constructor intent (None = env default) — the window
        #: stream distinguishes "forced on" from "default on" (below).
        self._staged_arg = staged
        self._engine: Any = None
        #: Post-H2D distribution tier: "ici" routes the device-side hop
        #: through the Pallas fan-out + redistribution planner
        #: (ddl_tpu/parallel/ici.py), "xla" keeps the pre-existing
        #: sharded device_put, "auto" (the default, also via the
        #: DDL_TPU_DISTRIBUTE env) picks ici on accelerator meshes and
        #: xla on the CPU client (where there is no ICI to control) —
        #: DDL_TPU_ICI_INGEST=0 is the auto-mode kill switch.
        distribute = distribute or envspec.get("DDL_TPU_DISTRIBUTE")
        if distribute not in ("ici", "xla", "auto"):
            raise ValueError(
                f"distribute must be ici|xla|auto, got {distribute!r}"
            )
        self.distribute = distribute
        self._ici: Any = None  # lazily-built IciDistributor

    @property
    def stream_staged(self) -> bool:
        """Should the WINDOW STREAM route through the staging engine?

        The batch paths always staged a host copy, so pooling/offloading
        them is strictly-no-worse everywhere.  The stream is different:
        inline ``put_window`` is the ZERO-COPY path (transfer straight
        from the ring slot), so staging it adds a whole host memcpy per
        window.  That trade buys early slot release — worth it where the
        transfer is a genuine DMA the slot would otherwise sit acquired
        behind (accelerators), and a pure loss on the CPU client, which
        can alias host buffers into "device" arrays (measured ~2x slower
        staged).  Default: staged on accelerators, inline on CPU;
        ``staged=True`` passed explicitly forces the engine everywhere
        (tests, experiments).
        """
        if not self.staged:
            return False
        if self._staged_arg is True:
            return True
        return self._target_platform() != "cpu"

    @property
    def batch_staged(self) -> bool:
        """Should the BATCH paths (``put``/``put_batch``/prefetch
        offload) stage through the recycled pool + background executor?

        The CPU PJRT client zero-copy-aliases 64-byte-aligned host
        buffers — which pooled ``np.empty`` staging buffers are — so on
        CPU every buffer is alias-dropped after its first transfer: an
        ALL-MISS pool whose per-transfer pointer walk, sweep, and gauge
        bookkeeping are pure ceremony on top of the same fresh
        allocation the inline path does plainly (measured on the 2-core
        box: inline no-prefetch 83.2k vs staged 74.5k samples/s —
        docs/PERF_NOTES.md "Write-once producers").  Accelerator puts
        genuinely copy, the pool recycles, and the executor buys
        overlap.  ``staged=True`` passed explicitly forces the engine
        everywhere (tests, A/B measurement).

        The decision predicate is deliberately the stream's (does this
        client's put genuinely copy?), so it DELEGATES — two copies of
        the same gate would drift."""
        return self.stream_staged

    @property
    def stream_alias(self) -> bool:
        """Should staged window-stream jobs ALIAS the ring slot (skip the
        slot→staging memcpy)?  True on accelerators under the
        ``DDL_TPU_SHM_STAGING`` gate: their ``device_put`` is a genuine
        host→HBM copy, so once the transfer completes nothing reads the
        slot and it can be released with ZERO host memcpys between
        producer fill and HBM.  The CPU client may zero-copy-alias host
        pages into "device" arrays, so it stays on the copying pool —
        and the executor's per-transfer ``unsafe_buffer_pointer`` check
        latches a fallback if an unrecognized client aliases anyway."""
        from ddl_tpu.staging import shm_staging_enabled

        return (
            self.stream_staged
            and shm_staging_enabled()
            and self._target_platform() != "cpu"
        )

    @property
    def ici_active(self) -> bool:
        """Does the post-H2D hop ride the ICI tier (fan-out kernel +
        redistribution planner) instead of an XLA-scattered
        ``device_put``?

        Requires a multi-device ``NamedSharding`` target and a single
        JAX process (the multihost assembly path owns its own
        distribution).  ``distribute="ici"`` forces the tier anywhere —
        including the CPU virtual mesh, where the kernel runs in
        interpret mode (that is how tier-1 proves byte identity);
        ``"auto"`` engages it only on accelerator meshes, gated by
        ``DDL_TPU_ICI_INGEST`` (default on — the distributor latches an
        xla fallback on any DMA failure, so auto cannot strand a run).
        """
        if self.distribute == "xla" or self.sharding is None:
            return False
        if getattr(self.sharding, "mesh", None) is None:
            return False  # ici needs a named mesh to plan over
        if len(self.sharding.device_set) <= 1:
            return False
        if self._jax.process_count() > 1:
            return False
        if self.distribute == "ici":
            return True
        return (
            self._target_platform() != "cpu"
            and envspec.flag("DDL_TPU_ICI_INGEST")
        )

    def ici(self):
        """The lazily-built ICI distributor (plan + kernel caches)."""
        if self._ici is None:
            from ddl_tpu.parallel.ici import IciDistributor

            self._ici = IciDistributor(
                self.sharding, metrics=self.metrics
            )
        return self._ici

    # -- staging engine ----------------------------------------------------

    def engine(self):
        """The lazily-built staging engine (pool + background executor).

        Built on first use so host-output loaders and ``staged=False``
        ingestors never pay for a worker thread.  Buffer-recycling
        safety against CPU zero-copy puts is checked per transfer by the
        pool itself (see :func:`ddl_tpu.staging._may_alias`).
        """
        if self._engine is None:
            from ddl_tpu.staging import StagedIngestEngine

            self._engine = StagedIngestEngine(metrics=self.metrics)
        return self._engine

    def close(self) -> None:
        """Stop the background executor and flush pooled buffers."""
        if self._engine is not None:
            self._engine.close()

    def _stage(self, arr: np.ndarray) -> np.ndarray:
        """Copy ``arr`` into a pooled staging buffer (timed)."""
        pool = self.engine().pool
        buf = pool.acquire(arr.shape, arr.dtype)
        t0 = time.perf_counter()
        np.copyto(buf, arr, casting="no")
        self.metrics.add_time(
            "ingest.stage_copy", time.perf_counter() - t0
        )
        return buf

    def put(self, cols: Sequence[np.ndarray]) -> Tuple[Any, ...]:
        """Transfer a tuple of column arrays; returns JAX arrays.

        ``device_put`` is async — the returned arrays are futures whose
        transfers overlap subsequent host work.  Columns are copied out of
        the ring slot first: the transfer source must stay valid after the
        slot is released back to the producer, so an explicit copy is
        mandatory (``ascontiguousarray`` would pass an already-contiguous
        slot view through uncopied and the producer would overwrite it
        mid-transfer).  Staged mode stages into recycled pool buffers;
        inline mode allocates fresh.
        """
        from ddl_tpu.profiling import annotate

        with annotate("ddl.ingest_put"):
            if self.batch_staged:
                pool = self.engine().pool
                out = []
                for c in cols:
                    buf = self._stage(c)
                    dev = self._transfer(buf)
                    pool.recycle_when_ready(buf, dev)
                    out.append(dev)
                out = tuple(out)
                pool.sweep()
            else:
                # Inline fresh copy: the DDL_TPU_STAGED=0 escape hatch
                # AND the CPU-client default (an aliasing client makes
                # the pool all-miss ceremony — see batch_staged).
                out = tuple(
                    self._transfer(
                        np.array(c, copy=True)  # ddl-lint: disable=DDL011
                    )
                    for c in cols
                )
        self.metrics.incr(
            "ingest.bytes", float(sum(int(c.nbytes) for c in cols))
        )
        self.metrics.incr("ingest.batches")
        return out

    def put_batch(
        self, batch: np.ndarray, splits: Sequence[int]
    ) -> Tuple[Any, ...]:
        """Transfer one unsplit batch, splitting into columns ON DEVICE.

        One copy + one transfer instead of one of each per column: narrow
        columns (a label column is ~KiB) otherwise pay the link's fixed
        per-transfer cost for a few bytes (measured 0.15 ms per 8 KiB put
        — tools/probe_ingest.py).  The device-side column slices are
        sub-microsecond XLA ops.
        """
        from ddl_tpu.profiling import annotate

        with annotate("ddl.ingest_put"):
            if self.batch_staged:
                pool = self.engine().pool
                buf = self._stage(batch)
                dev = self._transfer(buf)
                pool.recycle_when_ready(buf, dev)
                pool.sweep()
            else:
                # Inline fresh copy (DDL_TPU_STAGED=0, and the CPU-client
                # default — see batch_staged).
                dev = self._transfer(
                    np.array(batch, copy=True)  # ddl-lint: disable=DDL011
                )
        self.metrics.incr("ingest.bytes", float(batch.nbytes))
        self.metrics.incr("ingest.batches")
        return _device_split(dev, splits)

    def batch_transfer_fn(self, splits: Sequence[int]):
        """A :data:`~ddl_tpu.staging.TransferFn` running this ingestor's
        single-transfer batch put from an already-staged buffer — what
        the background executor runs after its slot→staging copy."""

        def transfer(buf: np.ndarray):
            dev = self._transfer(buf)
            self.metrics.incr("ingest.bytes", float(buf.nbytes))
            self.metrics.incr("ingest.batches")
            return _device_split(dev, splits), dev

        return transfer

    def _transfer(self, arr: np.ndarray) -> Any:
        """One host→device transfer honouring the multihost case: with
        multiple JAX processes each host contributes its local shard of
        the global array (same assembly as :func:`make_global_array`).

        With the ICI tier active the hop splits in two: H2D lands the
        whole buffer on the plan's anchor device (one link crossing),
        then the fan-out kernel + redistribution legs move it to the
        target sharding entirely over ICI — XLA never scatters from the
        host.  The distributor owns its own failure ladder (latched xla
        fallback), so this seam stays exception-free."""
        target = self.sharding if self.sharding is not None else self.device
        if self.sharding is not None and self._jax.process_count() > 1:
            return self._jax.make_array_from_process_local_data(
                self.sharding, arr
            )
        if self.ici_active:
            return self.ici().put(arr, self._jax.device_put)
        return self._jax.device_put(arr, target)

    def put_window(
        self, window: np.ndarray, defer_metrics: bool = False
    ) -> Any:
        """Transfer a whole window WITHOUT a host copy.

        The source may be a live ring-slot view: the caller must keep the
        slot acquired until the returned array is ready
        (``jax.block_until_ready``) — that is what
        ``DistributedDataLoader.windows`` does.  One large transfer per
        window beats per-batch/per-column puts wherever the link has fixed
        per-transfer cost (measured on the bench attach: an 8 KiB put costs
        0.15 ms against a 1.4 GB/s link — tools/probe_ingest.py).

        ``defer_metrics=True`` skips the ``ingest.bytes``/``ingest.windows``
        accounting here so the caller can record it when the transfer
        *completes* — the window stream does this so bytes-arrived and
        samples-served counters cover identical windows over any
        measurement span (a dispatch-time count leads completion by the
        whole lookahead depth).
        """
        from ddl_tpu.obs import spans as obs_spans
        from ddl_tpu.profiling import annotate

        if self._target_platform() == "cpu":
            # The CPU PJRT client may *alias* a compatible host buffer
            # instead of copying — the returned array would then observe
            # the producer's next refill through the released slot.  On an
            # accelerator the put is a genuine transfer and the zero-copy
            # path is safe.
            window = np.array(window, copy=True)
        # Dispatch span, keyed on the thread's current-window context
        # (set by the stream / staging executor) — the transfer itself
        # is async; completion shows up as the consumer.release mark.
        _span_t0 = obs_spans.t0()
        with annotate("ddl.ingest_put_window"):
            out = self._transfer(window)
        obs_spans.record(
            "ingest.transfer", *obs_spans.current_window(), _span_t0
        )
        if not defer_metrics:
            self.metrics.incr("ingest.bytes", float(window.nbytes))
            self.metrics.incr("ingest.windows")
        return out

    def window_source_detached(self) -> bool:
        """Does :meth:`put_window` detach the transfer from its host
        source?  True on the CPU client, whose alias-guard copy means
        the returned array never reads the ring slot again — the caller
        may release the slot immediately at yield.  On accelerators the
        transfer sources the slot directly (zero-copy), so release must
        wait for transfer completion (``DistributedDataLoader``'s
        readiness-gated backlog)."""
        return self._target_platform() == "cpu"

    def _target_platform(self) -> str:
        if self.sharding is not None:
            dev = next(iter(self.sharding.device_set))
        else:
            dev = self.device
        return getattr(dev, "platform", "cpu")


def make_global_array(
    local_batch: np.ndarray, sharding: Any, axis: str = "dp"
) -> Any:
    """Assemble a process-local host batch into a global dp-sharded array.

    Multihost ingest: every host's loader drains its own producers'
    windows (the per-host shard of the global batch), and this stitches
    them into one global ``jax.Array`` without gathering — the TPU analog
    of the reference's per-instance window ownership
    (reference ``ddl/ddl_env.py:45-50``: each trainer only ever reads its
    own block's producers).

    Single-process (including the 8-device CPU sim), the local batch IS the
    global batch and this is a sharded ``device_put``.
    """
    import jax

    # Copy before the async transfer: the input is typically a view of a
    # ring slot that the producer will refill once the caller releases it.
    local_batch = np.array(local_batch, copy=True)
    if jax.process_count() == 1:
        return jax.device_put(local_batch, sharding)
    return jax.make_array_from_process_local_data(sharding, local_batch)


def measure_h2d_bandwidth(
    nbytes: int = 1 << 26, device: Any = None, trials: int = 3
) -> float:
    """Measured host→device link capability in bytes/sec.

    The denominator for BASELINE.md's "≥90% bandwidth utilization" target
    (VERDICT r2 Missing #8: utilization previously had no denominator).
    Measured, not quoted from a spec sheet, so it is honest on any attach
    (PCIe on a real host, the tunnel on the bench box).
    """
    import time

    import jax

    if device is None:
        device = jax.local_devices()[0]
    buf = np.ones(nbytes, np.uint8)
    jax.block_until_ready(jax.device_put(buf, device))  # warmup
    best = 0.0
    for _ in range(trials):
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(buf, device))
        best = max(best, nbytes / (time.perf_counter() - t0))
    return best


def north_star_report(
    metrics: Optional[Metrics] = None,
    link_bytes_per_sec: Optional[float] = None,
) -> dict:
    """The BASELINE.md metric set, computed from the shared registry.

    Note ``ingest_bytes_per_sec`` counts *device transfers* only — it stays
    zero in host-output (numpy/torch) runs by design.  Pass
    ``link_bytes_per_sec`` (e.g. from :func:`measure_h2d_bandwidth`) to get
    ``bandwidth_utilization`` — achieved ingest over link capability.
    """
    m = metrics or default_metrics()
    # Metrics.rates() computes every rate over ONE elapsed snapshot, so
    # bytes/s and samples/s agree exactly when their counters cover
    # identical windows (they do on the stream path — completion-time
    # accounting in DistributedDataLoader.windows).
    report = dict(m.rates())
    report["windows"] = m.counter("consumer.windows")
    # Staged-ingest observability (ddl_tpu.staging): where the engine's
    # time went (staging memcpy, observed transfer spans, consumer pop
    # stalls) and whether the buffer pool is actually recycling.
    report["stage_copy_s"] = m.timer("ingest.stage_copy").total_s
    report["transfer_s"] = m.timer("ingest.transfer").total_s
    report["stall_s"] = m.timer("ingest.stall").total_s
    report["pool_hits"] = m.counter("staging.pool_hits")
    report["pool_misses"] = m.counter("staging.pool_misses")
    report["queue_depth_max"] = m.gauge("staging.queue_depth.max")
    # Shm-backed (zero-copy) staging: windows whose transfer sourced the
    # ring slot directly (no slot→staging memcpy), and jobs the
    # per-transfer alias check bounced back to the copying pool.
    report["alias_windows"] = m.counter("staging.alias_windows")
    report["alias_fallbacks"] = m.counter("staging.alias_fallbacks")
    # Training hot-path observability (ISSUE 5): time the trainer's
    # stream loop spent waiting for the next window (overlap health —
    # near zero when H2D hides behind the scans), time the loader spent
    # in FORCED transfer-completion waits before slot release, and the
    # analytic bubble/chunking of the last-compiled pipeline schedule.
    report["window_wait_s"] = m.timer("trainer.window_wait").total_s
    report["release_wait_s"] = m.timer("ingest.release_wait").total_s
    # The pp gauges are PROCESS-level trace-time facts (pipeline_apply
    # records them once per compilation, on the default registry — it
    # cannot see a run's private registry), so read them from the
    # default registry even when reporting a private one; otherwise
    # every private-registry run reports 0.0 for a schedule that ran.
    report["pp_bubble"] = default_metrics().gauge("pp.bubble")
    report["pp_chunks"] = default_metrics().gauge("pp.chunks")
    # Robustness observability (ISSUE 3): recovery events must be visible
    # in the report and the bench JSON trajectories, not just in logs —
    # a "passing" run that silently replayed half its windows is a
    # regression the BENCH_* history should show.
    report["respawns"] = m.counter("watchdog.respawns")
    report["watchdog_failures"] = m.counter("watchdog.failures")
    report["corrupt_windows"] = m.counter("integrity.corrupt_windows")
    report["replays"] = m.counter("integrity.replays")
    report["shuffle_degraded"] = m.counter("shuffle.degraded")
    report["staging_retries"] = m.counter("staging.retries")
    report["inline_fallbacks"] = m.counter("staging.inline_fallbacks")
    # Shard-cache observability (ddl_tpu.cache, ISSUE 4): the warm tier's
    # effectiveness (hit ratio), pressure (evictions/spills), and health
    # (quarantines = corrupt disk entries healed by refetch) belong in
    # the same report the bench JSON charts — a run whose "warm" epochs
    # quietly missed every shard is a perf regression, and one that
    # quarantined entries deserves a look even when throughput held.
    report["cache_hits"] = m.counter("cache.hits")
    report["cache_misses"] = m.counter("cache.misses")
    report["cache_evictions"] = m.counter("cache.evictions")
    report["cache_spills"] = m.counter("cache.spills")
    report["cache_spill_hits"] = m.counter("cache.spill_hits")
    report["cache_quarantined"] = m.counter("cache.quarantined")
    report["cache_resident_bytes"] = m.gauge("cache.resident_bytes")
    report["cache_resident_bytes_max"] = m.gauge("cache.resident_bytes.max")
    # ICI ingest tier (ddl_tpu/parallel/ici.py, ISSUE 7): wire bytes the
    # device-side fan-out moved, dispatch time split between the Pallas
    # kernel and the redistribution legs, the plan's asserted per-device
    # peak, and fallback latches (a nonzero ici_fallbacks on a run that
    # "passed" means the tier degraded to the xla path mid-stream).
    report["ici_bytes"] = m.counter("ici.bytes")
    report["ici_windows"] = m.counter("ici.windows")
    report["ici_fallbacks"] = m.counter("ici.fallbacks")
    report["ici_fanout_s"] = m.timer("ici.fanout").total_s
    report["ici_redistribute_s"] = m.timer("ici.redistribute").total_s
    report["ici_peak_bytes"] = m.gauge("ici.peak_bytes")
    # Fused compute/ingest step (ISSUE 12): how much of the data plane
    # actually hid under the train step.  ``ingest_overlap_s`` is the
    # trainer-measured lower bound on hidden ingest time (acquire spans
    # that ran while the previous scan was still computing),
    # ``fused_windows`` counts windows driven through the fused loop
    # (``trainer.*``; the distributor's own two-slot dispatches ride
    # ``ici.fused_windows`` inside the ici counters above), and
    # ``slots_in_flight`` is the HIGH-WATER landing-slot occupancy —
    # 2 means the double-buffer genuinely had both slots carrying
    # unresolved windows at once.
    report["ingest_overlap_s"] = m.timer("trainer.ingest_overlap").total_s
    report["fused_windows"] = m.counter("trainer.fused_windows")
    report["slots_in_flight"] = m.gauge("ici.slots_in_flight.max")
    # Distributed optimizer (ddl_tpu/parallel/optimizer.py, ISSUE 8):
    # optimizer-state bytes actually STORED per dp replica (shrinks ~dp×
    # under zero1), the per-step gradient-communication payload raw vs
    # quantized, and the measured collective-leg times.  The byte gauges
    # are trace-time facts recorded on the default registry (the
    # pp.bubble pattern — ShardedOptimizer.update cannot see a private
    # registry from inside a trace); the leg timers come from
    # ShardedOptimizer.measure_legs on whichever registry ran it.
    report["opt_state_bytes_per_replica"] = default_metrics().gauge(
        "opt.state_bytes_per_replica"
    )
    report["opt_state_bytes_total"] = default_metrics().gauge(
        "opt.state_bytes_total"
    )
    report["opt_grad_comm_bytes_raw"] = default_metrics().gauge(
        "opt.grad_comm_bytes_raw"
    )
    report["opt_grad_comm_bytes_quantized"] = default_metrics().gauge(
        "opt.grad_comm_bytes_quantized"
    )
    report["opt_gather_s"] = m.timer("opt.gather").total_s
    report["opt_scatter_s"] = m.timer("opt.scatter").total_s
    # Multi-host control plane (ddl_tpu.cluster, ISSUE 10): membership
    # churn (view changes / host losses / rejoins) and the recovery
    # ladder's cross-host actions (shard adoptions, cache warm-start
    # adoptions, consumer pool updates).  A "passing" run that silently
    # lost a host and re-partitioned mid-stream must be visible in the
    # BENCH_* trajectories, exactly like respawns and replays.
    report["view_changes"] = m.counter("cluster.view_changes")
    report["host_losses"] = m.counter("cluster.host_losses")
    report["host_rejoins"] = m.counter("cluster.rejoins")
    report["heartbeats_dropped"] = m.counter("cluster.heartbeats_dropped")
    report["shard_adoptions"] = m.counter("producer.shard_adoptions")
    report["cluster_cache_adoptions"] = m.counter("cluster.cache_adoptions")
    report["pool_updates"] = m.counter("consumer.pool_updates")
    # Multi-tenant ingest service (ddl_tpu.serve, ISSUE 11): how many
    # tenants share the fabric, how the autoscaler moved the pool
    # (scale-ups via rejoin_host, scale-downs via drain-then-release),
    # total time tenants spent parked at the fair-share admission gate,
    # and each tenant's admission-stall fraction (the serve.stall.<t>
    # gauges AdmissionController.report refreshes) — a "fair" run whose
    # smallest tenant quietly waited out every round must be visible in
    # the BENCH_* trajectories, exactly like replays and view changes.
    report["serve_tenants"] = m.gauge("serve.tenants")
    report["serve_scale_ups"] = m.counter("serve.scale_ups")
    report["serve_scale_downs"] = m.counter("serve.scale_downs")
    report["serve_admission_waits_s"] = m.timer(
        "serve.admission_wait"
    ).total_s
    # Keyed by TENANT NAME only: set_gauge's ``.max`` high-water
    # companions are dropped, or a consumer iterating the dict would
    # see a phantom tenant "<name>.max".
    report["serve_tenant_stall"] = {
        k: v
        for k, v in m.prefixed("serve.stall.").items()
        if not k.endswith(".max")
    }
    # Data-plane wire format (ddl_tpu.wire, ISSUE 13): bytes that
    # actually traveled an encode-engaged wire (slot commits, exchange
    # envelopes, the ICI fan-out) vs the logical raw bytes the same
    # windows represent — the honest numerator/denominator pair for
    # every "the wire got smaller" claim — plus the consumer-edge
    # decode counter and the degradation-ladder counters.  SCOPE: like
    # every producer.* counter, the EXCHANGE wire's ladder events are
    # counted in the shuffler's own registry — consumer-visible in
    # THREAD mode (shared default registry), per-worker-process in
    # PROCESS mode (read them from the producer logs / the bench wire
    # mode's own shuffler registries); the slot-path decode counters
    # below are consumer-side and surface in every mode.
    report["wire_encoded_bytes"] = m.counter("wire.encoded_bytes")
    report["wire_payload_bytes"] = m.counter("wire.payload_bytes")
    report["wire_decoded_windows"] = m.counter("wire.decoded_windows")
    report["wire_decode_fails"] = m.counter("wire.decode_fails")
    report["wire_fallbacks"] = m.counter("wire.fallbacks")
    # Preemption tolerance (ISSUE 14: ddl_tpu.resilience): notices
    # absorbed and drains run, the async checkpoint tier's hot-path
    # stall (the submit timer — the ONLY stall the step loop pays) vs
    # its hidden write time, and the restore ladder's health
    # (quarantined generations / cold starts are incidents the BENCH_*
    # trajectories must chart even when the run "passed").  The
    # revocation counter is the serve-plane half of the drain ladder.
    report["resilience_notices"] = m.counter("resilience.notices")
    report["resilience_drains"] = m.counter("resilience.drains")
    report["resilience_drain_s"] = m.timer("resilience.drain").total_s
    report["resilience_ckpts"] = m.counter("resilience.ckpts")
    report["resilience_final_ckpts"] = m.counter("resilience.final_ckpts")
    report["resilience_ckpt_submit_s"] = m.timer(
        "resilience.ckpt_submit"
    ).total_s
    report["resilience_ckpt_write_s"] = m.timer(
        "resilience.ckpt_write"
    ).total_s
    report["resilience_ckpt_quarantined"] = m.counter(
        "resilience.ckpt_quarantined"
    )
    report["resilience_ckpt_cold_starts"] = m.counter(
        "resilience.ckpt_cold_starts"
    )
    report["serve_revocations"] = m.counter("serve.revocations")
    # End-to-end tracing layer (ISSUE 15: ddl_tpu.obs).  Percentiles
    # come from the bounded log-spaced histograms Metrics.observe
    # feeds: window latency (time a blocking head acquire waited for
    # its committed window) and the fair-share admission wait — the
    # p99s the tenancy/preempt benches previously computed ad hoc.
    report["window_latency_p50"] = m.quantile(
        "consumer.window_latency", 0.5
    )
    report["window_latency_p99"] = m.quantile(
        "consumer.window_latency", 0.99
    )
    report["admission_wait_p99"] = m.quantile("serve.admission_wait", 0.99)
    # Per-tenant admission p99s.  Tenants are discovered from the
    # histogram names themselves (every admit observes into
    # ingest.<tenant>.admission_wait), so the dict is complete even
    # when no AdmissionController.report() refreshed the stall gauges.
    _suffix = ".admission_wait"
    report["serve_tenant_admission_p99"] = {
        name[len("ingest."):-len(_suffix)]: m.quantile(name, 0.99)
        for name in m.hist_names("ingest.")
        if name.endswith(_suffix)
    }
    # Where the per-window time went, by pipeline stage: the curated
    # always-on timers every mode records, plus (when span tracing is
    # armed) the SpanLog's measured per-stage totals under their lane
    # names — one dict the bench JSON charts instead of ten scattered
    # *_s keys.
    breakdown = {
        "acquire_wait": m.timer("consumer.wait").total_s,
        "stage_copy": m.timer("ingest.stage_copy").total_s,
        "transfer": m.timer("ingest.transfer").total_s,
        "release_wait": m.timer("ingest.release_wait").total_s,
        "window_wait": m.timer("trainer.window_wait").total_s,
        "admission_wait": m.timer("serve.admission_wait").total_s,
        "ici_fanout": m.timer("ici.fanout").total_s,
    }
    from ddl_tpu.obs import spans as _obs_spans

    _slog = _obs_spans.log()
    if _slog is not None:
        for stage, total in _slog.stage_totals().items():
            breakdown[f"span.{stage}"] = total
    report["stage_breakdown"] = breakdown
    # Cross-process aggregation health: reports merged vs dropped
    # stale, and the flight recorder's dump count — zero in THREAD
    # mode / disarmed runs by construction.
    report["obs_reports_applied"] = m.counter("obs.reports_applied")
    report["obs_reports_stale"] = m.counter("obs.reports_stale")
    report["obs_flight_dumps"] = m.counter("obs.flight_dumps")
    # Self-tuning audit (ISSUE 20: ddl_tpu.tune).  How many knob
    # decisions the Calibrator/KnobController made, how many the
    # never-worse guard took back, and what evidence drove them — the
    # cost_source histogram (measured / declared / default) that tells
    # an operator whether this run was tuned from probes or from
    # guesses.  Zeros in untuned runs by construction.
    report["tune_decisions"] = m.counter("tune.decisions")
    report["tune_reverts"] = m.counter("tune.reverts")
    report["tune_cost_source"] = {
        src: m.counter(f"tune.cost_source.{src}")
        for src in ("measured", "declared", "default")
    }
    if link_bytes_per_sec:
        report["link_bytes_per_sec"] = link_bytes_per_sec
        report["bandwidth_utilization"] = (
            report["ingest_bytes_per_sec"] / link_bytes_per_sec
        )
    return report


class PrefetchIterator:
    """Wrap a batch iterator, keeping ``depth`` device transfers in flight.

    The standard TPU input recipe: while step k computes, batch k+1 is
    already crossing PCIe/DMA into HBM.

    Two operating modes:

    - **Staged** (``transfer`` given and the ingestor is staged): host
      batches are *enqueued* to the background executor, which stages
      them into pooled buffers and dispatches the transfers off-thread —
      ``__next__`` never copies; it only pops ready device values (pop
      wait time accumulates into ``ingest.stall``).  Offload is
      ADAPTIVE: when the worker demonstrably loses every claim to the
      consumer's work-stealing (a GIL/core-saturated host, where
      per-batch handoffs cost without buying overlap), fills switch to
      direct pooled puts — dispatch-now, recycled buffers — and
      periodically re-probe the executor in case cores free up.
    - **Inline** (default): each fill calls ``put`` on the caller thread
      — the pre-engine behavior, and the path for tuple-shaped host
      batches the single-buffer executor does not model.
    """

    #: Consecutive consumer-stolen jobs before concluding the worker is
    #: starved, and the direct-put span to run before probing it again.
    #: Each probe miss costs one handoff's ceremony (~ms on a saturated
    #: host), so conclude fast and re-probe sparsely.
    PROBE_MISSES = 2
    DIRECT_SPAN = 256

    def __init__(
        self,
        it: Any,
        ingestor: DeviceIngestor,
        depth: Optional[int] = None,
        put: Any = None,
        transfer: Any = None,
    ):
        """``put`` overrides the inline transfer call (default
        ``ingestor.put``) — e.g. a bound ``put_batch`` for
        single-transfer column batches.  ``transfer`` (a staged
        :data:`~ddl_tpu.staging.TransferFn`, e.g. from
        ``ingestor.batch_transfer_fn``) selects staged mode instead;
        staged direct-mode fills use ``put``, so pass both for the
        adaptive fallback to stay on the pooled path.  ``depth=None``
        reads ``DDL_TPU_PREFETCH_DEPTH`` (the tunable seam)."""
        self._it = iter(it)
        self._ingestor = ingestor
        self._put = put or ingestor.put
        # Gated on batch_staged: on the aliasing CPU client the executor
        # handoff costs without buying overlap (all-miss pool), so fills
        # go straight through `put` there.
        self._transfer = transfer if ingestor.batch_staged else None
        if depth is None:
            depth = envspec.get("DDL_TPU_PREFETCH_DEPTH")
        self._depth = max(1, depth)
        self._queue: collections.deque = collections.deque()

    def set_depth(self, depth: int) -> None:
        """Retune the in-flight transfer count live (ddl_tpu.tune).

        Takes effect on the next ``__next__`` fill: a shrink simply
        stops refilling until the queue drains below the new depth —
        already-dispatched transfers are never cancelled."""
        self._depth = max(1, int(depth))

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self) -> Any:
        engine = (
            self._ingestor.engine() if self._transfer is not None else None
        )
        while len(self._queue) < self._depth:
            try:
                host_batch = next(self._it)
            except StopIteration:
                break
            if (
                engine is not None
                and not engine.faulted
                and engine.direct_left == 0
            ):
                self._queue.append(
                    engine.submit(host_batch, self._transfer)
                )
            else:
                if engine is not None and not engine.faulted:
                    engine.direct_left -= 1
                self._queue.append(self._put(host_batch))
        if not self._queue:
            raise StopIteration
        head = self._queue.popleft()
        if isinstance(head, StagedTransfer):
            # Work-stealing pop: an unstarted head job runs inline here
            # (never slower than the inline path); a worker-claimed one
            # is a genuine wait, counted as ingest.stall.  On transfer-
            # retry exhaustion the engine salvages the verified staging
            # copy down the inline path (degradation ladder; no loss,
            # no dup — `engine.faulted` routes later batches inline).
            value = engine.complete_or_salvage(head, self._put)
            if head.worker_executed:
                engine.stolen_streak = 0
            else:
                engine.stolen_streak += 1
                if engine.stolen_streak >= self.PROBE_MISSES:
                    # The worker lost PROBE_MISSES claims in a row: it is
                    # starved for CPU and each handoff is pure overhead.
                    # Run direct pooled puts for a span, then probe again.
                    engine.stolen_streak = 0
                    engine.direct_left = self.DIRECT_SPAN
            return value
        return head


def _device_split(dev: Any, splits: Sequence[int]) -> Tuple[Any, ...]:
    """Column-split a transferred (B, sum(splits)) batch ON DEVICE."""
    out, off = [], 0
    for w in splits:
        out.append(dev[:, off : off + w])
        off += w
    return tuple(out)
