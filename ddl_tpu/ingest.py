"""Device ingest: host windows → TPU HBM.

The reference stopped at host memory — GPU transfer was left to the user
(commented out in its harness, reference ``tests/run_ddl.py:233-235``).  On
TPU the HBM hop is mandatory, so hiding it is a core feature
(SURVEY §8.3 "hard part #3"):

- :class:`DeviceIngestor` — async ``device_put`` of host batches onto a
  device or a sharded mesh (``jax.device_put`` returns immediately; the
  transfer overlaps subsequent host work).  This backs the loader's
  ``output="jax"`` mode.
- :class:`PrefetchIterator` — keeps N transfers in flight ahead of
  compute; used by training loops and the benchmark harness around any
  host-batch iterator.
"""

from __future__ import annotations

import collections
from typing import Any, Optional, Sequence, Tuple

import numpy as np

from ddl_tpu.observability import Metrics, metrics as default_metrics


class DeviceIngestor:
    """Puts host batches onto a device (or a sharded mesh) asynchronously.

    With ``sharding`` set (a ``jax.sharding.Sharding``), batches land
    sharded across the mesh — the data-parallel ingest path.  Otherwise
    they land on ``device`` (default: first local device).
    """

    def __init__(
        self,
        device: Any = None,
        sharding: Any = None,
        metrics: Optional[Metrics] = None,
    ):
        import jax

        self._jax = jax
        self.sharding = sharding
        self.device = device
        if sharding is None and device is None:
            self.device = jax.local_devices()[0]
        self.metrics = metrics or default_metrics()

    def put(self, cols: Sequence[np.ndarray]) -> Tuple[Any, ...]:
        """Transfer a tuple of column arrays; returns JAX arrays.

        ``device_put`` is async — the returned arrays are futures whose
        transfers overlap subsequent host work.  Columns are copied out of
        the ring slot first (the transfer source must stay valid after the
        slot is released back to the producer).
        """
        target = self.sharding if self.sharding is not None else self.device
        out = tuple(
            self._jax.device_put(np.ascontiguousarray(c), target) for c in cols
        )
        self.metrics.incr(
            "ingest.bytes", float(sum(int(c.nbytes) for c in cols))
        )
        self.metrics.incr("ingest.batches")
        return out


class PrefetchIterator:
    """Wrap a batch iterator, keeping ``depth`` device transfers in flight.

    The standard TPU input recipe: while step k computes, batch k+1 is
    already crossing PCIe/DMA into HBM.
    """

    def __init__(
        self,
        it: Any,
        ingestor: DeviceIngestor,
        depth: int = 2,
    ):
        self._it = iter(it)
        self._ingestor = ingestor
        self._depth = max(1, depth)
        self._queue: collections.deque = collections.deque()

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self) -> Any:
        while len(self._queue) < self._depth:
            try:
                host_batch = next(self._it)
            except StopIteration:
                break
            self._queue.append(self._ingestor.put(host_batch))
        if not self._queue:
            raise StopIteration
        return self._queue.popleft()
