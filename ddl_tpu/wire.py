"""Wire format for the data plane: quantized and compressed window bytes.

Every byte the pipeline moves — producer→consumer ring slots, the DCN
shuffle exchange, the ICI fan-out, shard fetches — has so far traveled
at the window's storage dtype.  PR 8 gave *gradients* a wire discipline
(EQuARX blockwise int8, ``parallel/collectives.py``); this module gives
the same discipline to the data plane itself (ROADMAP item 3):

- **Lossy tier** (``wire_dtype``): ``"bf16"`` halves and ``"int8"``
  quarters the wire bytes of float windows with blockwise fp32 scales
  (one per :data:`QUANT_BLOCK` values, the EQuARX granularity — the
  NUMERICS intentionally match ``parallel.collectives.quantize_blockwise``
  so the loss-parity story is one story).  Opt-in per reader
  (``ProducerFunctionSkeleton.wire_dtype``) and licensed by the same
  ``loss_parity`` gate the int8 optimizer wire is
  (``parallel.optimizer.loss_parity``): a lossy wire may never silently
  change training.
- **Lossless tier** (``codec``): general-purpose compression for
  token/image shards where quantization is wrong — ``zlib`` (stdlib,
  always available) plus ``zstd``/``lz4`` seams that engage only when
  the host has the libraries (the container may not; missing codecs are
  *named* in the error, never silently swapped).  Every codec call is
  bounded: encode takes an explicit ``level``, decode an explicit
  ``max_output`` (a corrupt length header must never balloon the
  decoder — ddl-lint DDL021 enforces both at configured wire paths).

Chaos sites ``wire.encode`` / ``wire.decode`` (``ddl_tpu.faults``):
``WIRE_CORRUPTION`` flips bytes in an encoded payload (integrity
verifies the *encoded* bytes, so the quarantine-and-replay ladder
catches it exactly like raw-slot corruption); ``DECODE_FAIL`` raises
the real :class:`~ddl_tpu.exceptions.DecodeError` so the production
retry/fallback ladders are what chaos exercises.

Accounting: encoders report ``wire.encoded_bytes`` (what actually moved)
next to ``wire.payload_bytes`` (the logical raw bytes) so every
bytes-per-second headline divides honest numerators —
``north_star_report`` surfaces both as ``wire_*`` keys.
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ddl_tpu import envspec
from ddl_tpu.exceptions import DecodeError
from ddl_tpu.faults import fault_point

#: Valid wire dtypes for the lossy tier.  "raw" is the identity (the
#: window's own storage dtype travels).
WIRE_DTYPES = ("raw", "bf16", "int8")

#: Header wire-code values (stable on-the-wire enum: the integrity
#: trailer extension and the pack_rows header both carry these).
WIRE_CODES = {"raw": 0, "bf16": 1, "int8": 2}
_CODE_TO_DTYPE = {v: k for k, v in WIRE_CODES.items()}

#: Quantization granularity (values per fp32 scale) — deliberately the
#: optimizer wire's ``parallel.collectives.QUANT_BLOCK`` so the data
#: plane and the gradient plane share one error model.
QUANT_BLOCK = 256

#: Decode output bound default: no window/exchange payload in this repo
#: exceeds it, and a corrupt compressed stream claiming more dies here
#: instead of in the allocator.
DEFAULT_MAX_OUTPUT = 1 << 31


def check_wire_dtype(wire_dtype: Optional[str]) -> str:
    """Normalise/validate a wire dtype (None → "raw")."""
    wd = wire_dtype or "raw"
    if wd not in WIRE_DTYPES:
        raise ValueError(
            f"wire_dtype must be one of {WIRE_DTYPES}, got {wire_dtype!r}"
        )
    return wd


def resolve_wire_dtype(requested: Optional[str]) -> str:
    """The effective wire dtype: ``DDL_TPU_WIRE_DTYPE`` (the operator's
    override — ``raw`` is the kill switch, a lossy value forces the
    tier on for A/B runs) wins over the per-reader capability
    (``ProducerFunctionSkeleton.wire_dtype``)."""
    env = envspec.raw("DDL_TPU_WIRE_DTYPE")
    if env is not None and env != "":
        return check_wire_dtype(env)
    return check_wire_dtype(requested)


def resolve_wire_codec(requested: Optional[str] = None) -> Optional[str]:
    """The effective lossless codec name: ``DDL_TPU_WIRE_CODEC`` wins
    when SET AND NON-EMPTY (``"none"`` is the explicit kill switch; an
    empty string states no opinion, exactly like the sibling
    :func:`resolve_wire_dtype` knob), else the requested name.
    Validated against the registry but NOT constructed — callers
    construct at use sites so a gated library fails where the bytes
    are, with the available set named."""
    env = envspec.raw("DDL_TPU_WIRE_CODEC")
    name = env if env is not None and env != "" else requested
    if not name or name == "none":
        return None
    if name not in _CODECS:
        raise ValueError(
            f"unknown codec {name!r}; known: {tuple(_CODECS)}"
        )
    return name


def lossy_supported(dtype: Any) -> bool:
    """The lossy tier only makes sense on float windows: quantizing an
    int8 token stream would corrupt ids for zero wire win (use the
    lossless codec tier there — docs/PERF_NOTES.md)."""
    return np.dtype(dtype).kind == "f"


# -- blockwise quantization (host-side numpy twin of collectives) ------------


def _nblocks(cols: int, block: int = QUANT_BLOCK) -> int:
    return -(-cols // block)


def scale_bytes_for(shape: Tuple[int, ...], wire_dtype: str,
                    block: int = QUANT_BLOCK) -> int:
    """Trailer-extension bytes the scales of one encoded window occupy
    (0 for raw/bf16 — only int8 carries per-block fp32 scales)."""
    if wire_dtype != "int8":
        return 0
    rows = int(shape[0])
    cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
    return 4 * rows * _nblocks(cols, block)


def encoded_nbytes(shape: Tuple[int, ...], dtype: Any, wire_dtype: str) -> int:
    """Payload bytes of one window after lossy encoding (scales are
    priced separately — :func:`scale_bytes_for`)."""
    n = int(np.prod(shape))
    itemsize = np.dtype(dtype).itemsize
    if wire_dtype == "raw":
        return n * itemsize
    if wire_dtype == "bf16":
        return n * 2
    return n  # int8: one byte per value


def quantize_rows(arr: np.ndarray, block: int = QUANT_BLOCK
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Blockwise int8 quantize over the last axis of a 2D row view.

    ``arr`` is reshaped to ``(rows, cols)`` (rows = ``shape[0]``);
    returns ``(q int8 (rows, cols), scales fp32 (rows, nblocks))`` with
    ``scale = max(|x|)/127`` per block (zero blocks get scale 1 so the
    round trip is exact there) — the numerics of
    ``parallel.collectives.quantize_blockwise``, round-to-nearest.
    """
    rows = arr.shape[0]
    flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(rows, -1)
    cols = flat.shape[1]
    pad = (-cols) % block
    padded = np.pad(np.abs(flat), ((0, 0), (0, pad))) if pad else np.abs(flat)
    s = padded.reshape(rows, -1, block).max(axis=-1) / 127.0
    s = np.where(s == 0.0, 1.0, s).astype(np.float32)
    expand = np.repeat(s, block, axis=1)[:, :cols]
    q = np.clip(np.rint(flat / expand), -127.0, 127.0).astype(np.int8)
    return q, s


def dequantize_rows(q: np.ndarray, scales: np.ndarray,
                    block: int = QUANT_BLOCK) -> np.ndarray:
    """Inverse of :func:`quantize_rows` (fp32, up to rounding error)."""
    cols = q.shape[1]
    expand = np.repeat(scales.astype(np.float32), block, axis=1)[:, :cols]
    return q.astype(np.float32) * expand


def encode_window(arr: np.ndarray, wire_dtype: str,
                  block: int = QUANT_BLOCK
                  ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Encode a window into its wire payload.

    Returns ``(payload uint8 1-D, scales fp32 | None)``.  Raw is a
    zero-copy byte view; bf16/int8 require a float window
    (:func:`lossy_supported`).
    """
    wire_dtype = check_wire_dtype(wire_dtype)
    if wire_dtype == "raw":
        return np.ascontiguousarray(arr).view(np.uint8).reshape(-1), None
    if not lossy_supported(arr.dtype):
        raise ValueError(
            f"lossy wire_dtype {wire_dtype!r} needs a float window, got "
            f"{np.dtype(arr.dtype).name} (use the lossless codec tier)"
        )
    if wire_dtype == "bf16":
        import ml_dtypes

        enc = np.ascontiguousarray(arr, dtype=np.float32).astype(
            ml_dtypes.bfloat16
        )
        return enc.view(np.uint8).reshape(-1), None
    q, s = quantize_rows(arr.reshape(arr.shape[0], -1), block)
    return q.view(np.uint8).reshape(-1), s


def decode_window(payload: np.ndarray, scales: Optional[np.ndarray],
                  shape: Tuple[int, ...], dtype: Any, wire_dtype: str,
                  block: int = QUANT_BLOCK, out: Optional[np.ndarray] = None
                  ) -> np.ndarray:
    """Decode a wire payload back to window shape/dtype.

    ``out`` (optional, shape/dtype-matched) receives the decode in
    place — the consumer edge's write-once discipline (DDL015: decode
    straight into the serving buffer, no extra temp copy-out).
    """
    wire_dtype = check_wire_dtype(wire_dtype)
    dtype = np.dtype(dtype)
    n = int(np.prod(shape))
    if wire_dtype == "raw":
        dec = payload[: n * dtype.itemsize].view(dtype).reshape(shape)
    elif wire_dtype == "bf16":
        import ml_dtypes

        dec = (
            payload[: n * 2].view(ml_dtypes.bfloat16)
            .astype(dtype).reshape(shape)
        )
    else:
        if scales is None:
            raise DecodeError("int8 wire payload arrived without scales")
        rows = int(shape[0])
        q = payload[:n].view(np.int8).reshape(rows, -1)
        dec = dequantize_rows(q, scales.reshape(rows, -1), block).astype(
            dtype
        ).reshape(shape)
    if out is not None:
        np.copyto(out, dec)
        return out
    return dec


# -- lossless codec seam -----------------------------------------------------


class ZlibCodec:
    """stdlib zlib — the always-available codec (levels 1-9).

    Decode auto-detects zlib AND gzip framing (``wbits=47`` = 32+15):
    :class:`~ddl_tpu.cache.backends.CodecBackend` maps the ``.gz``
    shard suffix here, and a plain ``decompressobj()`` cannot read a
    gzip header — every ``.gz`` shard would fail persistently.
    """

    name = "zlib"

    def encode_bytes(self, data: bytes, level: int) -> bytes:
        return zlib.compress(data, min(max(int(level), 1), 9))

    def decode_bytes(self, data: bytes, max_output: int) -> bytes:
        d = zlib.decompressobj(47)  # auto-detect zlib/gzip headers
        try:
            out = d.decompress(data, max_output)
        except zlib.error as e:
            raise DecodeError(f"zlib decode failed: {e}") from e
        if d.unconsumed_tail:
            raise DecodeError(
                f"zlib decode exceeded max_output={max_output} bytes"
            )
        if not d.eof:
            # A truncated stream decompresses "successfully" to partial
            # output with no exception — the torn-partial-object case
            # the retry ladders exist for must FAIL here, not surface
            # later as a short np.load/tar read.
            raise DecodeError(
                f"zlib stream truncated ({len(data)} input bytes, "
                "no end-of-stream marker)"
            )
        return out


class ZstdCodec:
    """zstandard, engaged only when the library is importable."""

    name = "zstd"

    def __init__(self) -> None:
        import zstandard  # gated: raises ImportError where absent

        self._mod = zstandard

    def encode_bytes(self, data: bytes, level: int) -> bytes:
        return self._mod.ZstdCompressor(level=int(level)).compress(data)

    def decode_bytes(self, data: bytes, max_output: int) -> bytes:
        try:
            return self._mod.ZstdDecompressor().decompress(
                data, max_output_size=max_output
            )
        except self._mod.ZstdError as e:
            raise DecodeError(f"zstd decode failed: {e}") from e


class Lz4Codec:
    """lz4.frame, engaged only when the library is importable."""

    name = "lz4"

    def __init__(self) -> None:
        import lz4.frame  # gated: raises ImportError where absent

        self._mod = lz4.frame

    def encode_bytes(self, data: bytes, level: int) -> bytes:
        return self._mod.compress(data, compression_level=int(level))

    def decode_bytes(self, data: bytes, max_output: int) -> bytes:
        try:
            out = self._mod.decompress(data)
        except RuntimeError as e:
            raise DecodeError(f"lz4 decode failed: {e}") from e
        if len(out) > max_output:
            raise DecodeError(
                f"lz4 decode exceeded max_output={max_output} bytes"
            )
        return out


#: Codec registry: name → (constructor, on-the-wire code).  Code 0 is
#: "no codec"; the constructors for zstd/lz4 raise ImportError where the
#: container lacks them — :func:`get_codec` turns that into a named
#: error and :func:`available_codecs` reports what this host can run.
_CODECS = {"zlib": (ZlibCodec, 1), "zstd": (ZstdCodec, 2), "lz4": (Lz4Codec, 3)}
_CODEC_BY_CODE = {code: name for name, (_, code) in _CODECS.items()}


def available_codecs() -> Tuple[str, ...]:
    """Codec names this host can actually construct."""
    out = []
    for name, (ctor, _) in _CODECS.items():
        try:
            ctor()
        except ImportError:
            continue
        out.append(name)
    return tuple(out)


def get_codec(name: str) -> Any:
    """Construct a codec by name, or raise naming what IS available."""
    if name not in _CODECS:
        raise ValueError(
            f"unknown codec {name!r}; known: {tuple(_CODECS)}"
        )
    ctor, _ = _CODECS[name]
    try:
        return ctor()
    except ImportError as e:
        raise ValueError(
            f"codec {name!r} needs a library this host lacks ({e}); "
            f"available here: {available_codecs()}"
        ) from e


# -- self-describing exchange payloads (the shuffle/DCN wire) ----------------

#: pack_rows header: magic, version, wire_code, codec_code, ndim,
#: dtype-name length, scales nbytes, payload nbytes, raw nbytes.
_PACK_MAGIC = 0x44444C58  # "DDLX"
_PACK_FMT = "<IHBBBBQQQ"
_PACK_BYTES = struct.calcsize(_PACK_FMT)


def pack_rows(
    rows: np.ndarray,
    wire_dtype: str = "raw",
    codec: Optional[str] = None,
    level: int = 3,
    block: int = QUANT_BLOCK,
    metrics: Any = None,
) -> np.ndarray:
    """Encode an exchange payload into one self-describing uint8 array.

    The shuffle fabrics (:class:`~ddl_tpu.shuffle.Rendezvous` /
    :class:`~ddl_tpu.shuffle.ShmRendezvous`) move numpy arrays; this
    wraps the lane rows in a wire envelope — header, shape, optional
    scales, (optionally codec-compressed) payload — so the DECODER needs
    no out-of-band agreement: a peer that latched the raw fallback still
    interoperates with one that didn't.  The ``wire.encode`` chaos site
    fires against the encoded payload bytes.
    """
    wire_dtype = check_wire_dtype(wire_dtype)
    payload, scales = encode_window(rows, wire_dtype, block)
    raw_nbytes = int(rows.nbytes)
    codec_code = 0
    body = payload.tobytes()
    if codec:
        c = get_codec(codec)
        body = c.encode_bytes(body, level=level)
        codec_code = _CODECS[codec][1]
    scales_b = scales.tobytes() if scales is not None else b""
    dtype_name = np.dtype(rows.dtype).name.encode()
    hdr = struct.pack(
        _PACK_FMT, _PACK_MAGIC, 1, WIRE_CODES[wire_dtype], codec_code,
        rows.ndim, len(dtype_name), len(scales_b), len(body), raw_nbytes,
    )
    shape_b = struct.pack(f"<{rows.ndim}q", *rows.shape)
    buf = np.frombuffer(
        hdr + shape_b + dtype_name + scales_b + body, dtype=np.uint8
    ).copy()
    # Chaos: WIRE_CORRUPTION flips encoded bytes post-encode — the
    # partner's decode (or the integrity CRC on slot paths) must catch
    # them, exactly like real wire corruption.
    fault_point("wire.encode", view=buf[_PACK_BYTES:])
    if metrics is not None:
        metrics.incr("wire.encoded_bytes", float(buf.nbytes))
        metrics.incr("wire.payload_bytes", float(raw_nbytes))
    return buf


def unpack_rows(
    buf: np.ndarray,
    max_output: int = DEFAULT_MAX_OUTPUT,
    block: int = QUANT_BLOCK,
    metrics: Any = None,
) -> np.ndarray:
    """Decode a :func:`pack_rows` envelope back to its rows.

    Raises :class:`~ddl_tpu.exceptions.DecodeError` on any malformed
    field — callers run the bounded-retry-then-raw-fallback ladder
    (``wire.fallbacks``).  The ``wire.decode`` chaos site fires first,
    against the encoded bytes (``DECODE_FAIL`` raises the real type;
    ``WIRE_CORRUPTION`` flips payload bytes so the decode itself, or
    the value checks downstream, trip).
    """
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    fault_point("wire.decode", view=buf[_PACK_BYTES:])
    if buf.nbytes < _PACK_BYTES:
        raise DecodeError(f"wire envelope truncated ({buf.nbytes} bytes)")
    raw = buf.tobytes()
    magic, ver, wcode, ccode, ndim, dlen, slen, blen, raw_nbytes = (
        struct.unpack_from(_PACK_FMT, raw)
    )
    if magic != _PACK_MAGIC or ver != 1:
        raise DecodeError(
            f"bad wire envelope magic/version 0x{magic:08x}/{ver}"
        )
    if wcode not in _CODE_TO_DTYPE:
        raise DecodeError(f"unknown wire code {wcode}")
    off = _PACK_BYTES
    # Corruption landing in the shape/dtype region raises non-DDL types
    # (struct.error on a short buffer, UnicodeDecodeError/TypeError on a
    # mangled dtype name) — normalise to DecodeError so every decode
    # ladder (retry, raw fallback, backend refetch) actually catches it.
    try:
        shape = struct.unpack_from(f"<{ndim}q", raw, off)
        off += 8 * ndim
        dtype = np.dtype(raw[off : off + dlen].decode())
        off += dlen
    except (struct.error, UnicodeDecodeError, TypeError, ValueError) as e:
        raise DecodeError(f"malformed wire envelope header: {e}") from e
    scales_b = raw[off : off + slen]
    off += slen
    body = raw[off : off + blen]
    if len(body) != blen:
        raise DecodeError(
            f"wire envelope payload truncated ({len(body)} < {blen})"
        )
    if ccode:
        name = _CODEC_BY_CODE.get(ccode)
        if name is None:
            raise DecodeError(f"unknown codec code {ccode}")
        body = get_codec(name).decode_bytes(body, max_output=max_output)
    payload = np.frombuffer(body, dtype=np.uint8)
    wire_dtype = _CODE_TO_DTYPE[wcode]
    n = int(np.prod(shape))
    # Every region is length-checked against what the SHAPE implies
    # before any numpy view: exchange envelopes carry no CRC, so a
    # corrupt length field must die here as DecodeError — a truncated
    # scales buffer fed to frombuffer/reshape raises plain ValueError,
    # which every decode ladder would miss.
    if len(scales_b) != slen or slen != scale_bytes_for(
        tuple(shape), wire_dtype, block
    ):
        raise DecodeError(
            f"wire scales region {len(scales_b)}/{slen} bytes disagrees "
            f"with shape {shape}/{wire_dtype}"
        )
    scales = np.frombuffer(scales_b, dtype=np.float32) if slen else None
    if encoded_nbytes(tuple(shape), dtype, wire_dtype) != payload.nbytes:
        raise DecodeError(
            f"wire payload size {payload.nbytes} disagrees with "
            f"shape {shape}/{dtype.name}/{wire_dtype}"
        )
    if n * dtype.itemsize != raw_nbytes:
        raise DecodeError("wire envelope raw-size field disagrees with shape")
    try:
        rows = decode_window(payload, scales, tuple(shape), dtype,
                             wire_dtype, block)
    except ValueError as e:
        raise DecodeError(f"wire payload decode failed: {e}") from e
    if metrics is not None:
        metrics.incr("wire.decoded_windows")
    return rows


def wire_report(metrics: Any) -> Dict[str, float]:
    """The ``wire.*`` counters one registry accumulated (bench/report)."""
    return {
        "encoded_bytes": metrics.counter("wire.encoded_bytes"),
        "payload_bytes": metrics.counter("wire.payload_bytes"),
        "decoded_windows": metrics.counter("wire.decoded_windows"),
        "fallbacks": metrics.counter("wire.fallbacks"),
        "decode_fails": metrics.counter("wire.decode_fails"),
    }


# -- format economics ------------------------------------------------------
#
# The break-even model every wire decision in this repo prices against:
# moving one raw byte over a link of speed L costs 1/L seconds on the
# raw leg, and 1/enc + ratio/L + 1/dec on an encoded leg.  The encoded
# leg wins exactly when L < (1 - ratio) / (1/enc + 1/dec) — a 4x ratio
# is worthless behind a codec slower than the link.  One implementation,
# shared by the probe_wire CLI and the boot-time Calibrator
# (``ddl_tpu.tune``), so the operator-facing table and the controller's
# decisions can never disagree.


def measure_wire_stats(
    sample: np.ndarray,
    wire_dtypes: Tuple[str, ...] = ("bf16", "int8"),
    codecs: Tuple[str, ...] = (),
    level: int = 1,
    deadline: Optional[float] = None,
) -> Dict[str, Dict[str, float]]:
    """Microbenchmark each wire format on ``sample``, probe_wire-shaped.

    Returns ``{fmt: {"ratio", "encode_bytes_per_s", "decode_bytes_per_s"}}``
    (lossy entries add ``max_rel_drift``) — the stats dict
    :func:`break_even_table` and :func:`pick_wire_format` consume.
    ``deadline`` is an absolute ``time.monotonic()`` bound: formats not
    reached before it are simply absent (the Calibrator's budget
    discipline — a partial table beats a stalled training start).
    """
    sample = np.ascontiguousarray(sample)
    out: Dict[str, Dict[str, float]] = {}
    for wd in wire_dtypes:
        if deadline is not None and time.monotonic() >= deadline:
            break
        if not lossy_supported(sample.dtype):
            break
        t0 = time.perf_counter()
        payload, scales = encode_window(sample, wd)
        t_enc = time.perf_counter() - t0
        enc_bytes = payload.nbytes + (
            scales.nbytes if scales is not None else 0
        )
        t0 = time.perf_counter()
        dec = decode_window(
            payload, scales, sample.shape, sample.dtype, wd
        )
        t_dec = time.perf_counter() - t0
        drift = float(
            np.abs(dec - sample).max()
            / max(float(np.abs(sample).max()), 1e-9)
        )
        out[wd] = {
            "ratio": round(enc_bytes / sample.nbytes, 4),
            "encode_bytes_per_s": round(
                sample.nbytes / max(t_enc, 1e-9), 1
            ),
            "decode_bytes_per_s": round(
                sample.nbytes / max(t_dec, 1e-9), 1
            ),
            "max_rel_drift": drift,
        }
    raw = sample.tobytes()
    for name in codecs:
        if deadline is not None and time.monotonic() >= deadline:
            break
        if name not in available_codecs():
            continue
        c = get_codec(name)
        t0 = time.perf_counter()
        enc = c.encode_bytes(raw, level=level)
        t_enc = time.perf_counter() - t0
        t0 = time.perf_counter()
        dec = c.decode_bytes(enc, max_output=2 * len(raw))
        t_dec = time.perf_counter() - t0
        if dec != raw:
            continue  # a corrupting codec never enters the table
        out[f"{name}-l{level}"] = {
            "ratio": round(len(enc) / len(raw), 4),
            "encode_bytes_per_s": round(len(raw) / max(t_enc, 1e-9), 1),
            "decode_bytes_per_s": round(len(raw) / max(t_dec, 1e-9), 1),
        }
    return out


def break_even_table(
    stats: Dict[str, Any],
    link_bytes_per_s: Optional[float] = None,
) -> Dict[str, float]:
    """Per-format break-even link speed (bytes/s) from measured stats.

    ``stats`` maps format name → a dict carrying at least ``ratio``,
    ``encode_bytes_per_s``, ``decode_bytes_per_s`` (non-dict or
    ratio-free entries are skipped, so a probe_wire shard entry passes
    through unfiltered).  A format appears only when it can win at all
    (``ratio < 1.0``); its value is the link speed below which paying
    the encode+decode CPU beats moving raw bytes.  When
    ``link_bytes_per_s`` is given, formats whose threshold the measured
    link already exceeds are dropped — what remains is exactly the set
    worth flipping on for that link.
    """
    table: Dict[str, float] = {}
    for fmt, st in stats.items():
        if not isinstance(st, dict) or "ratio" not in st:
            continue
        enc = float(st.get("encode_bytes_per_s", 0.0))
        dec = float(st.get("decode_bytes_per_s", 0.0))
        if enc <= 0 or dec <= 0:
            continue
        denom = 1.0 / enc + 1.0 / dec
        if st["ratio"] < 1.0 and denom > 0:
            threshold = (1.0 - float(st["ratio"])) / denom
            if link_bytes_per_s is None or link_bytes_per_s < threshold:
                table[fmt] = threshold
    return table


def pick_wire_format(
    stats: Dict[str, Any],
    link_bytes_per_s: float,
) -> str:
    """The cheapest format for a link, ``"raw"`` included as the floor.

    Prices one raw byte end to end (encode + wire + decode) per format
    at the measured link speed and returns the argmin — the Calibrator's
    wire_dtype decision, made from the same stats the break-even table
    reports to operators.
    """
    link = max(float(link_bytes_per_s), 1e-9)
    best, best_t = "raw", 1.0 / link
    for fmt, st in sorted(stats.items()):
        if not isinstance(st, dict) or "ratio" not in st:
            continue
        enc = float(st.get("encode_bytes_per_s", 0.0))
        dec = float(st.get("decode_bytes_per_s", 0.0))
        if enc <= 0 or dec <= 0:
            continue
        t = 1.0 / enc + float(st["ratio"]) / link + 1.0 / dec
        if t < best_t:
            best, best_t = fmt, t
    return best
