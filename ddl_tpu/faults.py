"""Deterministic fault injection: seeded chaos for the whole pipeline.

PR 1/2 gave the pipeline bounded waits, a respawning watchdog, and
shutdown propagation — but nothing could *prove* those paths work under
arbitrary failure timing.  This module is that proof harness: a seeded,
deterministic fault engine with named injection points threaded through
the transport rings, staging engine, shuffle exchange, worker set, and
watchdog.  The chaos suite (``tests/test_faults.py``) arms a
:class:`FaultPlan` and asserts exactly-once, byte-identical delivery of
the surviving stream (docs/ROBUSTNESS.md has the full fault matrix).

Design constraints:

- **Zero cost disarmed.**  Every injection point is
  ``fault_point("site", ...)`` whose disarmed path is a single module
  attribute read and a ``return`` — no dict build, no lock, no logging.
  Production binaries keep the points compiled in (the whole value is
  that the TESTED code path is the SHIPPED code path).
- **Deterministic.**  A spec fires on the *n*-th matching hit of its
  site (``at``), for ``count`` consecutive hits, per-producer
  selectable; corruption bytes come from the plan's seeded RNG.  Same
  plan + same pipeline ⇒ same faults.
- **Cross-process.**  ``DDL_TPU_FAULT_PLAN`` carries the JSON-encoded
  plan across the spawn boundary, so PROCESS-mode producers arm
  themselves on import exactly like the consumer did.

Injection points shipped today (site — fault kinds that act there):

========================  ====================================================
``producer.fill``         crash / hang / slowdown / spurious shutdown, at the
                          top of ``DataPusher.push_data``'s window loop
``producer.commit``       ring-slot corruption (payload bytes flipped AFTER
                          the integrity header was written)
``pusher.inplace_fill``   crash mid-write-once fill: fires with the live shm
                          slot fully WRITTEN but not yet stamped/committed —
                          a torn slot (new payload under the previous
                          occupant's stale trailer) the consumer must never
                          see

``producer.handshake``    crash during ``_producer_main`` construction
``ring.fill``/``ring.drain``  spurious shutdown / slowdown inside the ring
                          wait primitives (all three ring implementations)
``staging.copy``          staging-copy failure / source corruption
``staging.transfer``      staged-transfer failure / timeout (delay)
``shuffle.exchange``      peer loss (partner never posts its half)
``shuffle.device_exchange``  device-tier exchange, once per participant
                          per round (``DeviceExchangeFabric.exchange``,
                          before the post): ``ICI_DMA_FAIL`` poisons
                          the ROUND — every participant latches the
                          host exchange together with lanes unmutated,
                          so the host re-run is byte-identical
                          (``shuffle.device_fallbacks``);
                          ``SHUFFLE_PEER_LOSS`` keeps this participant
                          from ever posting, so its peers time out and
                          degrade via the seeded node-local rung
``watchdog.sweep``        spurious shutdown / crash inside ``check_once``
``cache.disk_read``       cache-entry corruption (bytes flipped in a
                          just-read disk-tier entry, BEFORE verification —
                          exercises quarantine-and-refetch)
``backend.fetch``         storage-backend fetch failure (transient under
                          the retry budget; persistent beyond it →
                          ``IntegrityError``), fired inside
                          ``cache.open_with_retry`` before every attempt
``ici.fanout``            ICI DMA-leg failure inside
                          ``IciDistributor.distribute`` (before the
                          fan-out kernel dispatch) — the distributor
                          latches a fallback to the ``xla`` scatter path
                          and counts ``ici.fallbacks``
``cluster.heartbeat``     membership control plane, once per host per
                          sweep (``producer_idx`` carries the HOST id):
                          ``HEARTBEAT_DROP`` loses that beat (the lease
                          keeps aging — only expiry changes the view);
                          ``HOST_LOSS`` declares the host dead NOW (the
                          injected analog of a rack losing power)
``cluster.view_change``   inside ``ClusterSupervisor`` just before the
                          epoch-fenced successor view is computed — a
                          crash/spurious-shutdown here exercises the
                          supervisor's own sweep-crash discrimination
                          (the watchdog.sweep contract, host-level)
``serve.admit``           multi-tenant admission gate, once per
                          admission attempt (``producer_idx`` carries
                          the TENANT registration index):
                          ``TENANT_BURST`` raises the real
                          ``TenantBurst`` type with ``param`` phantom
                          bytes — the fair-share scheduler charges them
                          to the bursting tenant's own share, so the
                          spike never starves its neighbours
``serve.scale``           top of every ``Autoscaler.step``:
                          ``SCALE_DECISION_DELAY`` sleeps ``param``
                          seconds there — a slow control plane degrades
                          scale-up reaction time, never correctness
``wire.encode``           wire-format encode sites (``ddl_tpu.wire``):
                          after a producer's encoded slot commit is
                          CRC-stamped, and inside ``pack_rows`` for the
                          shuffle exchange — ``WIRE_CORRUPTION`` flips
                          bytes in the ENCODED payload, so drain-time
                          integrity (which verifies the quantized
                          bytes) quarantines and replays exactly like
                          raw corruption
``wire.decode``           wire-format decode sites: the consumer edge's
                          slot decode, ``unpack_rows`` (exchange), and
                          ``CodecBackend.open`` — ``DECODE_FAIL``
                          raises the real ``DecodeError``, exercising
                          each path's ladder: bounded retry, then the
                          raw fallback (``wire.fallbacks``) or the
                          backend retry/refetch rung
``resilience.notice``     polled by ``PreemptionGuard.poll`` once per
                          window boundary: ``PREEMPT_NOTICE`` raises
                          the real ``PreemptionNotice`` (``param`` =
                          grace seconds, 0 = guard default) — the
                          deterministic analog of a TPU spot
                          preemption SIGTERM, driving the full
                          graceful-drain ladder
``resilience.ckpt_write`` inside ``AsyncCheckpointer``'s writer thread
                          on the fully CRC-stamped generation blob,
                          just before the atomic write —
                          ``CKPT_CORRUPTION`` flips committed bytes so
                          the written generation fails read-time
                          verification: quarantine + fallback to the
                          previous verified generation is what the
                          injection exercises
``cluster.supervise``     top of every ``SupervisorHA.step``
                          (``producer_idx`` carries the stepping
                          node's id): ``SUPERVISOR_CRASH`` raises the
                          real ``SupervisorCrashed`` — the leader dies
                          mid-lease, a standby observes expiry,
                          replays the journal, and promotes itself
                          under the next fencing term;
                          ``NETWORK_PARTITION`` isolates the stepping
                          node (its renews/observations are lost for
                          ``count`` steps — the split-brain setup)
``transport.control_send``  inside ``ControlSender.send``, once per
                          wire attempt (``producer_idx`` carries the
                          TARGET producer): ``CONTROL_MSG_DROP`` /
                          ``NETWORK_PARTITION`` lose the attempt (the
                          real transport types — the seam's backoff
                          retry absorbs them under the cap);
                          ``CONTROL_MSG_DUP`` sends the same envelope
                          twice (the receiver's (incarnation, seq)
                          dedup absorbs it)
``serve.fabric.admit``    the fabric client's admission wire attempt
                          (``producer_idx`` carries the JOB
                          registration index): ``JOB_ADMISSION_DROP``
                          raises the real ``AdmissionDropped`` — the
                          admit command is lost, the client's acked
                          envelope retry re-wires it, and the fabric's
                          journal-seeded dedup keeps the scheduler
                          ledger exactly-once
``serve.fabric.grant``    between a granted admit and its
                          ``note_served`` charge (``producer_idx``
                          carries the JOB registration index):
                          ``JOB_CRASH`` raises the real ``JobCrashed``
                          mid-grant — the fabric revokes the crashed
                          job's in-flight grants, releases its budget,
                          and its neighbours stay byte-correct
========================  ====================================================
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import threading

from ddl_tpu import envspec
from ddl_tpu.concurrency import named_lock
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ddl_tpu.exceptions import (
    BackendFetchError,
    DDLError,
    HeartbeatDropped,
    HostLostError,
    InjectedFault,
    ShutdownRequested,
    TenantBurst,
)


class FaultKind(enum.Enum):
    """What happens when a spec fires (see docs/ROBUSTNESS.md matrix)."""

    PRODUCER_CRASH = "producer_crash"
    PRODUCER_HANG = "producer_hang"
    PRODUCER_SLOWDOWN = "producer_slowdown"
    RING_CORRUPTION = "ring_corruption"
    STAGING_COPY_FAIL = "staging_copy_fail"
    STAGED_TRANSFER_FAIL = "staged_transfer_fail"
    STAGED_TRANSFER_TIMEOUT = "staged_transfer_timeout"
    SHUFFLE_PEER_LOSS = "shuffle_peer_loss"
    SPURIOUS_SHUTDOWN = "spurious_shutdown"
    CACHE_CORRUPTION = "cache_corruption"
    BACKEND_FETCH_FAIL = "backend_fetch_fail"
    ICI_DMA_FAIL = "ici_dma_fail"
    HOST_LOSS = "host_loss"
    HEARTBEAT_DROP = "heartbeat_drop"
    TENANT_BURST = "tenant_burst"
    SCALE_DECISION_DELAY = "scale_decision_delay"
    WIRE_CORRUPTION = "wire_corruption"
    DECODE_FAIL = "decode_fail"
    PREEMPT_NOTICE = "preempt_notice"
    CKPT_CORRUPTION = "ckpt_corruption"
    SUPERVISOR_CRASH = "supervisor_crash"
    CONTROL_MSG_DROP = "control_msg_drop"
    CONTROL_MSG_DUP = "control_msg_dup"
    NETWORK_PARTITION = "network_partition"
    JOB_ADMISSION_DROP = "job_admission_drop"
    JOB_CRASH = "job_crash"


@dataclasses.dataclass
class FaultSpec:
    """One scheduled fault.

    ``at`` is 1-based: the spec fires on the ``at``-th matching hit of
    ``site`` and keeps firing for ``count`` consecutive hits (``count``
    large ⇒ a persistent fault).  ``producer_idx`` narrows matching to
    one producer's hits (``None`` matches any, including consumer-side
    sites that carry no producer).  ``param`` parameterises the action:
    sleep seconds for hang/slowdown/timeout, corrupted-byte count for
    ring corruption.
    """

    site: str
    kind: FaultKind
    at: int = 1
    count: int = 1
    producer_idx: Optional[int] = None
    param: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "site": self.site,
            "kind": self.kind.value,
            "at": self.at,
            "count": self.count,
            "producer_idx": self.producer_idx,
            "param": self.param,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultSpec":
        return cls(
            site=d["site"],
            kind=FaultKind(d["kind"]),
            at=int(d.get("at", 1)),
            count=int(d.get("count", 1)),
            producer_idx=d.get("producer_idx"),
            param=float(d.get("param", 0.0)),
        )


class FaultPlan:
    """A seed plus a schedule of :class:`FaultSpec`\\ s.

    Thread-safe: injection points are hit concurrently from producers,
    the staging worker, and the consumer; hit counting happens under one
    lock (only while armed — the disarmed path never reaches it).
    ``fired`` records ``(site, kind, producer_idx, hit_number)`` per
    firing, for test introspection.
    """

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = int(seed)
        self.fired: List[Tuple[str, str, Optional[int], int]] = []
        self._lock = named_lock("faults.plan")
        # spec index -> matching hits: bounded by len(specs) by
        # construction (indices come only from enumerate(self.specs)).
        self._hits: Dict[int, int] = {}  # ddl-lint: disable=DDL013
        import numpy as np

        self._rng = np.random.default_rng(self.seed)

    # -- (de)serialisation (the spawn-boundary / env-var format) -----------

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "specs": [s.to_dict() for s in self.specs]}
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        d = json.loads(text)
        return cls(
            [FaultSpec.from_dict(s) for s in d.get("specs", [])],
            seed=int(d.get("seed", 0)),
        )

    # -- firing ------------------------------------------------------------

    def fire(
        self,
        site: str,
        producer_idx: Optional[int],
        view: Any,
        should_abort: Optional[Callable[[], bool]],
    ) -> List[str]:
        due: List[FaultSpec] = []
        with self._lock:
            for i, spec in enumerate(self.specs):
                if spec.site != site:
                    continue
                if (
                    spec.producer_idx is not None
                    and spec.producer_idx != producer_idx
                ):
                    continue
                n = self._hits.get(i, 0) + 1
                self._hits[i] = n
                if spec.at <= n < spec.at + spec.count:
                    self.fired.append(
                        (site, spec.kind.value, producer_idx, n)
                    )
                    due.append(spec)
        if due:
            # Post-mortem trail (ddl_tpu.obs): a fault-site trip dumps
            # the flight ring when a recorder is armed (no-op, and no
            # import, otherwise) — every chaos-matrix row and chip-run
            # anomaly leaves an artifact.  Lazy import: faults must not
            # pull the obs layer into processes that never arm it.
            from ddl_tpu.obs import recorder as _flight

            if _flight.armed_recorder() is not None:
                for spec in due:
                    _flight.flight_dump(
                        f"fault.{site}.{spec.kind.value}",
                        producer_idx=producer_idx,
                    )
        for spec in due:
            self._act(spec, view=view, should_abort=should_abort)
        # Non-raising kinds (CONTROL_MSG_DUP) reach here: the caller
        # learns what fired and acts itself (the sender duplicates).
        return [spec.kind.value for spec in due]

    def _act(
        self,
        spec: FaultSpec,
        view: Any,
        should_abort: Optional[Callable[[], bool]],
    ) -> None:
        kind = spec.kind
        where = f"injected at {spec.site!r}"
        if kind is FaultKind.PRODUCER_CRASH:
            raise InjectedFault(f"producer crash {where}")
        elif kind is FaultKind.SPURIOUS_SHUTDOWN:
            raise ShutdownRequested(f"spurious shutdown {where}")
        elif kind is FaultKind.PRODUCER_HANG:
            # A wedge, not a sleep: hold until the stall budget/shutdown
            # machinery reacts, observing shutdown so a healed run (or a
            # clean teardown) is never stranded behind the injection.
            deadline = time.monotonic() + (spec.param or 3600.0)
            while time.monotonic() < deadline:
                if should_abort is not None and should_abort():
                    raise ShutdownRequested(f"hang aborted {where}")
                time.sleep(0.05)
        elif kind in (
            FaultKind.PRODUCER_SLOWDOWN,
            FaultKind.STAGED_TRANSFER_TIMEOUT,
            FaultKind.SCALE_DECISION_DELAY,
        ):
            time.sleep(spec.param or 0.2)
        elif kind in (
            FaultKind.RING_CORRUPTION,
            FaultKind.CACHE_CORRUPTION,
            FaultKind.WIRE_CORRUPTION,
            FaultKind.CKPT_CORRUPTION,
        ):
            if view is None or len(view) == 0:
                return  # site carries no mutable payload; nothing to flip
            nbytes = max(1, int(spec.param))
            with self._lock:
                offs = self._rng.integers(0, len(view), size=nbytes)
            for off in offs:
                view[int(off)] ^= 0xFF
        elif kind in (
            FaultKind.STAGING_COPY_FAIL,
            FaultKind.STAGED_TRANSFER_FAIL,
            FaultKind.ICI_DMA_FAIL,
        ):
            raise InjectedFault(f"{kind.value} {where}")
        elif kind is FaultKind.BACKEND_FETCH_FAIL:
            # Raised as the REAL transient type, not InjectedFault: the
            # production retry/backoff ladder in cache.open_with_retry
            # must handle it exactly as it would a live remote-store
            # hiccup (that ladder is what the injection tests).
            raise BackendFetchError(f"backend fetch failure {where}")
        elif kind is FaultKind.HOST_LOSS:
            # Raised as the REAL membership type (the BACKEND_FETCH_FAIL
            # pattern): the supervisor's sweep must handle it exactly as
            # it would a declared host death — immediate epoch-fenced
            # view change, not lease aging.
            raise HostLostError(f"host loss {where}")
        elif kind is FaultKind.HEARTBEAT_DROP:
            # Also the real type: the sweep counts the drop and lets the
            # lease age — a single lost beat must NEVER change the view.
            raise HeartbeatDropped(f"heartbeat dropped {where}")
        elif kind is FaultKind.TENANT_BURST:
            # The real type (the BACKEND_FETCH_FAIL pattern): the
            # fair-share scheduler must absorb the spike exactly as it
            # would a live thundering herd — phantom bytes charged to
            # the bursting tenant's own share, neighbours untouched.
            raise TenantBurst(
                f"tenant burst {where}",
                burst_bytes=spec.param or (64 << 20),
            )
        elif kind is FaultKind.DECODE_FAIL:
            # The real type (the BACKEND_FETCH_FAIL pattern): every
            # wire.decode site's production ladder — bounded retry,
            # then raw fallback / backend refetch — is what the
            # injection tests.
            from ddl_tpu.exceptions import DecodeError

            raise DecodeError(f"decode failure {where}")
        elif kind is FaultKind.PREEMPT_NOTICE:
            # The real type (the BACKEND_FETCH_FAIL pattern): the
            # PreemptionGuard's poll absorbs it and runs the production
            # graceful-drain ladder — exactly what a platform SIGTERM
            # drives.  ``param`` carries the notice's grace seconds.
            from ddl_tpu.exceptions import PreemptionNotice

            raise PreemptionNotice(
                f"preemption notice {where}", deadline_s=spec.param
            )
        elif kind is FaultKind.SUPERVISOR_CRASH:
            # The real type (the BACKEND_FETCH_FAIL pattern): the HA
            # tier's step must absorb a dead leader exactly as it would
            # a real crash — lease stops renewing, standby promotes
            # under the next fencing term after expiry.
            from ddl_tpu.exceptions import SupervisorCrashed

            raise SupervisorCrashed(f"supervisor crash {where}")
        elif kind is FaultKind.CONTROL_MSG_DROP:
            # Real transport type: the acked envelope seam must absorb
            # a lost send exactly as it would a live pipe hiccup —
            # bounded backoff retry until acked.
            from ddl_tpu.exceptions import ControlSendDropped

            raise ControlSendDropped(f"control send dropped {where}")
        elif kind is FaultKind.NETWORK_PARTITION:
            # A partition is a drop with a duration: count>1 keeps the
            # site firing, so every retry inside the window is lost too
            # and the lease on the far side ages toward the split-brain
            # scenario (at cluster.supervise it suppresses the leader's
            # lease renewal instead — same type, site decides).
            from ddl_tpu.exceptions import NetworkPartitioned

            raise NetworkPartitioned(f"network partitioned {where}")
        elif kind is FaultKind.JOB_ADMISSION_DROP:
            # The real transport type (the BACKEND_FETCH_FAIL pattern):
            # the fabric client's acked envelope seam must absorb a
            # lost admission command exactly as it would a live wire
            # hiccup — backoff retry, journal-seeded dedup on the
            # fabric side keeping the ledger exactly-once.
            from ddl_tpu.exceptions import AdmissionDropped

            raise AdmissionDropped(f"job admission dropped {where}")
        elif kind is FaultKind.JOB_CRASH:
            # The real type (the BACKEND_FETCH_FAIL pattern): the
            # fabric's crash ladder — revoke the job's in-flight
            # grants, release its budget, unregister — is what the
            # injection tests; neighbours must stay byte-correct.
            from ddl_tpu.exceptions import JobCrashed

            raise JobCrashed(f"job crashed mid-grant {where}")
        elif kind is FaultKind.CONTROL_MSG_DUP:
            # No raise: ``fault_point`` returns the fired kinds, the
            # sender sees this one and sends the SAME envelope twice —
            # the receiver's (incarnation, seq) dedup is what the
            # injection tests.
            return
        elif kind is FaultKind.SHUFFLE_PEER_LOSS:
            raise DDLError(f"shuffle peer loss {where}")
        else:  # pragma: no cover - FaultKind is closed above
            raise ValueError(f"unhandled fault kind {kind!r}")


#: The armed plan, or None.  Read unlocked on every injection point —
#: a single module-attribute load is the entire disarmed cost.
_ARMED: Optional[FaultPlan] = None

#: Env var carrying a JSON plan across process-spawn boundaries.
PLAN_ENV = "DDL_TPU_FAULT_PLAN"


def fault_point(
    site: str,
    producer_idx: Optional[int] = None,
    view: Any = None,
    should_abort: Optional[Callable[[], bool]] = None,
) -> Optional[List[str]]:
    """One named injection point.  No-op (one attribute read) unless a
    plan is armed; with a plan, matching specs act — raising, sleeping,
    or corrupting ``view`` in place.  Returns the fired kind values (a
    possibly-empty list) so non-raising kinds (``CONTROL_MSG_DUP``) can
    be acted on by the caller; ``None`` when disarmed."""
    plan = _ARMED
    if plan is None:
        return None
    return plan.fire(site, producer_idx, view, should_abort)


def arm(plan: Optional[FaultPlan], export: bool = False) -> Optional[FaultPlan]:
    """Arm ``plan`` process-wide (``None`` disarms).  ``export=True``
    additionally publishes it to :data:`PLAN_ENV` so PROCESS-mode
    producers spawned afterwards arm themselves on import.  Returns the
    previously armed plan."""
    global _ARMED
    prev = _ARMED
    _ARMED = plan
    if export:
        if plan is None:
            os.environ.pop(PLAN_ENV, None)
        else:
            os.environ[PLAN_ENV] = plan.to_json()
    return prev


def armed_plan() -> Optional[FaultPlan]:
    return _ARMED


class armed:
    """Context manager: arm a plan for a scoped chaos run.

    ::

        plan = FaultPlan([FaultSpec("producer.fill", FaultKind.PRODUCER_CRASH, at=3)])
        with faults.armed(plan, export=True):
            run_pipeline()
        assert plan.fired

    Restores the previous plan (and the env var) on exit, even when the
    pipeline under test raises.
    """

    def __init__(self, plan: FaultPlan, export: bool = False):
        self.plan = plan
        self.export = export
        self._prev: Optional[FaultPlan] = None
        self._prev_env: Optional[str] = None

    def __enter__(self) -> FaultPlan:
        self._prev_env = envspec.raw(PLAN_ENV)
        self._prev = arm(self.plan, export=self.export)
        return self.plan

    def __exit__(self, *exc: Any) -> None:
        arm(self._prev)
        if self.export:
            if self._prev_env is None:
                os.environ.pop(PLAN_ENV, None)
            else:
                os.environ[PLAN_ENV] = self._prev_env


# Spawned producer processes (and any process launched with the env set)
# arm themselves at import: ddl_tpu.datapusher imports this module, so
# PROCESS-mode workers pick the plan up before their first window.
_env_plan = envspec.raw(PLAN_ENV)
if _env_plan:
    try:
        _ARMED = FaultPlan.from_json(_env_plan)
    except (ValueError, KeyError):
        import logging

        logging.getLogger("ddl_tpu").warning(
            "ignoring malformed %s (%d chars)", PLAN_ENV, len(_env_plan)
        )
del _env_plan
