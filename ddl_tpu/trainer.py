"""High-level Trainer: loader + sharded train step + aux systems in one call.

The reference left the whole consumer side to the user: init
``torch.distributed`` yourself, write the epoch loop yourself, call
``mark()`` yourself, no checkpointing, no failure detection (reference
``tests/run_ddl.py:171-238``, SURVEY §5.3-5.4).  ``Trainer`` composes the
ddl_tpu equivalents so one object owns the whole training run:

- the producer/consumer topology (``@distributed_dataloader`` role split),
- the GSPMD train step (``parallel.train.make_train_step``) on a caller
  mesh,
- the ``mark()`` protocol, driven automatically around the user-visible
  epoch loop,
- checkpoint/resume of BOTH halves (train state via Orbax, the loader's
  logical clock via ``LoaderCheckpoint``) at epoch boundaries,
- the producer watchdog and the metrics registry.

The loss function owns the batch layout: it receives exactly the column
tuple the loader serves (what the reference's user unpacked by hand,
``run_ddl.py:232``).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

from ddl_tpu.datasetwrapper import ProducerFunctionSkeleton
from ddl_tpu.observability import Metrics, metrics as default_metrics

logger = logging.getLogger("ddl_tpu")


def _stream_splits(loader: Any) -> Tuple[int, ...]:
    """The single column-split tuple a window stream serves, validated:
    heterogeneous per-producer splits cannot ride one scanned program."""
    splits = set(loader.splits_per_producer)
    if len(splits) != 1:
        raise ValueError(
            "window_stream requires homogeneous column splits across "
            f"producers, got {sorted(splits)}"
        )
    (col_splits,) = splits
    return col_splits


def _window_cols(win: Any, col_splits: Sequence[int]) -> Tuple[Any, ...]:
    """Split a (bpw, batch, *features) device window into column arrays
    along the FIRST feature axis — the axis every batch-path split uses
    (``dataloader._split_columns`` slices ``batch[:, off:off+w]``).

    A single full-width column (token windows, ``splits=(seq,)``) passes
    through UNSLICED: the identity slice was a per-window device op
    whose output also lost the window's NamedSharding, forcing the
    multistep's ``_reshard`` into a second device_put — two dispatches
    per window for nothing, squarely on the stream-fit hot path."""
    if len(col_splits) == 1 and col_splits[0] == win.shape[2]:
        return (win,)
    cols, off = [], 0
    for w in col_splits:
        cols.append(win[:, :, off : off + w])
        off += w
    return tuple(cols)


@dataclasses.dataclass
class FitResult:
    state: Any  # final TrainState
    losses: List[float]  # per-epoch mean loss
    epochs_run: int
    resumed_from_epoch: int
    metrics: Metrics
    #: True when the run ended in a graceful preemption drain
    #: (``ddl_tpu.resilience.PreemptionGuard``) rather than completing
    #: its epochs — the caller should exit and let the restart resume.
    preempted: bool = False


class Trainer:
    """Owns one sharded training run fed by the ddl_tpu loader."""

    def __init__(
        self,
        loss_fn: Callable[[Any, Any], Any],
        optimizer: Any,
        mesh: Any,
        param_specs: Any,
        init_params: Any,
        batch_spec: P = P(("dp",)),
        checkpoint_dir: Optional[str] = None,
        checkpoint_every_epochs: int = 1,
        watchdog: bool = True,
        watchdog_respawn: bool = False,
        stall_budget_s: float = 300.0,
        metrics: Optional[Metrics] = None,
        accum_steps: Optional[int] = None,
        train_config: Any = None,
        checkpoint_async: Optional[bool] = None,
        checkpoint_keep: int = 3,
        preemption_guard: Any = None,
    ):
        """``loss_fn(params, batch) -> scalar`` over the loader's batch
        tuple; ``init_params`` is the initial params pytree (ignored when a
        checkpoint exists in ``checkpoint_dir``).

        ``train_config`` (a :class:`ddl_tpu.config.TrainConfig`)
        supplies the training hot-path defaults — ``accum_steps`` (an
        explicit argument wins; the default is the ``None`` sentinel
        precisely so an explicit ``accum_steps=1`` can DISABLE
        accumulation against a config that asks for it) and the
        distributed-optimizer knobs (``optimizer_sharding="zero1"``
        shards optimizer state + weight update over dp, ``grad_comm=
        "int8"`` opts into the quantized wire format — both flow into
        every step factory this Trainer builds); its remat policy and
        pipeline schedule apply where the model is BUILT
        (``train_config.model_config(cfg)`` /
        ``train_config.pipeline_kwargs()``), since the Trainer only
        ever sees the closed-over ``loss_fn``.

        ``checkpoint_async`` (default: the ``DDL_TPU_CKPT_ASYNC`` env
        gate, on) routes checkpoints through
        :class:`~ddl_tpu.resilience.AsyncCheckpointer` — the step
        loop's stall is the D2H snapshot alone, generations carry
        integrity trailers, and the loader cursor is fenced into the
        same atomic blob; ``False`` keeps the legacy synchronous Orbax
        path (now atomic temp+rename + manifest-verified on read).
        ``checkpoint_keep`` is the async tier's keep-K retention.
        ``preemption_guard`` (a :class:`~ddl_tpu.resilience.
        PreemptionGuard`) is polled at every window/epoch boundary:
        on a notice the run drains gracefully — forced final
        checkpoint, tenant-window revocation, graceful host drain,
        clean producer shutdown — and ``fit`` returns with
        ``FitResult.preempted`` set."""
        from ddl_tpu.parallel.train import make_train_step

        if accum_steps is None:
            accum_steps = (
                train_config.accum_steps if train_config is not None else 1
            )
        self.train_config = train_config
        # Distributed-optimizer knobs (TrainConfig.optimizer_kwargs):
        # zero1 state sharding / int8 grad comm flow into BOTH step
        # factories (the per-batch step here and every window-stream
        # multistep in _fit_windows) from the same dict, so the two
        # paths cannot train under different optimizer semantics.
        self._opt_kwargs = (
            train_config.optimizer_kwargs()
            if train_config is not None
            else {}
        )

        self.mesh = mesh
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_epochs = max(1, checkpoint_every_epochs)
        if checkpoint_async is None:
            from ddl_tpu.utils import env_flag

            checkpoint_async = env_flag("DDL_TPU_CKPT_ASYNC")
        self.checkpoint_async = bool(checkpoint_async)
        self.checkpoint_keep = max(1, int(checkpoint_keep))
        self._ckptr: Any = None  # lazy AsyncCheckpointer
        self._guard = preemption_guard
        self._restored_loader_ck: Any = None
        self._preempted = False
        self.watchdog_enabled = watchdog
        self.watchdog_respawn = watchdog_respawn
        self.stall_budget_s = stall_budget_s
        self.metrics = metrics or default_metrics()
        self._init_params = init_params
        self._batch_spec = batch_spec
        self._loss_fn = loss_fn
        self._optimizer = optimizer
        self._param_specs = param_specs
        self._accum_steps = accum_steps
        self._init_fn, self._step_fn = make_train_step(
            loss_fn, optimizer, mesh, param_specs, batch_spec=batch_spec,
            accum_steps=accum_steps, **self._opt_kwargs,
        )
        # window_stream multistep programs, keyed by steps-per-window, so
        # repeated fit() calls on one Trainer reuse the compiled scan.
        # LRU-bounded (DDL013): a pathological producer mix emitting a
        # new window depth per rotation would otherwise pin every
        # compiled program it ever built; evicted depths just recompile.
        self._multistep_cache: dict = {}
        self._multistep_cache_cap = 8

    # -- checkpoint plumbing ----------------------------------------------

    def _loader_ckpt_path(self) -> str:
        assert self.checkpoint_dir is not None
        return os.path.join(self.checkpoint_dir, "loader.json")

    def _checkpointer(self) -> Any:
        """The lazily built per-trainer async checkpointer."""
        if self._ckptr is None:
            from ddl_tpu.resilience import AsyncCheckpointer

            assert self.checkpoint_dir is not None
            self._ckptr = AsyncCheckpointer(
                self.checkpoint_dir, keep=self.checkpoint_keep,
                metrics=self.metrics,
            )
        return self._ckptr

    def _restore_or_init(self) -> Tuple[Any, int]:
        """Returns (train state, epoch to start from).

        Restore prefers the VERIFIED source with the newest step:
        resilience generation files (integrity-trailer checked, loader
        cursor fenced inside the blob) vs legacy Orbax ``step_*``
        directories (manifest-verified since ISSUE 14) — so a run that
        switched checkpointing modes still resumes from its true
        frontier.  Unverifiable generations of either format are
        quarantined and the previous verified one restores instead;
        exhaustion is a cold start (loud counter), never a crash.
        """
        from ddl_tpu.checkpoint import (
            LoaderCheckpoint,
            latest_verified_step,
            restore_train_state,
        )
        from ddl_tpu.resilience import (
            latest_verified_generation,
            restore_latest,
        )

        state = self._init_fn(self._init_params)
        self._restored_loader_ck = None
        if self.checkpoint_dir is None:
            return state, 0
        gen = latest_verified_generation(
            self.checkpoint_dir, metrics=self.metrics
        )
        legacy_step = latest_verified_step(self.checkpoint_dir)
        if gen is not None and (
            legacy_step is None or gen[0] >= legacy_step
        ):
            # found=gen: the scan above already CRC'd every candidate —
            # restore must not re-read the blobs a second time.
            restored = restore_latest(
                self.checkpoint_dir, like=state, metrics=self.metrics,
                found=gen,
            )
            assert restored is not None  # gen verified just above
            self._restored_loader_ck = restored.loader
            start_epoch = (
                restored.loader.epoch if restored.loader is not None else 0
            )
            logger.info(
                "trainer: resumed step %d / epoch %d from generation "
                "checkpoint %s", restored.state.step, start_epoch,
                self.checkpoint_dir,
            )
            return restored.state, start_epoch
        if legacy_step is None:
            return state, 0
        state = restore_train_state(
            self.checkpoint_dir, like=state, step=legacy_step
        )
        start_epoch = 0
        if os.path.exists(self._loader_ckpt_path()):
            ck = LoaderCheckpoint.load(self._loader_ckpt_path())
            self._restored_loader_ck = ck
            start_epoch = ck.epoch
        logger.info(
            "trainer: resumed step %d / epoch %d from %s",
            state.step, start_epoch, self.checkpoint_dir,
        )
        return state, start_epoch

    def _checkpoint(
        self, state: Any, loader: Any, shuffler: Any = None,
        force: bool = False, timeout_s: float = 60.0,
    ) -> None:
        # Producer-side shuffler rounds need no explicit capture: on resume
        # ``fit`` replays the consumed windows (``loader.fast_forward``) and
        # the producers re-execute their deterministic schedule — including
        # every exchange round — so the shuffle continues exactly where it
        # stopped (proven end-to-end by tests/test_resume_shuffle.py).
        # Consumer-owned device shufflers DO carry state; their round rides
        # in ``LoaderCheckpoint.shuffle_round`` via ``capture(loader,
        # shuffler)`` (tests/test_aux.py::TestShuffleRoundResume).
        from ddl_tpu.checkpoint import LoaderCheckpoint, save_train_state

        assert self.checkpoint_dir is not None
        ck = LoaderCheckpoint.capture(loader, shuffler=shuffler)
        if self.checkpoint_async:
            # Async tier: the measured stall is the D2H snapshot; the
            # serialize/fsync/rename hides under training.  ``force``
            # (the preemption drain's final checkpoint) waits for the
            # bytes to be durably on disk before returning.
            cp = self._checkpointer()
            if force:
                cp.checkpoint_now(state, ck, timeout_s=timeout_s)
            else:
                cp.submit(state, ck)
            return
        with self.metrics.timed("resilience.ckpt_sync"):
            save_train_state(state, self.checkpoint_dir)
            ck.save(self._loader_ckpt_path())

    def _finish_checkpoints(self) -> None:
        """Bounded flush of the async writer at the end of a fit: the
        final periodic checkpoint must be durable before the process
        can exit (the writer is a daemon thread — without this flush a
        completed run could silently lose its newest generation and a
        restart would resume one interval early)."""
        if self._ckptr is None:
            return
        from ddl_tpu.exceptions import CheckpointError

        try:
            self._ckptr.flush(timeout_s=60.0)
        except CheckpointError:
            logger.exception(
                "trainer: async checkpoint flush at fit end failed — "
                "the newest generation may be missing on restart"
            )

    def _preempt_drain(
        self, state: Any, loader: Any, shuffler: Any = None
    ) -> None:
        """Run the guard's graceful-drain ladder at a window boundary:
        forced final checkpoint (state + fenced loader cursor), tenant
        revocation / host drain (the guard's attached rungs), then a
        clean producer shutdown — the watchdog sees an orderly close,
        not failures."""
        self._preempted = True

        def final_ckpt():
            if self.checkpoint_dir is not None:
                # Bounded by the REMAINING grace budget: a wedged
                # writer must not eat the whole notice window and
                # starve the revoke/drain/shutdown rungs behind it.
                self._checkpoint(
                    state, loader, shuffler=shuffler, force=True,
                    timeout_s=max(1.0, self._guard.remaining()),
                )

        self._guard.drain(
            final_checkpoint=final_ckpt, shutdown=loader.shutdown
        )

    def _should_drain(self) -> bool:
        return self._guard is not None and self._guard.poll()

    # -- evaluation --------------------------------------------------------

    def evaluate(
        self,
        producer_function: ProducerFunctionSkeleton,
        state: Any,
        metric_fn: Callable[[Any, Any], Any],
        batch_size: int,
        n_producers: Optional[int] = None,
        mode: Optional[str] = None,
        output: str = "numpy",
        window_stream: bool = False,
        n_epochs: int = 1,
    ) -> float:
        """Metric pass over a (held-out) producer's windows.

        Drains ``n_epochs`` epochs (one window per producer rotation —
        the Q7 epoch; pass ``n_epochs=n_producers`` to cover every
        producer once) computing ``metric_fn(params, batch) -> scalar``
        per batch and returns the sample-weighted mean.  Uses the same
        producer/consumer machinery as ``fit`` but runs no optimizer
        step — e.g. pass ``models.vit.accuracy`` for classification
        eval.  ``window_stream=True`` (``output="jax"``): each window
        streams zero-copy and all its batches evaluate in one jitted
        scan.
        """
        from ddl_tpu import DistributedDataLoader, Marker, distributed_dataloader

        if window_stream and output != "jax":
            raise ValueError("window_stream requires output='jax'")
        trainer = self

        @distributed_dataloader(n_producers=n_producers, mode=mode)
        def _run(env):
            lkw: dict = {}
            if output == "jax":
                # Same sharded-landing optimisation as fit: batches land
                # distributed over the mesh, not whole on device 0.
                from ddl_tpu.parallel.train import _named

                spec = (
                    P(*((None,) + tuple(trainer._batch_spec)))
                    if window_stream
                    else trainer._batch_spec
                )
                lkw["sharding"] = _named(trainer.mesh, spec)
            loader = DistributedDataLoader(
                producer_function,
                batch_size=batch_size,
                connection=env.connection,
                n_epochs=n_epochs,
                output=output,
                metrics=trainer.metrics,
                **lkw,
            )
            if window_stream:
                import jax

                col_splits = _stream_splits(loader)

                @jax.jit
                def window_metric(params, win):
                    vals = jax.vmap(
                        lambda *b: metric_fn(params, tuple(b))
                    )(*_window_cols(win, col_splits))
                    return vals.mean()

                vals = []
                for win in loader.windows():
                    # Weight each window's mean by its batch count: with
                    # mixed batches_per_window across producers (served
                    # by weighted rotation), a plain mean-of-means would
                    # overweight small windows.
                    vals.append((window_metric(state.params, win),
                                 win.shape[0]))
                    loader.mark(Marker.END_OF_EPOCH)
                total = sum(w for _, w in vals)
                return (
                    sum(float(v) * w for v, w in vals) / total
                    if total else float("nan")
                )
            vals: List[Any] = []
            for _epoch in range(n_epochs):
                it = loader.prefetch() if output == "jax" else loader
                for batch in it:
                    # Keep metrics as device arrays; a float() here would
                    # serialise loading against compute (see fit).
                    vals.append(metric_fn(state.params, batch))
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
            # Batches all hold batch_size samples, so a plain mean over
            # batches IS the sample-weighted mean even with mixed
            # window sizes.
            fvals = [float(v) for v in vals]
            return sum(fvals) / len(fvals) if fvals else float("nan")

        return _run()

    # -- window-stream epoch loop -----------------------------------------

    def _fit_windows(
        self,
        loader: Any,
        state: Any,
        start_epoch: int,
        n_epochs: int,
        epoch_losses: List[float],
        window_hook: Any = None,
        hook_state: Any = None,
        stream_lookahead: int = 1,
        fused: Optional[bool] = None,
    ) -> FitResult:
        """One multistep scan per streamed window (see ``fit`` docstring).

        Two disciplines, selected by ``fused`` (default: the
        ``DDL_TPU_FUSED`` gate, on):

        - **Fused** (:meth:`_fused_stream_loop`): the whole data plane
          hides under the train step.  Window N+1's transfer — and on a
          multi-device mesh its double-buffered ICI fan-out ring
          (``IciDistributor``'s landing slots) — is dispatched before
          scan N, the slot release is gated on the CONSUMING step's
          done-future (``loader.gate_release_on``), and the per-epoch
          loss read-back is deferred by one window so the host sync of
          scan k never blocks the enqueue of scan k+1 or the stream of
          window k+2.
        - **Synchronous** (:meth:`_sync_stream_loop`,
          ``DDL_TPU_FUSED=0``): the window lands
          (``block_until_ready``) before the step is dispatched and the
          losses are read back before the next acquire — measured step
          time is compute + ingest, not max().  This is the bench A/B's
          unfused baseline and the discipline every fallback rung
          degrades toward; it must stay loss-identical to the fused
          loop (same data, same math, different dispatch timing).
        """
        from ddl_tpu.parallel.train import make_multistep

        col_splits = _stream_splits(loader)
        if fused is None:
            from ddl_tpu.parallel.ici import fused_enabled

            fused = fused_enabled()

        # Window-stream scans are UNDONATED on the CPU client: a
        # donated jit call executes SYNCHRONOUSLY there (measured —
        # dispatch blocks for the whole execution), which collapses the
        # async dispatch queue the stream's overlap (and the whole
        # fused step) rides on.  Accelerator runtimes pipeline donated
        # buffers fine, so the chip path keeps donation (undonated
        # params + optimizer state would double peak HBM — DDL017's
        # whole point); on CPU the second buffer is the price of the
        # entire data plane hiding under the step.
        donate = all(
            getattr(d, "platform", "cpu") != "cpu"
            for d in self.mesh.devices.flat
        )

        def multi_for(n_steps: int):
            # Resolved PER WINDOW: with mixed batches_per_window across
            # producers, windows of different depths arrive as the
            # rotation advances, each needing its own scan length
            # (compiled once per distinct depth, cached — ``donate`` is
            # constant per trainer, so depth alone keys the cache).
            fn = self._multistep_cache.pop(n_steps, None)
            if fn is None:
                _, fn = make_multistep(
                    self._loss_fn, self._optimizer, self.mesh,
                    self._param_specs, batch_spec=self._batch_spec,
                    n_steps=n_steps, accum_steps=self._accum_steps,
                    donate=donate, **self._opt_kwargs,
                )
            # Re-insert at the MRU end (dict preserves insertion order);
            # trim the LRU end past the cap.
            self._multistep_cache[n_steps] = fn
            while len(self._multistep_cache) > self._multistep_cache_cap:
                self._multistep_cache.pop(
                    next(iter(self._multistep_cache))
                )
            return fn

        stream = loader.windows(lookahead=stream_lookahead)
        loop = self._fused_stream_loop if fused else self._sync_stream_loop
        state = loop(
            loader, stream, state, multi_for, col_splits, window_hook,
            hook_state, epoch_losses, start_epoch,
        )
        for i, mean in enumerate(epoch_losses):
            logger.info(
                "trainer: epoch %d/%d mean loss %.6f (windowed)",
                start_epoch + i + 1, n_epochs, mean,
            )
        return FitResult(
            state=state,
            losses=epoch_losses,
            epochs_run=(
                len(epoch_losses)
                if self._preempted
                else n_epochs - start_epoch
            ),
            resumed_from_epoch=start_epoch,
            metrics=self.metrics,
            preempted=self._preempted,
        )

    def _fused_stream_loop(
        self, loader, stream, state, multi_for, col_splits, window_hook,
        hook_state, epoch_losses, start_epoch,
    ):
        """The fused compute/ingest step (DDL020: no host syncs).

        Per window: acquire (the data plane already dispatched it under
        the previous scan), dispatch the scan, hand the scan's
        done-future to the loader (``gate_release_on`` — slot release
        waits for the CONSUMER, not the transfer), then read back the
        PREVIOUS window's losses.  That deferred read-back is the only
        host sync, it blocks on a scan that is already one window old
        (bounding in-flight depth at two — the landing-slot count), and
        the overlap it buys is measured: the acquire span of window k+1
        while scan k is still computing accumulates into
        ``trainer.ingest_overlap`` (a LOWER bound on hidden ingest:
        spans whose scan finished mid-acquire are not counted).
        """
        from ddl_tpu import Marker
        from ddl_tpu.obs import spans as obs_spans
        from ddl_tpu.profiling import annotate
        from ddl_tpu.utils import value_ready

        m = self.metrics
        pending = None
        epoch = start_epoch
        _done = object()
        while True:
            # Window-wait accounting: with healthy overlap the next
            # window is already in flight while the previous scan runs,
            # so this wait stays near zero; it flows into
            # north_star_report["window_wait_s"] and the bench JSON.
            # The annotation puts the same wait on the jax.profiler
            # timeline, named to line up with the SpanLog lanes.
            t0 = time.perf_counter()
            with m.timed("trainer.window_wait"), annotate("ddl.window_wait"):
                win = next(stream, _done)
            # Ready-by-default polarity: an unprobeable future must
            # never inflate the overlap measurement.
            if pending is not None and not value_ready(pending, True):
                # The previous scan computed through this whole acquire:
                # the data plane was hidden under the step.
                m.add_time(
                    "trainer.ingest_overlap", time.perf_counter() - t0
                )
            if win is _done:
                break
            if window_hook is not None:
                win = window_hook(win)
            _span_t0 = obs_spans.t0()
            _wkey = loader.last_window_key() or (None, None)
            state, losses = multi_for(win.shape[0])(
                state, _window_cols(win, col_splits), per_step=True
            )
            # The epoch-loss reduction is dispatched HERE, right behind
            # its own scan: backends that execute in dispatch order
            # (the CPU client) would otherwise queue a read-time
            # ``pending.mean()`` behind the NEXT scan, silently
            # re-serializing the loop the fused step exists to overlap.
            loss_mean = losses.mean()
            loader.gate_release_on(losses)
            # Consume span = the scan DISPATCH (DDL020: the fused loop
            # never waits on the device, so dispatch is all there is).
            obs_spans.record("trainer.consume", *_wkey, _span_t0)
            m.incr("trainer.fused_windows")
            if pending is not None:
                # Deferred ONE window: blocks on the PREVIOUS scan's
                # already-queued reduction, bounding in-flight depth at
                # the landing-slot count.
                epoch_losses.append(float(pending))
            pending = loss_mean
            epoch += 1
            loader.mark(Marker.END_OF_EPOCH)
            if (
                self.checkpoint_dir is not None
                and epoch % self.checkpoint_every_epochs == 0
            ):
                self._checkpoint(state, loader, shuffler=hook_state)
            if self._should_drain():
                # Graceful preemption drain at the window boundary: the
                # forced checkpoint inside syncs on the dispatched
                # scans (device_get at the step-future boundary), so
                # ZERO completed windows are lost.
                self._preempt_drain(state, loader, shuffler=hook_state)
                break
        if pending is not None:
            # Stream drained; the final scan must be consumed.
            epoch_losses.append(float(pending))
        return state

    def _sync_stream_loop(
        self, loader, stream, state, multi_for, col_splits, window_hook,
        hook_state, epoch_losses, start_epoch,
    ):
        """The synchronous (unfused) discipline — ``DDL_TPU_FUSED=0``.

        The window lands, THEN compute starts, THEN the losses are read
        back: measured step time is compute + ingest.  Kept as (a) the
        explicit escape hatch, (b) the fused A/B's baseline leg in the
        bench, and (c) the behavior every degradation rung falls back
        toward — bit-identical losses to the fused loop by
        construction (same windows, same compiled scans, different
        dispatch timing only).
        """
        import jax

        from ddl_tpu import Marker
        from ddl_tpu.obs import spans as obs_spans
        from ddl_tpu.profiling import annotate

        epoch = start_epoch
        _done = object()
        while True:
            with self.metrics.timed("trainer.window_wait"), annotate(
                "ddl.window_wait"
            ):
                win = next(stream, _done)
                if win is not _done:
                    # "The window lands...": expose the whole transfer.
                    jax.block_until_ready(win)
            if win is _done:
                break
            if window_hook is not None:
                win = window_hook(win)
            _span_t0 = obs_spans.t0()
            _wkey = loader.last_window_key() or (None, None)
            state, losses = multi_for(win.shape[0])(
                state, _window_cols(win, col_splits), per_step=True
            )
            # "...then compute runs to completion": immediate read-back
            # serializes the next acquire behind this scan.
            epoch_losses.append(float(losses.mean()))
            # Consume span covers dispatch + the blocking read-back —
            # the synchronous discipline's whole per-window compute.
            obs_spans.record("trainer.consume", *_wkey, _span_t0)
            epoch += 1
            loader.mark(Marker.END_OF_EPOCH)
            if (
                self.checkpoint_dir is not None
                and epoch % self.checkpoint_every_epochs == 0
            ):
                self._checkpoint(state, loader, shuffler=hook_state)
            if self._should_drain():
                self._preempt_drain(state, loader, shuffler=hook_state)
                break
        return state

    # -- the run -----------------------------------------------------------

    def fit(
        self,
        producer_function: ProducerFunctionSkeleton,
        batch_size: Optional[int] = None,
        n_epochs: Optional[int] = None,
        n_producers: Optional[int] = None,
        mode: Optional[str] = None,
        nslots: Optional[int] = None,
        output: Optional[str] = None,
        global_shuffle_fraction_exchange: Optional[float] = None,
        shuffler_factory: Any = None,
        loader_kwargs: Optional[dict] = None,
        prefetch_depth: Optional[int] = None,
        window_stream: Optional[bool] = None,
        window_hook: Any = None,
        stream_lookahead: int = 1,
        fused: Optional[bool] = None,
        config: Any = None,
    ) -> FitResult:
        """Run the full producer/consumer training job; returns FitResult.

        ``config`` (a :class:`ddl_tpu.config.LoaderConfig`) supplies
        defaults for the *run-level* knobs — batch_size, n_epochs,
        n_producers, mode, nslots, output,
        global_shuffle_fraction_exchange, exchange_method, ring_timeout_s
        — with explicit arguments winning.  Checkpointing and watchdog
        knobs are `Trainer` constructor arguments, not read from the
        config here.  With no config, ``batch_size`` and ``n_epochs`` are
        required.

        ``window_stream=True`` (``output="jax"`` only) drives the run off
        the zero-copy window stream: each epoch-window crosses into HBM as
        ONE transfer straight out of the ring slot
        (``DistributedDataLoader.windows``) and all its batches run as ONE
        jitted ``lax.scan`` of optimizer steps (``make_multistep``,
        ``per_step=True``) — one dispatch and one transfer per window
        instead of one of each per batch, with the next window streaming
        while the scan computes.  The optimizer-step sequence is exactly
        the per-batch path's, so results match batch-mode ``fit``.

        ``window_hook`` (window-stream mode only): a callable applied to
        each drained device window before its train scan — the trainer-
        side extension point for DEVICE-side transforms, e.g. a
        cross-instance ``DeviceGlobalShuffler`` exchange (which, unlike
        the producer-side host exchange, composes with elastic respawn:
        no producer carries exchange state).  Must be shape-preserving.

        ``stream_lookahead`` (window-stream mode only) deepens the window
        stream's in-flight pipeline (``DistributedDataLoader.windows``'s
        ``lookahead``); with the staged ingest engine early slot release
        lets the same ``nslots`` sustain the deeper pipeline.

        ``fused`` (window-stream mode only; default: the
        ``DDL_TPU_FUSED`` env gate, on) selects the fused
        compute/ingest step — the data plane dispatched under the train
        step, slot release gated on the consuming step's done-future —
        vs the synchronous discipline (window lands, then compute, then
        loss read-back).  Loss-identical either way; only dispatch
        timing differs (see ``_fit_windows``).

        Under PROCESS/MULTIHOST modes call this from under
        ``if __name__ == "__main__":`` (multiprocessing spawn re-imports
        the main module).  Global shuffle needs BOTH knobs: the exchange
        fraction and a ``shuffler_factory`` (e.g.
        ``ThreadExchangeShuffler.factory(...)``) — producers only build a
        shuffler when a factory is given.
        """
        from ddl_tpu import DistributedDataLoader, Marker, distributed_dataloader
        from ddl_tpu.watchdog import Watchdog

        if config is not None:
            batch_size = config.batch_size if batch_size is None else batch_size
            n_epochs = config.n_epochs if n_epochs is None else n_epochs
            n_producers = (
                config.n_producers if n_producers is None else n_producers
            )
            mode = config.mode if mode is None else mode
            nslots = config.nslots if nslots is None else nslots
            output = config.output if output is None else output
            if global_shuffle_fraction_exchange is None:
                global_shuffle_fraction_exchange = (
                    config.global_shuffle_fraction_exchange
                )
            if window_stream is None:
                window_stream = getattr(config, "window_stream", False)
            loader_kwargs = dict(loader_kwargs or {})
            loader_kwargs.setdefault(
                "exchange_method", config.exchange_method
            )
            loader_kwargs.setdefault("timeout_s", config.ring_timeout_s)
        if batch_size is None or n_epochs is None:
            raise ValueError(
                "batch_size and n_epochs are required (directly or via "
                "config=LoaderConfig(...))"
            )
        nslots = 2 if nslots is None else nslots
        output = "jax" if output is None else output
        if prefetch_depth is None:
            # config field → env mirror → default, via the envspec seam
            # (the tunable every ddl_tpu.tune knob change lands on).
            if config is not None and hasattr(config, "prefetch_depth"):
                prefetch_depth = config.prefetch_depth
            else:
                from ddl_tpu import envspec

                prefetch_depth = envspec.get("DDL_TPU_PREFETCH_DEPTH")
        window_stream = bool(window_stream)
        if window_stream and output != "jax":
            raise ValueError("window_stream requires output='jax'")
        if window_hook is not None and not window_stream:
            raise ValueError("window_hook requires window_stream=True")
        if fused is not None and not window_stream:
            raise ValueError("fused requires window_stream=True")
        # A stateful hook provider (DeviceGlobalShuffler or anything with
        # a .window_hook() factory) is passed WHOLE so the trainer can
        # checkpoint/restore its round state with the loader clock.  A
        # bare hook produced by .window_hook() carries its provider as
        # ``.owner`` — both forms checkpoint identically; only a hand-
        # written callable with no owner is the caller's responsibility
        # to resume.
        hook_state = None
        if window_hook is not None:
            if hasattr(window_hook, "window_hook"):
                hook_state = window_hook
                window_hook = hook_state.window_hook()
            else:
                hook_state = getattr(window_hook, "owner", None)
        global_shuffle_fraction_exchange = (
            global_shuffle_fraction_exchange or 0.0
        )
        if global_shuffle_fraction_exchange > 0 and shuffler_factory is None:
            raise ValueError(
                "global_shuffle_fraction_exchange > 0 requires a "
                "shuffler_factory (producers build no shuffler without one)"
            )
        trainer = self

        @distributed_dataloader(
            n_producers=n_producers, mode=mode, nslots=nslots,
            shuffler_factory=shuffler_factory,
        )
        def _main(env):
            trainer._preempted = False
            state, start_epoch = trainer._restore_or_init()
            lkw = dict(loader_kwargs or {})
            if output == "jax" and "sharding" not in lkw:
                # Batches land directly sharded over the mesh instead of
                # materialising whole on device 0 and resharding.  Window
                # layout is (batches_per_window, batch, ...), so stream
                # mode shards one axis deeper.
                from ddl_tpu.parallel.train import _named

                spec = (
                    P(*((None,) + tuple(trainer._batch_spec)))
                    if window_stream
                    else trainer._batch_spec
                )
                lkw["sharding"] = _named(trainer.mesh, spec)
            loader = DistributedDataLoader(
                producer_function,
                batch_size=batch_size,
                connection=env.connection,
                n_epochs=n_epochs,
                output=output,
                metrics=trainer.metrics,
                global_shuffle_fraction_exchange=(
                    global_shuffle_fraction_exchange
                ),
                **lkw,
            )
            if start_epoch >= n_epochs:
                # Nothing left to run (fit re-invoked with fewer epochs
                # than the checkpoint already completed).
                logger.info(
                    "trainer: checkpoint at epoch %d >= n_epochs %d — "
                    "nothing to do", start_epoch, n_epochs,
                )
                loader.shutdown()
                return FitResult(
                    state=state, losses=[], epochs_run=0,
                    resumed_from_epoch=start_epoch, metrics=trainer.metrics,
                )
            if start_epoch:
                from ddl_tpu.checkpoint import LoaderCheckpoint

                # The cursor FENCED to the restored train state (it
                # rode inside the verified generation blob) wins over
                # the loader.json mirror — a crash between the two
                # writes can never desync data from params.
                ck = trainer._restored_loader_ck
                if ck is None:
                    ck = LoaderCheckpoint.load(trainer._loader_ckpt_path())
                # Discard the windows the pre-checkpoint run consumed (one
                # per epoch): producers regenerate their sequence
                # deterministically, so resumed epochs see the DATA they
                # would have seen, not a replay of epoch 0.
                loader.fast_forward(ck.epoch)
                # shuffler=hook_state also restores a device shuffler's
                # round counter, so post-resume exchange permutations
                # continue the schedule instead of replaying round 0.
                ck.apply(loader, shuffler=hook_state)
            wd = None
            if trainer.watchdog_enabled and env.workers is not None:
                # respawn=True turns failure detection into elastic
                # recovery: dead producer workers are replaced in place
                # and the run continues (tests/test_elastic.py).
                wd = Watchdog(
                    env.workers,
                    stall_budget_s=trainer.stall_budget_s,
                    respawn=trainer.watchdog_respawn,
                    # The trainer's registry, not the process default:
                    # respawns/failures must show in THIS run's
                    # north_star_report robustness block.
                    metrics=trainer.metrics,
                ).start()
            epoch_losses: List[float] = []
            if window_stream:
                try:
                    return trainer._fit_windows(
                        loader, state, start_epoch, n_epochs, epoch_losses,
                        window_hook=window_hook, hook_state=hook_state,
                        stream_lookahead=stream_lookahead, fused=fused,
                    )
                finally:
                    trainer._finish_checkpoints()
                    if wd is not None:
                        wd.stop()
            try:
                for epoch in range(start_epoch, n_epochs):
                    batch_losses: List[Any] = []
                    # Device output iterates with lookahead: batch k+1 is
                    # crossing into HBM while step k computes (VERDICT r2
                    # item 5 — PrefetchIterator was previously unwired).
                    epoch_iter = (
                        loader.prefetch(prefetch_depth)
                        if output == "jax" and prefetch_depth > 1
                        else loader
                    )
                    for batch in epoch_iter:
                        state_new, loss = trainer._step_fn(state, batch)
                        state = state_new
                        # Keep losses as device arrays: a float() here
                        # would block on the step and serialize loading
                        # against compute, defeating the ring overlap.
                        batch_losses.append(loss)
                        loader.mark(Marker.END_OF_BATCH)
                    loader.mark(Marker.END_OF_EPOCH)
                    vals = [float(x) for x in batch_losses]
                    mean = sum(vals) / len(vals) if vals else float("nan")
                    epoch_losses.append(mean)
                    logger.info(
                        "trainer: epoch %d/%d mean loss %.6f (%d batches)",
                        epoch + 1, n_epochs, mean, len(batch_losses),
                    )
                    if (
                        trainer.checkpoint_dir is not None
                        and (epoch + 1) % trainer.checkpoint_every_epochs == 0
                    ):
                        trainer._checkpoint(state, loader)
                    if trainer._should_drain():
                        # Batch-path drain at the epoch boundary (the
                        # stream path drains per window == per epoch).
                        trainer._preempt_drain(state, loader)
                        break
            finally:
                trainer._finish_checkpoints()
                if wd is not None:
                    wd.stop()
            return FitResult(
                state=state,
                losses=epoch_losses,
                epochs_run=(
                    len(epoch_losses)
                    if trainer._preempted
                    else n_epochs - start_epoch
                ),
                resumed_from_epoch=start_epoch,
                metrics=trainer.metrics,
                preempted=trainer._preempted,
            )

        return _main()
